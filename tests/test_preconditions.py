"""Protocol-19 V2 preconditions: time/ledger bounds, minSeqNum,
minSeqAge / minSeqLedgerGap, extraSigners.

Reference behaviors: TransactionFrame isTooEarly/isTooLate (time AND
ledger bounds inside one cond), isBadSeq's relaxed minSeqNum window,
isTooEarlyForAccount (seqAge/seqLedgerGap vs the account's SeqNum
extension), and the extraSigners checks — duplicate pair and empty
signed-payload are txMALFORMED, unmet extra signer is txBAD_AUTH even
when account thresholds pass.
"""

import pytest

from stellar_core_tpu.xdr.results import TransactionResultCode
from stellar_core_tpu.xdr.transaction import (LedgerBounds, Preconditions,
                                              PreconditionType,
                                              PreconditionsV2, TimeBounds)
from stellar_core_tpu.xdr.types import (Ed25519SignedPayload, SignerKey,
                                        SignerKeyType)

from txtest_utils import (TestAccount, TestLedger, op_payment,
                          signed_payload_hint)

XLM = 10_000_000


@pytest.fixture
def ledger():
    return TestLedger()


@pytest.fixture
def root(ledger):
    return ledger.root_account


def tx_code(frame):
    return frame.result.result.disc


def v2(**kw):
    kw.setdefault("timeBounds", None)
    kw.setdefault("ledgerBounds", None)
    kw.setdefault("minSeqNum", None)
    kw.setdefault("minSeqAge", 0)
    kw.setdefault("minSeqLedgerGap", 0)
    kw.setdefault("extraSigners", [])
    return Preconditions(PreconditionType.PRECOND_V2, PreconditionsV2(**kw))


def _mk(ledger, root):
    a = TestAccount.fresh(ledger)
    b = TestAccount.fresh(ledger)
    assert root.create(a, 100 * XLM)
    assert root.create(b, 100 * XLM)
    a.sync_seq()
    return a, b


class TestBounds:
    def test_ledger_bounds_window(self, ledger, root):
        a, b = _mk(ledger, root)
        seq = ledger.header().ledgerSeq
        # open window: applies
        frame = a.tx([op_payment(b.muxed, XLM)],
                     cond=v2(ledgerBounds=LedgerBounds(
                         minLedger=0, maxLedger=seq + 10)))
        assert ledger.apply_tx(frame), frame.result
        a.sync_seq()
        # check_valid-only frames below share one explicit next seq
        # (TestAccount.tx consumes its local counter per call)
        nxt = a.seq + 1
        # minLedger in the future: too early
        frame = a.tx([op_payment(b.muxed, XLM)], seq=nxt,
                     cond=v2(ledgerBounds=LedgerBounds(
                         minLedger=seq + 5, maxLedger=0)))
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txTOO_EARLY
        # maxLedger == current is EXCLUSIVE (reference: <=): too late
        frame = a.tx([op_payment(b.muxed, XLM)], seq=nxt,
                     cond=v2(ledgerBounds=LedgerBounds(
                         minLedger=0, maxLedger=seq)))
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txTOO_LATE
        # maxLedger 0 = unbounded
        frame = a.tx([op_payment(b.muxed, XLM)], seq=nxt,
                     cond=v2(ledgerBounds=LedgerBounds(
                         minLedger=0, maxLedger=0)))
        assert ledger.check_valid(frame)

    def test_time_bounds_inside_v2(self, ledger, root):
        a, b = _mk(ledger, root)
        now = ledger.header().scpValue.closeTime
        frame = a.tx([op_payment(b.muxed, XLM)],
                     cond=v2(timeBounds=TimeBounds(minTime=now + 100,
                                                   maxTime=0)))
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txTOO_EARLY
        frame = a.tx([op_payment(b.muxed, XLM)],
                     cond=v2(timeBounds=TimeBounds(minTime=0,
                                                   maxTime=now - 1)))
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txTOO_LATE


class TestMinSeqNum:
    def test_seq_jump_allowed_with_min_seq_num(self, ledger, root):
        """With minSeqNum, any tx seq > current is valid as long as
        current >= minSeqNum (the protocol-19 relaxed rule)."""
        a, b = _mk(ledger, root)
        cur = a.seq
        frame = a.tx([op_payment(b.muxed, XLM)], seq=cur + 1000,
                     cond=v2(minSeqNum=0))
        assert ledger.apply_tx(frame), frame.result
        # and the account seq lands at the tx's seq
        acct = ledger.account(a.account_id)
        assert acct.seqNum == cur + 1000

    def test_min_seq_num_not_met(self, ledger, root):
        a, b = _mk(ledger, root)
        cur = a.seq
        frame = a.tx([op_payment(b.muxed, XLM)], seq=cur + 2,
                     cond=v2(minSeqNum=cur + 1))    # current < minSeqNum
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txBAD_SEQ

    def test_seq_must_still_exceed_current(self, ledger, root):
        a, b = _mk(ledger, root)
        cur = a.seq
        frame = a.tx([op_payment(b.muxed, XLM)], seq=cur,
                     cond=v2(minSeqNum=0))          # current >= tx seq
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txBAD_SEQ


class TestSeqAgeGap:
    def test_min_seq_ledger_gap(self, ledger, root):
        """The source's last seq bump must be >= gap ledgers old;
        a fresh account bumped this ledger fails, then passes after
        advancing the ledger."""
        a, b = _mk(ledger, root)
        # bump the account's seq NOW so seqLedger = current ledger
        assert a.pay(b, XLM)
        a.sync_seq()
        frame = a.tx([op_payment(b.muxed, XLM)],
                     cond=v2(minSeqLedgerGap=3))
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == \
            TransactionResultCode.txBAD_MIN_SEQ_AGE_OR_GAP
        ledger.advance_ledger(3)
        frame2 = a.tx([op_payment(b.muxed, XLM)], seq=frame.seq_num,
                      cond=v2(minSeqLedgerGap=3))
        assert ledger.check_valid(frame2), frame2.result

    def test_min_seq_age(self, ledger, root):
        a, b = _mk(ledger, root)
        assert a.pay(b, XLM)
        a.sync_seq()
        frame = a.tx([op_payment(b.muxed, XLM)],
                     cond=v2(minSeqAge=10_000))
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == \
            TransactionResultCode.txBAD_MIN_SEQ_AGE_OR_GAP


class TestExtraSigners:
    def test_extra_signer_required_and_satisfied(self, ledger, root):
        a, b = _mk(ledger, root)
        c = TestAccount.fresh(ledger)
        sk = SignerKey(SignerKeyType.SIGNER_KEY_TYPE_ED25519,
                       c.key.public_key().raw)
        # account thresholds pass with the master sig alone, but the
        # extra signer is still demanded
        nxt = a.seq + 1
        frame = a.tx([op_payment(b.muxed, XLM)], seq=nxt,
                     cond=v2(extraSigners=[sk]))
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txBAD_AUTH
        frame = a.tx([op_payment(b.muxed, XLM)], seq=nxt,
                     cond=v2(extraSigners=[sk]),
                     extra_signers=[c.key])
        assert ledger.apply_tx(frame), frame.result

    def test_duplicate_extra_signers_malformed(self, ledger, root):
        a, b = _mk(ledger, root)
        c = TestAccount.fresh(ledger)
        sk = SignerKey(SignerKeyType.SIGNER_KEY_TYPE_ED25519,
                       c.key.public_key().raw)
        frame = a.tx([op_payment(b.muxed, XLM)],
                     cond=v2(extraSigners=[sk, sk]),
                     extra_signers=[c.key])
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txMALFORMED

    def test_empty_payload_extra_signer_malformed(self, ledger, root):
        a, b = _mk(ledger, root)
        c = TestAccount.fresh(ledger)
        sp = SignerKey(
            SignerKeyType.SIGNER_KEY_TYPE_ED25519_SIGNED_PAYLOAD,
            Ed25519SignedPayload(ed25519=c.key.public_key().raw,
                                 payload=b""))
        frame = a.tx([op_payment(b.muxed, XLM)],
                     cond=v2(extraSigners=[sp]))
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txMALFORMED

    def test_signed_payload_extra_signer(self, ledger, root):
        """A signed-payload EXTRA signer: the signature over the payload
        satisfies the precondition without being an account signer."""
        from stellar_core_tpu.xdr.transaction import DecoratedSignature
        a, b = _mk(ledger, root)
        c = TestAccount.fresh(ledger)
        payload = b"precondition payload"
        sp = SignerKey(
            SignerKeyType.SIGNER_KEY_TYPE_ED25519_SIGNED_PAYLOAD,
            Ed25519SignedPayload(ed25519=c.key.public_key().raw,
                                 payload=payload))
        frame = a.tx([op_payment(b.muxed, XLM)], cond=v2(extraSigners=[sp]))
        hint = signed_payload_hint(c.key.public_key().raw, payload)
        frame.signatures.append(DecoratedSignature(
            hint=hint, signature=c.key.sign(payload)))
        frame.envelope.value.signatures = frame.signatures
        assert ledger.apply_tx(frame), frame.result
