"""The ledger-close completion pipeline (deferred post-commit I/O).

Covers the perf_opt tentpole: the consensus-critical close segment
returns before tx-history/meta/publish run; a per-ledger barrier makes
readers (next close, DB snapshot readers, shutdown) join first; a crash
between the seal commit and the completion flush recovers from the last
durable header; and the deferred schedule is byte-identical to the
synchronous one (header hashes + tx meta).

Plus the satellites that ride the same paths: HAS snapshot at queue
time, GC protection for publish-queue/catchup buckets, the passive
index sidecar, and the DNS cache TTL.
"""

import json
import os
import time

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.db.database import Database
from stellar_core_tpu.herder import make_tx_set_from_transactions
from stellar_core_tpu.ledger.completion import CloseCompletionQueue
from stellar_core_tpu.ledger.ledger_manager import (LedgerCloseData,
                                                    LedgerManager)
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.xdr.ledger import StellarValue

import test_ledger_close as lc
import test_standalone_app as m1
from txtest_utils import op_create_account, op_payment


# ----------------------------------------------------- completion queue --

def test_completion_queue_runs_in_order_and_joins():
    q = CloseCompletionQueue()
    done = []

    def job(n):
        def run():
            time.sleep(0.01)
            done.append(n)
        return run

    for n in (2, 3, 4):
        q.submit(n, job(n))
    q.join()
    assert done == [2, 3, 4]
    assert q.pending() == 0
    assert q.last_completed() == 4


def test_completion_queue_error_surfaces_on_join():
    q = CloseCompletionQueue()

    def boom():
        raise OSError("disk gone")

    q.submit(7, boom)
    with pytest.raises(RuntimeError, match="ledger 7"):
        q.join()
    # the error is STICKY: a reader thread swallowing the first raise
    # cannot hide the failure from the consensus path
    done = []
    q.submit(8, lambda: done.append(8))
    with pytest.raises(RuntimeError, match="ledger 7"):
        q.join()
    assert done == [8]          # later jobs still ran
    q.join(reraise=False)       # shutdown drain ignores it


def test_completion_queue_join_from_worker_is_noop():
    q = CloseCompletionQueue()
    saw = {}

    def introspect():
        # a completion job reading its own artifacts must not deadlock
        q.join()
        saw["ok"] = True

    q.submit(1, introspect)
    q.join()
    assert saw.get("ok")


# ------------------------------------------------------ barrier ordering --

def _close_payment_ledger(lm, db=None):
    """One close via the deferred pipeline (no manual-close join)."""
    mk = lc.master_key()
    seq = lc.master_seq(lm)
    dest = SecretKey.pseudo_random_for_testing(lm.get_last_closed_ledger_num())
    tx = lc.make_tx(lm, mk, seq + 1,
                    [op_create_account(lc.xpk(dest), 10 ** 9)])
    lcl = lm.get_last_closed_ledger_header()
    frame, applicable, _ = make_tx_set_from_transactions(
        [tx], lcl, lc.NETWORK_ID)
    value = StellarValue(txSetHash=frame.get_contents_hash(),
                         closeTime=1000 + lcl.ledgerSeq)
    lm.close_ledger(LedgerCloseData(lcl.ledgerSeq + 1, frame, value))


def test_reader_barrier_orders_tx_history_reads():
    """A direct DB read of txhistory right after close_ledger returns
    must observe the completed rows, even though they are written on the
    background worker — the Database-level barrier joins first."""
    db = Database(":memory:")
    db.initialize()
    lm = lc.make_manager(db=db)
    assert lm.defer_completion

    # make the completion tail visibly slow so an unbarriered read
    # would deterministically miss the rows
    orig = lm._store_tx_history

    def slow_store(*a, **kw):
        time.sleep(0.15)
        orig(*a, **kw)

    lm._store_tx_history = slow_store
    _close_payment_ledger(lm)
    # close_ledger returned while completion sleeps; the read barriers
    row = db.query_one("SELECT txbody FROM txhistory WHERE ledgerseq=2")
    assert row is not None
    assert lm._completion.pending() == 0


def test_next_close_joins_previous_completion():
    db = Database(":memory:")
    db.initialize()
    lm = lc.make_manager(db=db)
    order = []
    orig = lm._store_tx_history

    def slow_store(seq, *a, **kw):
        time.sleep(0.1)
        order.append(("complete", seq))
        orig(seq, *a, **kw)

    lm._store_tx_history = slow_store
    _close_payment_ledger(lm)
    order.append(("close-returned", 2))
    _close_payment_ledger(lm)   # must join ledger 2's completion first
    order.append(("close-returned", 3))
    lm.join_completion()
    assert order.index(("close-returned", 2)) < \
        order.index(("complete", 2)) < order.index(("close-returned", 3)) \
        and order[-1] != ("complete", 2)
    assert order.index(("complete", 2)) < order.index(("complete", 3))


def test_deferred_path_byte_identical_to_synchronous():
    """Golden regression: header hashes AND emitted meta are
    byte-identical between the deferred and inline completion
    schedules."""
    def run(defer):
        metas = []
        db = Database(":memory:")
        db.initialize()
        lm = lc.make_manager(db=db)
        lm.defer_completion = defer
        lm.meta_stream = metas.append
        for _ in range(3):
            _close_payment_ledger(lm)
        lm.join_completion()
        rows = db.query_all(
            "SELECT ledgerseq, txindex, txbody, txresult, txmeta "
            "FROM txhistory ORDER BY ledgerseq, txindex")
        return (lm.get_last_closed_ledger_hash(),
                [m.to_bytes() for m in metas],
                [tuple(bytes(c) if isinstance(c, (bytes, memoryview))
                       else c for c in r) for r in rows])

    deferred = run(True)
    inline = run(False)
    assert deferred[0] == inline[0]
    assert deferred[1] == inline[1]
    assert deferred[2] == inline[2]


# -------------------------------------------------- crash mid-completion --

def _file_cfg(tmp_path):
    cfg = get_test_config()
    cfg.DATABASE = f"sqlite3://{tmp_path}/node.db"
    cfg.BUCKET_DIR_PATH = str(tmp_path / "buckets")
    return cfg


def test_crash_mid_completion_restart(tmp_path):
    """Kill after seal, before tx-history/meta flush: the node restarts
    from the last durable header (seal committed entries + header + HAS
    atomically) and keeps closing ledgers cleanly."""
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             _file_cfg(tmp_path))
    app.start()
    master = m1.master_account(app)
    dest = m1.AppAccount(app, SecretKey.from_seed(b"\x09" * 32))
    m1.submit(app, master.tx([op_create_account(dest.account_id, 10**10)]))
    app.manual_close()
    lcl_before = app.ledger_manager.get_last_closed_ledger_num()

    # simulate the crash: the completion job for the next close is lost
    # (worker killed after the seal transaction committed)
    app.ledger_manager._completion.submit = lambda seq, fn: None
    m1.submit(app, master.tx([op_payment(dest.muxed, 777)]))
    app.manual_close()
    crashed_seq = app.ledger_manager.get_last_closed_ledger_num()
    assert crashed_seq == lcl_before + 1
    expected_hash = app.ledger_manager.get_last_closed_ledger_hash()
    # the seal segment was durable...
    assert app.database.query_one(
        "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=?",
        (crashed_seq,)) is not None
    # ...but the completion tail never flushed
    assert app.database.query_one(
        "SELECT txbody FROM txhistory WHERE ledgerseq=?",
        (crashed_seq,)) is None
    # abandon the app without shutdown (no drain, no clean close)

    app2 = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                              _file_cfg(tmp_path))
    app2.start()
    try:
        lm2 = app2.ledger_manager
        # recovered from the last durable header, hashes intact
        assert lm2.get_last_closed_ledger_num() == crashed_seq
        assert lm2.get_last_closed_ledger_hash() == expected_hash
        # the gap was recorded + healed: the marker now matches the LCL
        from stellar_core_tpu.main.persistent_state import StateEntry
        assert int(app2.persistent_state.get(
            StateEntry.LAST_CLOSE_COMPLETED)) == crashed_seq
        # and the node replays forward cleanly, with complete artifacts
        master2 = m1.master_account(app2)
        dest2 = m1.AppAccount(app2, SecretKey.from_seed(b"\x09" * 32))
        dest2.sync_seq()
        m1.submit(app2, master2.tx([op_payment(dest2.muxed, 555)]))
        app2.manual_close()
        new_seq = lm2.get_last_closed_ledger_num()
        assert new_seq == crashed_seq + 1
        assert app2.database.query_one(
            "SELECT txbody FROM txhistory WHERE ledgerseq=?",
            (new_seq,)) is not None
    finally:
        app2.shutdown()


# ------------------------------------------------ HAS snapshot at queue --

def _archive_cfg(tmp_path, delay=0.0):
    archive_root = str(tmp_path / "archive")
    cfg = get_test_config()
    cfg.PUBLISH_TO_ARCHIVE_DELAY = delay
    cfg.HISTORY = {"test": {
        "get": f"cp {archive_root}/{{0}} {{1}}",
        "put": f"mkdir -p $(dirname {archive_root}/{{1}}) && "
               f"cp {{0}} {archive_root}/{{1}}",
    }}
    return cfg, archive_root


def test_publish_records_queue_time_has(tmp_path):
    """With PUBLISH_TO_ARCHIVE_DELAY, ledgers keep closing between
    queue and publish; the published stellar-history.json must record
    checkpoint 63's OWN bucket levels, not a later ledger's."""
    cfg, root = _archive_cfg(tmp_path, delay=30.0)
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        master = m1.master_account(app)
        while app.ledger_manager.get_last_closed_ledger_num() < 63:
            # churn state every close so the live bucket list keeps
            # changing during the publish delay
            m1.submit(app, master.tx([op_payment(master.muxed, 1)]))
            app.manual_close()
        queued = app.history_manager._publish_queue
        assert len(queued) == 1 and queued[0].seq == 63
        snapshot_json = queued[0].has.to_json()
        # keep closing during the delay — the live list moves on
        for _ in range(8):
            m1.submit(app, master.tx([op_payment(master.muxed, 1)]))
            app.manual_close()
        from stellar_core_tpu.history.archive import HistoryArchiveState
        live_now = HistoryArchiveState.from_bucket_list(
            app.ledger_manager.get_last_closed_ledger_num(),
            app.bucket_manager.bucket_list,
            app.config.NETWORK_PASSPHRASE)
        assert json.loads(live_now.to_json())["currentBuckets"] != \
            json.loads(snapshot_json)["currentBuckets"]
        app.clock.crank_for(35.0)
        assert app.history_manager.published_count == 1
        with open(os.path.join(
                root, ".well-known/stellar-history.json")) as f:
            published = json.load(f)
        assert published == json.loads(snapshot_json)
        assert published["currentLedger"] == 63


def test_gc_keeps_buckets_of_queued_checkpoint(tmp_path):
    """forget_unreferenced_buckets must not unlink bucket files a
    queued-but-unpublished checkpoint still references."""
    cfg, root = _archive_cfg(tmp_path, delay=30.0)
    cfg.BUCKET_DIR_PATH = str(tmp_path / "buckets")
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        master = m1.master_account(app)
        while app.ledger_manager.get_last_closed_ledger_num() < 63:
            m1.submit(app, master.tx([op_payment(master.muxed, 1)]))
            app.manual_close()
        queued_hashes = app.history_manager.queued_bucket_hashes()
        assert queued_hashes
        for _ in range(8):
            m1.submit(app, master.tx([op_payment(master.muxed, 1)]))
            app.manual_close()
        app.bucket_manager.forget_unreferenced_buckets()
        for h in queued_hashes:
            assert os.path.exists(os.path.join(
                str(tmp_path / "buckets"), f"bucket-{h.hex()}.xdr")), \
                "GC dropped a bucket the publish queue references"
        # the delayed publish then succeeds from the retained files
        app.clock.crank_for(35.0)
        assert app.history_manager.published_count == 1


def test_gc_keeps_pinned_hot_buckets(tmp_path):
    """Hot-archive files adopted by an in-flight catchup are pinned
    until the catchup installs (or abandons) its levels."""
    from stellar_core_tpu.bucket.manager import BucketManager
    bm = BucketManager(str(tmp_path / "b"))
    raw = b"\x00" * 64
    bm.adopt_hot_bucket_raw(raw)
    import hashlib
    path = os.path.join(str(tmp_path / "b"),
                        f"hot-{hashlib.sha256(raw).hexdigest()}.xdr")
    assert os.path.exists(path)
    bm.forget_unreferenced_buckets()
    assert os.path.exists(path), "GC dropped an in-flight catchup bucket"
    bm.clear_hot_pins()
    bm.forget_unreferenced_buckets()
    assert not os.path.exists(path)
    bm.shutdown()


# ------------------------------------------------- passive index sidecar --

def test_index_sidecar_passive_roundtrip(tmp_path):
    from stellar_core_tpu.bucket import bucket_index
    from stellar_core_tpu.bucket.bucket import Bucket
    from stellar_core_tpu.tx.tx_utils import make_account_ledger_entry
    from stellar_core_tpu.xdr.ledger_entries import ledger_entry_key
    from stellar_core_tpu.xdr.types import PublicKey

    entries = []
    for i in range(20):
        le = make_account_ledger_entry(
            PublicKey.ed25519(bytes([i]) * 32), 10**7, seq_num=1)
        entries.append(le)
    b = Bucket.fresh(11, entries, [], [])
    path = str(tmp_path / "bucket-test.xdr")
    b.write_to(path)

    bucket_index.set_persist_index(True)
    try:
        b1 = Bucket.from_file(path)
        k0 = ledger_entry_key(entries[0])
        assert b1.get(k0) is not None
        sidecar = path + ".idx"
        assert os.path.exists(sidecar)
        with open(sidecar, "rb") as f:
            raw = f.read()
        # passive struct format, not a pickle
        assert raw.startswith(bucket_index.SIDECAR_MAGIC)
        assert not raw.startswith(b"\x80")      # pickle protocol marker

        # reload goes through the sidecar and serves identical lookups
        b2 = Bucket.from_file(path)
        idx = b2._build_index()
        for le in entries:
            assert idx.lookup(b2.raw_bytes(),
                              ledger_entry_key(le)) is not None
        assert b2.get(k0).value.to_bytes() == b1.get(k0).value.to_bytes()

        # damaged sidecars are rebuilt, not trusted and not fatal
        with open(sidecar, "wb") as f:
            f.write(b"\x80\x04garbage-that-is-not-an-index")
        b3 = Bucket.from_file(path)
        assert b3.get(k0) is not None
        with open(sidecar, "rb") as f:
            assert f.read().startswith(bucket_index.SIDECAR_MAGIC)

        # stale-tuning sidecars are ignored (None), then rewritten
        bucket_index.configure_index(cutoff_mb=1, page_size_exponent=10)
        b4 = Bucket.from_file(path)
        assert b4.get(k0) is not None
    finally:
        bucket_index.set_persist_index(False)
        bucket_index.configure_index(cutoff_mb=20, page_size_exponent=14)


def test_bucket_module_has_no_pickle():
    import inspect

    from stellar_core_tpu.bucket import bucket
    src = inspect.getsource(bucket)
    assert "pickle" not in src


# ------------------------------------------------------- DNS cache TTL --

def test_dns_cache_ttl_and_no_failure_caching(monkeypatch):
    from stellar_core_tpu.overlay.manager import OverlayManager

    om = object.__new__(OverlayManager)
    om._dns_cache = {}
    calls = {"n": 0}
    results = {"peer.example": OSError("no resolver")}

    import socket

    def fake_resolve(host):
        calls["n"] += 1
        r = results[host]
        if isinstance(r, Exception):
            raise r
        return r

    monkeypatch.setattr(socket, "gethostbyname", fake_resolve)
    # failures are NOT cached: each call retries
    assert om._resolve_host("peer.example") is None
    assert om._resolve_host("peer.example") is None
    assert calls["n"] == 2
    # success IS cached...
    results["peer.example"] = "10.0.0.7"
    assert om._resolve_host("peer.example") == "10.0.0.7"
    assert om._resolve_host("peer.example") == "10.0.0.7"
    assert calls["n"] == 3
    # ...until the TTL expires, after which a record change is seen
    host_ip, expiry = om._dns_cache["peer.example"]
    om._dns_cache["peer.example"] = (host_ip, time.monotonic() - 1)
    results["peer.example"] = "10.0.0.8"
    assert om._resolve_host("peer.example") == "10.0.0.8"
    assert calls["n"] == 4
    # localhost still short-circuits without a resolver
    assert om._resolve_host("localhost") == "127.0.0.1"
    assert calls["n"] == 4


# ----------------------------------------------------- phase instrumentation --

def test_close_emits_phase_zones():
    db = Database(":memory:")
    db.initialize()
    lm = lc.make_manager(db=db)
    _close_payment_ledger(lm)
    lm.join_completion()
    report = lm.perf.report()
    for zone in ("ledger.closeLedger", "ledger.close.completeWait",
                 "ledger.close.prepare", "ledger.close.fees",
                 "ledger.close.applyTx", "ledger.close.seal",
                 "ledger.close.complete", "ledger.close.txHistory",
                 "ledger.close.meta"):
        assert zone in report, f"missing phase zone {zone}"


def test_slow_log_names_guilty_phase():
    from stellar_core_tpu.ledger.ledger_manager import _phase_summary
    s = _phase_summary({"ledger.close.applyTx": 2.1,
                        "ledger.close.seal": 0.3})
    assert s.startswith("applyTx=2100ms")
    assert "seal=300ms" in s
