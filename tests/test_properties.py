"""Property-based tests (reference analogue: lib/autocheck usage, e.g.
in bucket tests). Hypothesis drives randomized structural invariants the
example-based suites can't sweep."""

import io
import random

import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package")
from hypothesis import given, settings, strategies as st  # noqa: E402

from stellar_core_tpu.crypto.strkey import StrKey
from stellar_core_tpu.main.fuzzer import XdrGenerator


# ----------------------------------------------------------------- strkey --

@given(st.binary(min_size=32, max_size=32))
def test_strkey_public_roundtrip(raw):
    s = StrKey.encode_ed25519_public(raw)
    assert StrKey.decode_ed25519_public(s) == raw


@given(st.binary(min_size=32, max_size=32), st.integers(0, 55),
       st.integers(1, 25))
def test_strkey_rejects_single_char_corruption(raw, pos, delta):
    """Any single-character substitution is caught by the CRC16 (or the
    version byte / alphabet check)."""
    import pytest
    s = StrKey.encode_ed25519_public(raw)
    alphabet = "ABCDEFGHIJKLMNOPQRSTUVWXYZ234567"
    pos = pos % len(s)
    orig = s[pos]
    repl = alphabet[(alphabet.index(orig) + delta) % 32] \
        if orig in alphabet else "A"
    if repl == orig:
        repl = alphabet[(alphabet.index(orig) + 1) % 32]
    corrupted = s[:pos] + repl + s[pos + 1:]
    with pytest.raises(Exception):
        StrKey.decode_ed25519_public(corrupted)


# ------------------------------------------------------------------- xdr --

@given(st.integers(0, 2**32 - 1))
@settings(max_examples=30, deadline=None)
def test_random_tx_envelope_roundtrips(seed):
    """Arbitrary generated envelopes survive pack -> unpack -> pack
    byte-identically (canonical XDR)."""
    from stellar_core_tpu.xdr.transaction import TransactionEnvelope
    gen = XdrGenerator(random.Random(seed))
    env = gen.gen(TransactionEnvelope)
    raw = env.to_bytes()
    again = TransactionEnvelope.from_bytes(raw)
    assert again.to_bytes() == raw


@given(st.integers(0, 2**32 - 1))
@settings(max_examples=30, deadline=None)
def test_random_ledger_entry_roundtrips(seed):
    from stellar_core_tpu.xdr.ledger_entries import LedgerEntry
    gen = XdrGenerator(random.Random(seed))
    le = gen.gen(LedgerEntry)
    raw = le.to_bytes()
    assert LedgerEntry.from_bytes(raw).to_bytes() == raw


# ---------------------------------------------------------------- bucket --

def _bucket_entry(n, balance):
    from stellar_core_tpu.xdr.ledger import BucketEntry, BucketEntryType
    from stellar_core_tpu.xdr.ledger_entries import (
        AccountEntry, LedgerEntry, LedgerEntryType, _LedgerEntryData)
    from stellar_core_tpu.xdr.types import PublicKey, PublicKeyType
    ae = AccountEntry(
        accountID=PublicKey(PublicKeyType.PUBLIC_KEY_TYPE_ED25519,
                            n.to_bytes(4, "big") * 8),
        balance=balance, thresholds=b"\x01\x00\x00\x00")
    le = LedgerEntry(lastModifiedLedgerSeq=1,
                     data=_LedgerEntryData(LedgerEntryType.ACCOUNT, ae))
    return BucketEntry(BucketEntryType.LIVEENTRY, le)


@given(st.lists(st.integers(0, 50), max_size=30),
       st.lists(st.integers(0, 50), max_size=30))
@settings(max_examples=25, deadline=None)
def test_bucket_merge_is_sorted_newest_wins(old_ids, new_ids):
    """Merge output stays sorted and deduplicated, and for keys present
    on both sides the NEW side's entry wins (merge lifecycle,
    Bucket.cpp:252-453)."""
    from stellar_core_tpu.bucket.bucket import (Bucket, _entry_sort_key,
                                                merge_buckets)
    old = Bucket.from_entries(
        [_bucket_entry(n, 1000 + n) for n in sorted(set(old_ids))])
    new = Bucket.from_entries(
        [_bucket_entry(n, 2000 + n) for n in sorted(set(new_ids))])
    merged = merge_buckets(old, new)
    keys = [_entry_sort_key(be) for be in merged.entries()]
    assert keys == sorted(keys)
    assert len(keys) == len(set(keys))
    assert len(keys) == len(set(old_ids) | set(new_ids))
    by_id = {be.value.data.value.accountID.value: be.value.data.value
             for be in merged.entries()}
    for n in set(new_ids):
        assert by_id[n.to_bytes(4, "big") * 8].balance == 2000 + n
    for n in set(old_ids) - set(new_ids):
        assert by_id[n.to_bytes(4, "big") * 8].balance == 1000 + n


@given(st.lists(st.integers(0, 60), min_size=1, max_size=40))
@settings(max_examples=25, deadline=None)
def test_bucket_index_equivalent_to_scan(ids):
    """Index lookups agree with a linear scan for hits and misses."""
    from stellar_core_tpu.bucket.bucket import Bucket
    from stellar_core_tpu.xdr.ledger_entries import (LedgerKey,
                                                     ledger_entry_key)
    b = Bucket.from_entries(
        [_bucket_entry(n, n) for n in sorted(set(ids))])
    scan = {ledger_entry_key(be.value).to_bytes(): be
            for be in b.entries()}
    for n in range(0, 61):
        from stellar_core_tpu.xdr.types import PublicKey, PublicKeyType
        key = LedgerKey.account(PublicKey(
            PublicKeyType.PUBLIC_KEY_TYPE_ED25519, n.to_bytes(4, "big") * 8))
        got = b.get(key)
        want = scan.get(key.to_bytes())
        assert (got is None) == (want is None)
        if got is not None:
            assert got.value.to_bytes() == want.value.to_bytes()


# ------------------------------------------------------------------- scp --

@given(st.integers(0, 2**32 - 1), st.integers(1, 6))
@settings(max_examples=40, deadline=None)
def test_qset_normalize_idempotent(seed, width):
    """normalize_qset is idempotent and preserves sanity."""
    import hashlib
    from stellar_core_tpu.scp.quorum_set_utils import (is_quorum_set_sane,
                                                       normalize_qset)
    from stellar_core_tpu.xdr.scp import SCPQuorumSet
    from stellar_core_tpu.xdr.types import PublicKey
    rng = random.Random(seed)

    def mk(depth):
        vals = [PublicKey.ed25519(hashlib.sha256(
            b"%d-%d" % (seed, rng.randrange(10))).digest())
            for _ in range(rng.randrange(0, width + 1))]
        inner = []
        if depth < 2:
            inner = [mk(depth + 1) for _ in range(rng.randrange(0, 3))]
        total = len(vals) + len(inner)
        return SCPQuorumSet(threshold=max(1, rng.randint(0, total)),
                            validators=vals, innerSets=inner)

    q = mk(0)
    sane_before, _ = is_quorum_set_sane(q, False)
    normalize_qset(q)
    once = q.to_bytes()
    normalize_qset(q)
    assert q.to_bytes() == once
    if sane_before:
        sane_after, why = is_quorum_set_sane(q, False)
        assert sane_after, why


# --------------------------------------------------------------- offers --

@given(st.integers(1, 10**6), st.integers(1, 10**6),
       st.integers(0, 10**10), st.integers(0, 10**10),
       st.integers(0, 10**10), st.integers(0, 10**10),
       st.integers(0, 2))
@settings(max_examples=300, deadline=None)
def test_exchange_v10_value_conservation(pn, pd, mws, mwr, mss, msr,
                                         round_idx):
    """OfferExchange core properties (reference OfferExchange.cpp
    exchangeV10): outputs respect every limit and the resting side is
    never favored. Precondition mirrored from the reference: the resting
    (wheat) offer amount is first adjusted via adjustOffer, which is what
    makes the internal price-error assertions unreachable."""
    from stellar_core_tpu.tx.offer_math import (Price, RoundingType,
                                                adjust_offer_amount,
                                                exchange_v10)
    rt = [RoundingType.NORMAL, RoundingType.PATH_PAYMENT_STRICT_RECEIVE,
          RoundingType.PATH_PAYMENT_STRICT_SEND][round_idx]
    price = Price(n=pn, d=pd)
    mws = adjust_offer_amount(price, mws, msr)
    r = exchange_v10(price, mws, mwr, mss, msr, rt)
    # limits
    assert 0 <= r.num_wheat_received <= min(mwr, mws)
    assert 0 <= r.num_sheep_send <= min(msr, mss)
    # the staying side must never be favored: value given >= value priced
    if r.num_wheat_received > 0 and r.num_sheep_send > 0:
        wheat_value = r.num_wheat_received * pn
        sheep_value = r.num_sheep_send * pd
        if r.wheat_stays:
            assert sheep_value >= wheat_value
        else:
            assert sheep_value <= wheat_value
