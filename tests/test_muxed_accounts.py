"""Muxed accounts (CAP-27): med25519 sources/destinations demux to the
underlying ed25519 account for every ledger effect, while the mux id IS
part of the signed payload (two mux ids → different tx hashes). Plus
SEP-23 M-address strkey round trips.

Reference behaviors: transactions/TransactionUtils toAccountID (ledger
effects are mux-blind), tx signatures covering the full MuxedAccount
XDR, and StrKey muxed-account encoding.
"""

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.strkey import StrKey, StrKeyError
from stellar_core_tpu.xdr.transaction import (MuxedAccount,
                                              _MuxedAccountMed25519)
from stellar_core_tpu.xdr.types import CryptoKeyType

from txtest_utils import TestAccount, TestLedger, op_payment

XLM = 10_000_000


@pytest.fixture
def ledger():
    return TestLedger()


@pytest.fixture
def root(ledger):
    return ledger.root_account


def muxed(acct: TestAccount, mux_id: int) -> MuxedAccount:
    return MuxedAccount(
        CryptoKeyType.KEY_TYPE_MUXED_ED25519,
        _MuxedAccountMed25519(id=mux_id,
                              ed25519=acct.key.public_key().raw))


def _mk(ledger, root):
    a = TestAccount.fresh(ledger)
    b = TestAccount.fresh(ledger)
    assert root.create(a, 100 * XLM)
    assert root.create(b, 100 * XLM)
    a.sync_seq()
    return a, b


class TestMuxedLedgerEffects:
    def test_payment_to_muxed_dest_credits_base_account(self, ledger,
                                                        root):
        a, b = _mk(ledger, root)
        before = ledger.balance(b.account_id)
        assert a.apply([op_payment(muxed(b, 12345), XLM)])
        assert ledger.balance(b.account_id) - before == XLM

    def test_tx_from_muxed_source_debits_base_account(self, ledger, root):
        a, b = _mk(ledger, root)
        frame = a.tx([op_payment(b.muxed, XLM)])
        # rewrite the source as a muxed form of the same key, re-sign
        frame.tx.sourceAccount = muxed(a, 7)
        frame._contents_hash = None
        frame.signatures.clear()
        from txtest_utils import sign_frame
        sign_frame(frame, a.key)
        before = ledger.balance(a.account_id)
        assert ledger.apply_tx(frame), frame.result
        assert before - ledger.balance(a.account_id) == XLM + 100

    def test_mux_id_changes_the_signed_hash(self, ledger, root):
        """The mux id is inside the signature payload: the same tx
        under two mux ids has two different contents hashes (CAP-27's
        design: muxing is not malleable)."""
        a, b = _mk(ledger, root)
        nxt = a.seq + 1
        f1 = a.tx([op_payment(b.muxed, XLM)], seq=nxt)
        f2 = a.tx([op_payment(b.muxed, XLM)], seq=nxt)
        f1.tx.sourceAccount = muxed(a, 1)
        f2.tx.sourceAccount = muxed(a, 2)
        f1._contents_hash = f2._contents_hash = None
        assert f1.contents_hash() != f2.contents_hash()
        # ...so a signature made for mux id 1 does not validate id 2
        from txtest_utils import sign_frame
        f1.signatures.clear()       # drop the pre-mux signature
        f2.signatures.clear()
        sign_frame(f1, a.key)
        f2.signatures[:] = list(f1.signatures)
        f2.envelope.value.signatures = f2.signatures
        assert not ledger.check_valid(f2)

    def test_account_id_demux(self):
        acct = TestAccount(None,
                           SecretKey.pseudo_random_for_testing(424242))
        m = muxed(acct, 99)
        assert m.account_id() == acct.account_id
        assert MuxedAccount.from_ed25519(
            acct.key.public_key().raw).account_id() == acct.account_id


class TestMuxedStrKey:
    def test_m_address_roundtrip(self):
        raw = bytes(range(32))
        s = StrKey.encode_muxed_account(raw, 0xDEADBEEF)
        assert s.startswith("M")
        k, mid = StrKey.decode_muxed_account(s)
        assert k == raw and mid == 0xDEADBEEF

    def test_m_address_zero_and_max_id(self):
        raw = b"\x07" * 32
        for mid in (0, 2**64 - 1):
            k, got = StrKey.decode_muxed_account(
                StrKey.encode_muxed_account(raw, mid))
            assert (k, got) == (raw, mid)

    def test_m_address_rejects_corruption(self):
        s = StrKey.encode_muxed_account(b"\x01" * 32, 5)
        bad = s[:-1] + ("A" if s[-1] != "A" else "B")
        with pytest.raises(StrKeyError):
            StrKey.decode_muxed_account(bad)
        # a G-address is not an M-address
        g = StrKey.encode_ed25519_public(b"\x01" * 32)
        with pytest.raises(StrKeyError):
            StrKey.decode_muxed_account(g)
