"""Dual-host (curr/prev) protocol seam.

Reference: the node links two complete soroban host versions and routes
by ledger protocol (rust/Cargo.toml:27-56) so that replaying a
protocol-transition boundary is bit-exact. Here: SorobanHostPrev (p20,
original cost model) vs SorobanHost (p21+, recalibrated), dispatched by
header.ledgerVersion in InvokeHostFunctionOpFrame, exercised by a
catchup replay across the upgrade boundary — including the proof that
the seam is load-bearing (forcing the curr host for p20 ledgers makes
catchup diverge at exactly the pre-upgrade ledger)."""

import pytest

from stellar_core_tpu.catchup import (CatchupConfiguration, CatchupWork)
from stellar_core_tpu.herder.upgrades import UpgradeParameters
from stellar_core_tpu.history import make_tmpdir_archive
from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.soroban import host as host_mod
from stellar_core_tpu.soroban.host import (Budget, SorobanHost,
                                           SorobanHostPrev,
                                           host_for_protocol, instance_key)
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.work import State, run_work_to_completion
from stellar_core_tpu.xdr import contract as cx
from stellar_core_tpu.xdr.ledger_entries import LedgerKey

import test_standalone_app as m1
import test_soroban as ts


def test_host_dispatch_by_protocol():
    assert host_for_protocol(20) is SorobanHostPrev
    assert host_for_protocol(21) is SorobanHost
    assert host_for_protocol(25) is SorobanHost
    # the divergence is real: the prev host is strictly more expensive
    assert SorobanHostPrev.COST_CALL > SorobanHost.COST_CALL
    assert SorobanHostPrev.COST_STORAGE_OP > SorobanHost.COST_STORAGE_OP


def _probe_used(app, cid, host_cls) -> int:
    """Instructions one `increment` invoke consumes under host_cls."""
    addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid)
    source = m1.master_account(app).account_id
    with LedgerTxn(app.ledger_manager.root) as ltx:
        from stellar_core_tpu.soroban.network_config import \
            SorobanNetworkConfig
        budget = Budget(100_000_000)
        host = host_cls(
            ltx, ltx.get_header(), SorobanNetworkConfig(ltx),
            cx.LedgerFootprint(
                readOnly=[LedgerKey.contract_code(ts.wasm_hash()),
                          instance_key(addr)],
                readWrite=[ts.counter_key(cid)]),
            budget, app.config.network_id(), source)
        host.call_contract(addr, b"increment", [])
        ltx.rollback()
        return budget.used


@pytest.fixture
def published(tmp_path):
    """A node that crosses p20 -> p21 mid-history with a borderline
    invoke on each side, published to an archive."""
    archive_root = str(tmp_path / "archive")
    cfg = get_test_config()
    cfg.LEDGER_PROTOCOL_VERSION = 20
    cfg.HISTORY = {"test": {
        "get": f"cp {archive_root}/{{0}} {{1}}",
        "put": f"mkdir -p $(dirname {archive_root}/{{1}}) && "
               f"cp {{0}} {archive_root}/{{1}}",
    }}
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    assert app.ledger_manager.get_last_closed_ledger_header()\
        .ledgerVersion == 20
    ts.COUNTER_CODE = ts.CODE_BUILDS["scvm"]
    master, cid = ts.deploy(app)
    ro, rw = ts.invoke_footprints(cid)

    used_prev = _probe_used(app, cid, SorobanHostPrev)
    used_curr = _probe_used(app, cid, SorobanHost)
    assert used_curr < used_prev
    mid = (used_curr + used_prev) // 2

    # under p20 the borderline budget exhausts (prev cost model)
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "increment"), ro, rw,
        instructions=mid))
    assert res.result.result.disc.name == "txFAILED", res
    failed_hash = bytes(res.transactionHash)
    failed_at = app.ledger_manager.get_last_closed_ledger_num()

    # vote the protocol upgrade and close it in
    app.herder.upgrades.set_parameters(UpgradeParameters(
        upgrade_time=0, protocol_version=21))
    app.manual_close()
    assert app.ledger_manager.get_last_closed_ledger_header()\
        .ledgerVersion == 21

    # the SAME budget now succeeds (recalibrated host)
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "increment"), ro, rw,
        instructions=mid))
    assert res.result.result.disc.name == "txSUCCESS", res
    ok_hash = bytes(res.transactionHash)

    # run out to a published checkpoint (frequency 64: ledger 63)
    while app.ledger_manager.get_last_closed_ledger_num() < 63:
        app.manual_close()
    archive = make_tmpdir_archive("test", archive_root)
    return app, archive, failed_hash, failed_at, ok_hash, mid


def _fresh_replayer(app):
    cfg = get_test_config()
    cfg.LEDGER_PROTOCOL_VERSION = 20
    cfg.NETWORK_PASSPHRASE = app.config.NETWORK_PASSPHRASE
    app_b = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app_b.start()
    return app_b


def test_meta_version_in_upgrade_ledger_is_pre_upgrade(tmp_path):
    """Txs in the v19->v20 upgrade ledger were applied under protocol
    19 (upgrades run after txs), so their stored meta must be V2 — not
    the V3 the post-upgrade header would select."""
    from stellar_core_tpu.xdr.ledger import TransactionMeta

    cfg = get_test_config()
    cfg.LEDGER_PROTOCOL_VERSION = 19
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    try:
        from txtest_utils import op_payment
        master = m1.master_account(app)
        r = m1.submit(app, master.tx([op_payment(master.muxed, 1)]))
        assert r["status"] == "PENDING", r
        app.herder.upgrades.set_parameters(UpgradeParameters(
            upgrade_time=0, protocol_version=20))
        app.manual_close()
        hdr = app.ledger_manager.get_last_closed_ledger_header()
        assert hdr.ledgerVersion == 20
        seq = app.ledger_manager.get_last_closed_ledger_num()
        rows = app.database.query_all(
            "SELECT txmeta FROM txhistory WHERE ledgerseq=?", (seq,))
        assert rows, "upgrade ledger stored no txs"
        for row in rows:
            meta = TransactionMeta.from_bytes(bytes(row[0]))
            assert meta.disc == 2, \
                "meta in the upgrade ledger must use the apply-time " \
                f"protocol (got v{meta.disc})"
        # the NEXT ledger's txs are stored as V3
        r = m1.submit(app, master.tx([op_payment(master.muxed, 1)]))
        assert r["status"] == "PENDING", r
        app.manual_close()
        seq2 = app.ledger_manager.get_last_closed_ledger_num()
        rows = app.database.query_all(
            "SELECT txmeta FROM txhistory WHERE ledgerseq=?", (seq2,))
        assert rows
        for row in rows:
            assert TransactionMeta.from_bytes(bytes(row[0])).disc == 3
    finally:
        app.shutdown()


def test_catchup_replays_across_protocol_boundary(published):
    app, archive, failed_hash, _, ok_hash, _ = published
    app_b = _fresh_replayer(app)
    try:
        work = CatchupWork(app_b, archive, CatchupConfiguration(0))
        assert run_work_to_completion(app_b, work,
                                      timeout_virtual=3000) == \
            State.WORK_SUCCESS
        assert app_b.ledger_manager.get_last_closed_ledger_hash() == \
            app.database.query_one(
                "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=?",
                (63,))[0]
        # the replayed results reproduce the divergence exactly
        from stellar_core_tpu.xdr.results import TransactionResultPair
        for h, want in ((failed_hash, "txFAILED"), (ok_hash, "txSUCCESS")):
            row = app_b.database.query_one(
                "SELECT txresult FROM txhistory WHERE txid=?", (h,))
            assert row is not None
            got = TransactionResultPair.from_bytes(bytes(row[0]))
            assert got.result.result.disc.name == want
    finally:
        app_b.shutdown()
        app.shutdown()


def test_seam_is_load_bearing(published, monkeypatch, caplog):
    """Routing every ledger through the CURRENT host (no prev seam)
    makes replay diverge at exactly the pre-upgrade ledger — the
    hardest catchup case VERDICT r03 named unrepresentable before."""
    app, archive, _, failed_at, _, _ = published
    monkeypatch.setattr(
        "stellar_core_tpu.soroban.host.host_for_protocol",
        lambda _v: SorobanHost)
    app_b = _fresh_replayer(app)
    try:
        work = CatchupWork(app_b, archive, CatchupConfiguration(0))
        with caplog.at_level("ERROR"):
            final = run_work_to_completion(app_b, work,
                                           timeout_virtual=3000)
        assert final == State.WORK_FAILURE
        assert any(f"replay diverged at ledger {failed_at}" in r.message
                   for r in caplog.records), \
            [r.message for r in caplog.records]
    finally:
        app_b.shutdown()
        app.shutdown()
