"""Device-backend supervisor (ops/backend_supervisor.py) — circuit
breaker state machine, error classification, hung-dispatch watchdog,
degraded-mode semantics, and the observability surface (metrics,
Prometheus, flight recorder, backendstatus admin route).

The breaker wraps a duck-typed verifier, so most tests run against a
fake — no device, no XLA — and the parity contract stays the same as
the verify service's: results are identical to PubKeyUtils.verify_sig
in every breaker state.
"""

import time

import pytest

from stellar_core_tpu.crypto.keys import (SecretKey, clear_verify_cache,
                                          verify_sig_uncached)
from stellar_core_tpu.ops.backend_supervisor import (CLOSED, HALF_OPEN,
                                                     OPEN,
                                                     BackendSupervisor,
                                                     classify_error)
from stellar_core_tpu.ops.verify_service import VerifyService
from stellar_core_tpu.util import chaos
from stellar_core_tpu.util.chaos import ChaosEngine, FaultSpec
from stellar_core_tpu.util.timer import ClockMode, VirtualClock


class FakeVerifier:
    """Duck-typed device verifier: scriptable failures, dispatch
    counter independent of the supervisor's."""

    _device_min_batch = 7   # visible through the supervisor's proxy

    def __init__(self):
        self.fail_with = None
        self.dispatches = 0

    def verify_tuples_async(self, items):
        self.dispatches += 1
        if self.fail_with is not None:
            raise self.fail_with
        res = [verify_sig_uncached(p, s, m) for p, s, m in items]
        return lambda: res


def _mk_items(n, tag=b"sup"):
    sk = SecretKey.pseudo_random_for_testing(8200)
    out = []
    for i in range(n):
        m = (tag + b"-%d" % i).ljust(32, b".")
        out.append((sk.public_key().raw, sk.sign(m), m))
    return out


def _sup(fv=None, clock=None, **kw):
    kw.setdefault("failure_threshold", 3)
    kw.setdefault("probe_base_ms", 500.0)
    kw.setdefault("probe_max_ms", 2000.0)
    kw.setdefault("canary_batch", 2)
    return BackendSupervisor(fv or FakeVerifier(), clock=clock, **kw)


# ----------------------------------------------------- state machine --

def test_trips_after_consecutive_transient_failures():
    """N consecutive transient failures trip CLOSED→OPEN; while OPEN
    the device is never touched (dispatch counters frozen) and results
    stay correct through the native path."""
    items = _mk_items(2)
    fv = FakeVerifier()
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    sup = _sup(fv, clock)
    assert sup.verify_tuples(items) == [True, True]
    assert sup.state == CLOSED
    fv.fail_with = OSError("device gone")
    for _ in range(3):
        # every failed dispatch still resolves correctly (fallback)
        assert sup.verify_tuples(items) == [True, True]
    assert sup.state == OPEN
    inner_d, sup_d = fv.dispatches, sup.status()["dispatches"]
    for _ in range(5):
        assert sup.verify_tuples(items) == [True, True]
    assert fv.dispatches == inner_d          # zero device attempts
    assert sup.status()["dispatches"] == sup_d
    assert sup.status()["skips"] == 5
    assert sup.status()["failures"]["transient"] == 3


def test_success_resets_consecutive_count():
    items = _mk_items(1)
    fv = FakeVerifier()
    sup = _sup(fv)
    fv.fail_with = OSError("flap")
    sup.verify_tuples(items)
    sup.verify_tuples(items)
    fv.fail_with = None
    sup.verify_tuples(items)                 # success: counter resets
    fv.fail_with = OSError("flap")
    sup.verify_tuples(items)
    sup.verify_tuples(items)
    assert sup.state == CLOSED               # never 3 consecutive
    assert sup.consecutive_failures == 2


def test_fatal_error_trips_immediately():
    """Non-I/O errors (shape bugs, OOM) cannot succeed on retry: one
    occurrence trips the breaker without waiting for the threshold."""
    assert classify_error(ValueError("bad shape")) == "fatal"
    assert classify_error(OSError("io")) == "transient"
    assert classify_error(TimeoutError("deadline")) == "transient"
    items = _mk_items(1)
    fv = FakeVerifier()
    sup = _sup(fv)
    fv.fail_with = ValueError("reshape mismatch")
    assert sup.verify_tuples(items) == [True]
    assert sup.state == OPEN
    assert sup.status()["failures"]["fatal"] == 1


def test_probe_backoff_recovers_via_half_open():
    """The VirtualTimer probe schedule: failed canary probes bounce
    HALF_OPEN→OPEN with exponential backoff + jitter; once the device
    heals, a probe closes the breaker and traffic returns."""
    items = _mk_items(1)
    fv = FakeVerifier()
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    sup = _sup(fv, clock, jitter_seed=7)
    fv.fail_with = OSError("down")
    for _ in range(3):
        sup.verify_tuples(items)
    assert sup.state == OPEN
    st = sup.status()
    assert 0.5 <= st["next_probe_in_s"] <= 0.5 * 1.25
    clock.crank(True)                        # first probe: still down
    assert sup.state == OPEN
    st = sup.status()
    assert st["probe_attempt"] == 1
    assert 1.0 <= st["next_probe_in_s"] <= 1.0 * 1.25
    fv.fail_with = None                      # device heals
    clock.crank(True)                        # second probe: canary ok
    assert sup.state == CLOSED
    moves = [(t["from"], t["to"]) for t in sup.status()["transitions"]]
    assert moves == [(CLOSED, OPEN), (OPEN, HALF_OPEN),
                     (HALF_OPEN, OPEN), (OPEN, HALF_OPEN),
                     (HALF_OPEN, CLOSED)]
    d = fv.dispatches
    assert sup.verify_tuples(items) == [True]
    assert fv.dispatches == d + 1            # device traffic resumed


def test_canary_rejection_is_a_failed_probe_not_a_close():
    """A device that ANSWERS but rejects known-good canary signatures
    must not close the breaker: the collect completing is not the
    probe verdict — probe_now checks the canary contents, records a
    fatal probe failure, and the backoff escalates (wrong answers are
    worse than no answers)."""

    class WrongAnswerVerifier(FakeVerifier):
        def verify_tuples_async(self, items):
            self.dispatches += 1
            return lambda: [False] * len(items)

    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    sup = _sup(WrongAnswerVerifier(), clock, jitter_seed=3)
    sup.force_trip()
    assert sup.probe_now() is False
    assert sup.state == OPEN
    assert sup.status()["failures"]["fatal"] == 1
    assert sup.probe_attempt == 1            # backoff escalates
    moves = [(t["from"], t["to"]) for t in sup.status()["transitions"]]
    assert moves == [(CLOSED, OPEN), (OPEN, HALF_OPEN),
                     (HALF_OPEN, OPEN)]      # never CLOSED in between


def test_attribute_delegation_to_inner_verifier():
    sup = _sup(FakeVerifier())
    assert sup._device_min_batch == 7        # proxied, not shadowed


# --------------------------------------------------- hung dispatches --

def test_hang_fault_resolves_through_watchdog():
    """Chaos `hang` on the dispatch seam: the collect handle never
    completes; the watchdog deadline resolves the flush through native
    fallback (all futures set), quarantines the handle, and the
    breaker records a timeout-class failure."""
    clear_verify_cache()
    items = _mk_items(3, b"hang")
    sup = _sup(FakeVerifier(), dispatch_deadline_ms=80.0,
               failure_threshold=2)
    svc = VerifyService(sup, max_batch=8)
    chaos.install(ChaosEngine(5, [FaultSpec(
        "ops.backend.dispatch", "hang", start=0, count=1)]))
    try:
        futures = svc.submit_many(items)
        got = [f.result() for f in futures]
        assert got == [True] * 3
        assert all(f.done() for f in futures)
        st = sup.status()
        assert st["failures"]["timeout"] == 1
        assert len(st["quarantined"]) == 1
        assert st["quarantined"][0]["batch"] == 3
        assert chaos.engine().injected["chaos.injected.hang"] == 1
    finally:
        chaos.uninstall()
    # shutdown releases the parked collect thread; the quarantine list
    # forgets handles whose thread has exited
    sup.shutdown()
    deadline = time.monotonic() + 2.0
    while sup.status()["quarantined"] and time.monotonic() < deadline:
        time.sleep(0.01)
    assert sup.status()["quarantined"] == []


def test_consecutive_hangs_trip_breaker():
    clear_verify_cache()
    items = _mk_items(1, b"hang2")
    sup = _sup(FakeVerifier(), dispatch_deadline_ms=40.0,
               failure_threshold=2)
    chaos.install(ChaosEngine(6, [FaultSpec(
        "ops.backend.dispatch", "hang", start=0, count=2)]))
    try:
        assert sup.verify_tuples(items) == [True]
        assert sup.state == CLOSED
        assert sup.verify_tuples(items) == [True]
        assert sup.state == OPEN
        assert sup.status()["failures"]["timeout"] == 2
    finally:
        chaos.uninstall()
        sup.shutdown()


# --------------------------------------------------------- parity --

def test_results_identical_in_every_state():
    """Valid + corrupted signatures resolve identically to verify_sig
    whether the breaker is CLOSED, failing, or OPEN."""
    sk = SecretKey.pseudo_random_for_testing(8300)
    msg = b"parity".ljust(32, b".")
    sig = sk.sign(msg)
    bad = sig[:5] + bytes([sig[5] ^ 0xFF]) + sig[6:]
    items = [(sk.public_key().raw, sig, msg),
             (sk.public_key().raw, bad, msg)]
    want = [verify_sig_uncached(p, s, m) for p, s, m in items]
    assert want == [True, False]
    fv = FakeVerifier()
    sup = _sup(fv)
    assert sup.verify_tuples(items) == want          # CLOSED
    fv.fail_with = OSError("down")
    for _ in range(3):
        assert sup.verify_tuples(items) == want      # failing dispatch
    assert sup.state == OPEN
    assert sup.verify_tuples(items) == want          # OPEN (skip)


# ---------------------------------------------------- observability --

def _tpu_app():
    from stellar_core_tpu.main import Application, get_test_config
    cfg = get_test_config()
    cfg.SIGNATURE_VERIFY_BACKEND = "tpu"
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    return app


def test_backendstatus_route_and_forced_transitions():
    app = _tpu_app()
    try:
        out = app.command_handler.handle("backendstatus")
        assert out["backend"]["state"] == "CLOSED"
        assert out["backend"]["consecutive_failures"] == 0
        # forced trip (test config has ALLOW_CHAOS_INJECTION=True)
        out = app.command_handler.handle("backendstatus",
                                         {"action": "trip"})
        assert out["backend"]["state"] == "OPEN"
        assert out["backend"]["next_probe_in_s"] is not None
        out = app.command_handler.handle("backendstatus",
                                         {"action": "reset"})
        assert out["backend"]["state"] == "CLOSED"
        # production gating: no forced degradation over HTTP
        app.config.ALLOW_CHAOS_INJECTION = False
        out = app.command_handler.handle("backendstatus",
                                         {"action": "trip"})
        assert "exception" in out
        # plain status is always served
        out = app.command_handler.handle("backendstatus")
        assert out["backend"]["state"] == "CLOSED"
    finally:
        app.shutdown()


def test_backendstatus_without_device_backend():
    from stellar_core_tpu.main import Application, get_test_config
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             get_test_config())
    app.start()
    try:
        out = app.command_handler.handle("backendstatus")
        assert "exception" in out
    finally:
        app.shutdown()


def test_breaker_state_in_metrics_and_prometheus():
    app = _tpu_app()
    try:
        app.command_handler.handle("backendstatus", {"action": "trip"})
        j = app.command_handler.handle("metrics")["metrics"]
        assert j["crypto.verify_backend.state"]["count"] == 1  # OPEN
        assert j["crypto.verify_backend.transition.to_open"]["count"] \
            == 1
        prom = app.command_handler.handle(
            "metrics", {"format": "prometheus"})["_raw_body"]
        assert "crypto_verify_backend_state 1" in prom
        assert "crypto_verify_backend_transition_to_open 1" in prom
        assert "crypto_verify_backend_dispatch" in prom
        app.command_handler.handle("backendstatus", {"action": "reset"})
        j = app.command_handler.handle("metrics")["metrics"]
        assert j["crypto.verify_backend.state"]["count"] == 0  # CLOSED
    finally:
        app.shutdown()


def test_clearmetrics_preserves_breaker_state_gauge():
    """The state gauge is a level, not a flow: clearing metrics while
    the breaker is OPEN must not report it as CLOSED until the next
    transition happens to re-set the gauge."""
    app = _tpu_app()
    try:
        app.command_handler.handle("backendstatus", {"action": "trip"})
        app.command_handler.handle("clearmetrics")
        j = app.command_handler.handle("metrics")["metrics"]
        assert j["crypto.verify_backend.state"]["count"] == 1  # OPEN
    finally:
        app.shutdown()


def test_breaker_transitions_emit_flight_recorder_instants():
    app = _tpu_app()
    try:
        app.flight_recorder.start()
        app.command_handler.handle("backendstatus", {"action": "trip"})
        app.command_handler.handle("backendstatus", {"action": "reset"})
        app.flight_recorder.stop()
        doc = app.flight_recorder.to_chrome_trace()
        inst = [e for e in doc["traceEvents"]
                if e.get("name") == "backend.breaker"]
        assert len(inst) == 2
        assert inst[0]["args"] == {"from": "CLOSED", "to": "OPEN",
                                   "reason": "forced_trip"}
        assert inst[1]["args"]["to"] == "CLOSED"
    finally:
        app.shutdown()


def test_self_check_reports_backend_state():
    from stellar_core_tpu.main.self_check import self_check
    app = _tpu_app()
    try:
        app.batch_verifier.force_trip()
        # flip the backend label so self_check skips its §5 device
        # benchmark (a 1024-bucket XLA compile, ~90 s on the CPU test
        # mesh); §6 (service warmup) and §7 (supervisor state) — the
        # subjects here — key on the live objects, not the label
        app.config.SIGNATURE_VERIFY_BACKEND = "native"
        ok, report = self_check(app, crypto_bench_seconds=0.01,
                                max_headers=4)
        assert report["verify_backend"]["state"] == "OPEN"
        assert report["verify_backend_degraded"] is True
        # degraded mode is reported, not failed: the service warmup
        # ran through the native path and still verified
        assert report["verify_service_ok"] is True
    finally:
        app.shutdown()


def test_hang_fault_spec_json_roundtrip():
    spec = FaultSpec("ops.backend.dispatch", "hang", start=2, count=3)
    doc = spec.to_json()
    back = FaultSpec.from_json(doc)
    assert (back.point, back.kind, back.start, back.count) == \
        ("ops.backend.dispatch", "hang", 2, 3)
