"""SCP consensus kernel tests.

Modeled on the reference's pure-SCP scripted-driver tests
(scp/test/SCPTests.cpp, SCPUnitTests.cpp): no application, no network —
envelopes are hand-built and fed to one node under test; assertions run
against its emitted envelopes and driver callbacks.
"""

import hashlib

import pytest

from stellar_core_tpu.scp import (SCP, EnvelopeState, SCPDriver,
                                  ValidationLevel)
from stellar_core_tpu.scp import local_node as ln
from stellar_core_tpu.scp.ballot import SCPPhase
from stellar_core_tpu.scp.quorum_set_utils import (is_quorum_set_sane,
                                                   normalize_qset)
from stellar_core_tpu.xdr.scp import (SCPBallot, SCPEnvelope, SCPNomination,
                                      SCPQuorumSet, SCPStatement,
                                      SCPStatementConfirm,
                                      SCPStatementExternalize,
                                      SCPStatementPrepare, SCPStatementType,
                                      _SCPStatementPledges)
from stellar_core_tpu.xdr.types import PublicKey


def node(i: int) -> bytes:
    return hashlib.sha256(b"node-%d" % i).digest()


def make_qset(nodes, threshold, inner=()):
    return SCPQuorumSet(
        threshold=threshold,
        validators=[PublicKey.ed25519(n) for n in nodes],
        innerSets=list(inner))


class TestDriver(SCPDriver):
    def __init__(self):
        self.qsets = {}
        self.emitted = []
        self.externalized = {}
        self.timers = {}        # (slot, timer_id) -> (timeout, cb)
        self.heard_from_quorum = []
        self.priority_override = None  # node -> priority for leader tests

    def register_qset(self, qset):
        self.qsets[ln.qset_hash(qset)] = qset
        return ln.qset_hash(qset)

    def sign_envelope(self, env):
        env.signature = b"sig"

    def emit_envelope(self, env):
        self.emitted.append(env)

    def get_qset(self, h):
        return self.qsets.get(h)

    def validate_value(self, slot_index, value, nomination):
        return ValidationLevel.kFullyValidatedValue

    def combine_candidates(self, slot_index, candidates):
        # reference tests: largest candidate wins
        return max(candidates)

    def setup_timer(self, slot_index, timer_id, timeout, cb):
        self.timers[(slot_index, timer_id)] = (timeout, cb)

    def value_externalized(self, slot_index, value):
        assert slot_index not in self.externalized
        self.externalized[slot_index] = value

    def ballot_did_hear_from_quorum(self, slot_index, ballot):
        self.heard_from_quorum.append((slot_index, ballot.counter))

    def compute_hash_node(self, slot_index, prev, is_priority, round_n,
                          node_id):
        if self.priority_override is not None:
            return self.priority_override(node_id) if is_priority else 0
        return super().compute_hash_node(slot_index, prev, is_priority,
                                         round_n, node_id)


# ----------------------------------------------------- envelope builders --

def ballot(n, v):
    return SCPBallot(counter=n, value=v)


def make_env(node_raw, slot, pledges):
    st = SCPStatement(nodeID=PublicKey.ed25519(node_raw), slotIndex=slot,
                      pledges=pledges)
    return SCPEnvelope(statement=st, signature=b"sig")


def make_prepare(node_raw, qs_hash, slot, b, p=None, pp=None, nC=0, nH=0):
    return make_env(node_raw, slot, _SCPStatementPledges(
        SCPStatementType.SCP_ST_PREPARE,
        SCPStatementPrepare(quorumSetHash=qs_hash, ballot=b, prepared=p,
                            preparedPrime=pp, nC=nC, nH=nH)))


def make_confirm(node_raw, qs_hash, slot, nPrepared, b, nC, nH):
    return make_env(node_raw, slot, _SCPStatementPledges(
        SCPStatementType.SCP_ST_CONFIRM,
        SCPStatementConfirm(ballot=b, nPrepared=nPrepared, nCommit=nC,
                            nH=nH, quorumSetHash=qs_hash)))


def make_externalize(node_raw, qs_hash, slot, commit, nH):
    return make_env(node_raw, slot, _SCPStatementPledges(
        SCPStatementType.SCP_ST_EXTERNALIZE,
        SCPStatementExternalize(commit=commit, nH=nH,
                                commitQuorumSetHash=qs_hash)))


def make_nominate(node_raw, qs_hash, slot, votes, accepted=()):
    return make_env(node_raw, slot, _SCPStatementPledges(
        SCPStatementType.SCP_ST_NOMINATE,
        SCPNomination(quorumSetHash=qs_hash, votes=sorted(votes),
                      accepted=sorted(accepted))))


# ------------------------------------------------------------ quorum math --

class TestQuorumLogic:
    def test_is_quorum_slice_flat(self):
        qs = make_qset([node(i) for i in range(4)], 3)
        assert not ln.is_quorum_slice(qs, {node(0), node(1)})
        assert ln.is_quorum_slice(qs, {node(0), node(1), node(2)})
        assert ln.is_quorum_slice(qs, {node(i) for i in range(4)})

    def test_is_v_blocking_flat(self):
        qs = make_qset([node(i) for i in range(4)], 3)
        # threshold 3 of 4: any 2 nodes block
        assert not ln.is_v_blocking(qs, {node(0)})
        assert ln.is_v_blocking(qs, {node(0), node(1)})
        # threshold 0: nothing blocks
        qs0 = make_qset([], 0)
        assert not ln.is_v_blocking(qs0, {node(0)})

    def test_nested_slices(self):
        inner = make_qset([node(2), node(3), node(4)], 2)
        qs = make_qset([node(0), node(1)], 2, inner=[inner])
        # need 2 of {v0, v1, inner}; inner needs 2 of {v2,v3,v4}
        assert ln.is_quorum_slice(qs, {node(0), node(1)})
        assert ln.is_quorum_slice(qs, {node(0), node(2), node(3)})
        assert not ln.is_quorum_slice(qs, {node(0), node(2)})

    def test_node_weight_and_sanity(self):
        qs = make_qset([node(i) for i in range(4)], 2)
        w = ln.get_node_weight(node(1), qs)
        assert w == (2**64 - 1) * 2 // 4 + 1  # round-up of half
        assert ln.get_node_weight(node(9), qs) == 0
        ok, _ = is_quorum_set_sane(qs, False)
        assert ok
        bad = make_qset([node(0)], 2)
        ok, err = is_quorum_set_sane(bad, False)
        assert not ok and "Threshold exceeds" in err
        dup = make_qset([node(0), node(0)], 1)
        ok, err = is_quorum_set_sane(dup, False)
        assert not ok and "Duplicate" in err

    def test_normalize(self):
        inner = make_qset([node(2)], 1)
        qs = make_qset([node(1), node(0)], 2, inner=[inner])
        normalize_qset(qs)
        # singleton inner collapsed into validators; sorted
        assert len(qs.innerSets) == 0
        keys = [ln.node_key(v) for v in qs.validators]
        assert keys == sorted([node(0), node(1), node(2)])

    def test_normalize_removes_self(self):
        qs = make_qset([node(0), node(1), node(2)], 2)
        normalize_qset(qs, node(0))
        assert qs.threshold == 1
        assert len(qs.validators) == 2


# ----------------------------------------------------------- core5 ballot --

class Core5:
    """Node v0 with qset {v0..v4} threshold 4 (reference: SCPTests
    'ballot protocol core5')."""

    def __init__(self):
        self.driver = TestDriver()
        self.qset = make_qset([node(i) for i in range(5)], 4)
        self.qs_hash = self.driver.register_qset(self.qset)
        self.scp = SCP(self.driver, node(0), True, self.qset)
        self.x = b"x-value-lo"
        self.y = b"y-value-hi"   # y > x
        assert self.x < self.y

    def recv(self, env):
        return self.scp.receive_envelope(env)

    def recv_quorum(self, make_fn):
        """Envelopes from v1..v3 (with v0 itself = 4 of 5)."""
        for i in (1, 2, 3):
            assert self.recv(make_fn(node(i))) == EnvelopeState.VALID

    def recv_v_blocking(self, make_fn):
        """v1, v2: threshold 4 of 5 means 2 nodes are v-blocking."""
        for i in (1, 2):
            assert self.recv(make_fn(node(i))) == EnvelopeState.VALID

    def slot(self, idx=0):
        return self.scp.get_slot(idx)

    def last_emitted(self):
        assert self.driver.emitted
        return self.driver.emitted[-1]


class TestBallotProtocolCore5:
    def test_prepare_to_externalize(self):
        """The canonical happy path: x prepared → confirmed prepared →
        accept commit → confirm commit → externalize."""
        c5 = Core5()
        A1 = ballot(1, c5.x)

        # bump to <1, x>: emits PREPARE b=A1
        assert c5.slot().bump_state(c5.x, True)
        env = c5.last_emitted()
        assert env.statement.pledges.disc == SCPStatementType.SCP_ST_PREPARE
        assert env.statement.pledges.value.ballot.counter == 1

        # quorum votes prepare A1 → v0 accepts prepared A1
        c5.recv_quorum(lambda n: make_prepare(n, c5.qs_hash, 0, A1))
        env = c5.last_emitted()
        p = env.statement.pledges.value
        assert p.prepared is not None and p.prepared.counter == 1

        # quorum accepts prepared A1 → confirmed prepared: h=c=A1
        c5.recv_quorum(lambda n: make_prepare(n, c5.qs_hash, 0, A1, p=A1))
        env = c5.last_emitted()
        p = env.statement.pledges.value
        assert p.nC == 1 and p.nH == 1

        # quorum votes commit (nC=1, nH=1) → accept commit → CONFIRM
        c5.recv_quorum(lambda n: make_prepare(n, c5.qs_hash, 0, A1, p=A1,
                                              nC=1, nH=1))
        env = c5.last_emitted()
        assert env.statement.pledges.disc == SCPStatementType.SCP_ST_CONFIRM
        conf = env.statement.pledges.value
        assert conf.nCommit == 1 and conf.nH == 1

        # quorum accepts commit → confirm commit → EXTERNALIZE
        c5.recv_quorum(lambda n: make_confirm(n, c5.qs_hash, 0, 1, A1, 1, 1))
        env = c5.last_emitted()
        assert env.statement.pledges.disc == \
            SCPStatementType.SCP_ST_EXTERNALIZE
        assert c5.driver.externalized[0] == c5.x
        assert c5.slot().phase == SCPPhase.SCP_PHASE_EXTERNALIZE

    def test_v_blocking_accept_prepared(self):
        """A v-blocking set accepting prepared short-circuits the vote."""
        c5 = Core5()
        A1 = ballot(1, c5.x)
        assert c5.slot().bump_state(c5.x, True)
        c5.recv_v_blocking(lambda n: make_prepare(n, c5.qs_hash, 0, A1,
                                                  p=A1))
        env = c5.last_emitted()
        p = env.statement.pledges.value
        assert p.prepared is not None and p.prepared.counter == 1

    def test_v_blocking_jump_to_confirm(self):
        """v-blocking CONFIRM statements pull the node straight into
        accepting the commit (reference: 'v-blocking accept commit')."""
        c5 = Core5()
        A1 = ballot(1, c5.x)
        assert c5.slot().bump_state(c5.x, True)
        c5.recv_v_blocking(lambda n: make_confirm(n, c5.qs_hash, 0, 1, A1,
                                                  1, 1))
        env = c5.last_emitted()
        assert env.statement.pledges.disc == SCPStatementType.SCP_ST_CONFIRM

    def test_prepared_prime_tracks_incompatible(self):
        """Accepting a higher incompatible prepared ballot moves p→p'."""
        c5 = Core5()
        A1 = ballot(1, c5.x)
        B1 = ballot(1, c5.y)
        B2 = ballot(2, c5.y)
        assert c5.slot().bump_state(c5.x, True)
        c5.recv_quorum(lambda n: make_prepare(n, c5.qs_hash, 0, A1))
        # quorum prepares B2 (incompatible, higher)
        c5.recv_quorum(lambda n: make_prepare(n, c5.qs_hash, 0, B2, p=B2))
        bp = c5.slot().ballot
        assert bytes(bp.prepared.value) == c5.y
        assert bytes(bp.prepared_prime.value) == c5.x

    def test_timer_armed_on_quorum(self):
        """Hearing from a quorum on the current counter arms the ballot
        timer with computeTimeout(counter)."""
        c5 = Core5()
        A1 = ballot(1, c5.x)
        assert c5.slot().bump_state(c5.x, True)
        c5.recv_quorum(lambda n: make_prepare(n, c5.qs_hash, 0, A1))
        assert (0, 1) in c5.driver.timers
        timeout, cb = c5.driver.timers[(0, 1)]
        assert timeout == 1.0 and cb is not None
        assert c5.driver.heard_from_quorum

    def test_timer_bumps_counter(self):
        """Firing the ballot timer abandons the ballot: counter + 1."""
        c5 = Core5()
        A1 = ballot(1, c5.x)
        assert c5.slot().bump_state(c5.x, True)
        c5.recv_quorum(lambda n: make_prepare(n, c5.qs_hash, 0, A1))
        _, cb = c5.driver.timers[(0, 1)]
        cb()
        env = c5.last_emitted()
        assert env.statement.pledges.value.ballot.counter == 2

    def test_attempt_bump_on_v_blocking_ahead(self):
        """Step 9: a v-blocking set on higher counters drags us up to the
        lowest such counter."""
        c5 = Core5()
        A1 = ballot(1, c5.x)
        A3 = ballot(3, c5.x)
        assert c5.slot().bump_state(c5.x, True)
        c5.recv_v_blocking(lambda n: make_prepare(n, c5.qs_hash, 0, A3))
        bp = c5.slot().ballot
        assert bp.current.counter == 3

    def test_stale_and_malformed_rejected(self):
        c5 = Core5()
        A1 = ballot(1, c5.x)
        env = make_prepare(node(1), c5.qs_hash, 0, A1)
        assert c5.recv(env) == EnvelopeState.VALID
        # exact duplicate: not newer
        env2 = make_prepare(node(1), c5.qs_hash, 0, A1)
        assert c5.recv(env2) == EnvelopeState.INVALID
        # malformed: nC > nH
        bad = make_prepare(node(2), c5.qs_hash, 0, ballot(5, c5.x),
                           p=ballot(5, c5.x), nC=4, nH=2)
        assert c5.recv(bad) == EnvelopeState.INVALID
        # unknown qset hash
        unk = make_prepare(node(3), b"\x99" * 32, 0, A1)
        assert c5.recv(unk) == EnvelopeState.INVALID

    def test_externalize_envelope_moves_to_commit(self):
        """Quorum of EXTERNALIZE statements convinces a fresh node."""
        c5 = Core5()
        AInf = ballot(0xFFFFFFFF, c5.x)
        assert c5.slot().bump_state(c5.x, True)
        for i in (1, 2, 3):
            assert c5.recv(make_externalize(
                node(i), c5.qs_hash, 0, ballot(1, c5.x), 1)) == \
                EnvelopeState.VALID
        assert c5.driver.externalized.get(0) == c5.x


# ------------------------------------------------------------- nomination --

class TestNomination:
    def test_self_leader_nominates_and_externalizes_value(self):
        """v0 as round leader votes its own value; quorum votes/accepts
        drive it to candidate → ballot protocol."""
        c5 = Core5()
        c5.driver.priority_override = lambda n: 1000 if n == node(0) else 1
        prev = b"prev-value"
        assert c5.scp.nominate(0, c5.x, prev)
        env = c5.driver.emitted[-1]
        assert env.statement.pledges.disc == SCPStatementType.SCP_ST_NOMINATE
        assert bytes(env.statement.pledges.value.votes[0]) == c5.x

        # quorum votes for x → accepted
        for i in (1, 2, 3):
            assert c5.recv(make_nominate(node(i), c5.qs_hash, 0, [c5.x])) \
                == EnvelopeState.VALID
        nom = c5.slot().nomination
        assert c5.x in nom.accepted

        # quorum accepts x → candidate → ballot protocol starts
        for i in (1, 2, 3):
            assert c5.recv(make_nominate(node(i), c5.qs_hash, 0, [c5.x],
                                         accepted=[c5.x])) == \
                EnvelopeState.VALID
        assert c5.x in nom.candidates
        assert c5.slot().ballot.current is not None
        assert bytes(c5.slot().ballot.current.value) == c5.x

    def test_follower_adopts_leader_votes(self):
        """When v1 is the only leader, v0 echoes v1's nominations."""
        c5 = Core5()
        c5.driver.priority_override = lambda n: 1000 if n == node(1) else 1
        prev = b"prev-value"
        # v1's nomination arrives first
        assert c5.recv(make_nominate(node(1), c5.qs_hash, 0, [c5.y])) == \
            EnvelopeState.VALID
        c5.scp.nominate(0, c5.x, prev)
        nom = c5.slot().nomination
        assert c5.y in nom.votes
        assert c5.x not in nom.votes  # not leader → own value not voted

    def test_nomination_timer_set(self):
        c5 = Core5()
        c5.driver.priority_override = lambda n: 1000 if n == node(0) else 1
        c5.scp.nominate(0, c5.x, b"prev")
        assert (0, 0) in c5.driver.timers
        timeout, cb = c5.driver.timers[(0, 0)]
        assert timeout == 1.0

    def test_nomination_rejects_unsorted(self):
        c5 = Core5()
        env = make_env(node(1), 0, _SCPStatementPledges(
            SCPStatementType.SCP_ST_NOMINATE,
            SCPNomination(quorumSetHash=c5.qs_hash,
                          votes=[b"bb", b"aa"], accepted=[])))
        assert c5.recv(env) == EnvelopeState.INVALID


class TestSCPFacade:
    def test_purge_slots(self):
        c5 = Core5()
        for i in range(5):
            c5.scp.get_slot(i)
        c5.scp.purge_slots(3)
        assert sorted(c5.scp.known_slots) == [3, 4]

    def test_latest_messages_roundtrip(self):
        c5 = Core5()
        assert c5.slot().bump_state(c5.x, True)
        msgs = c5.scp.get_latest_messages_send(0)
        assert len(msgs) == 1
        assert msgs[0].statement.pledges.disc == \
            SCPStatementType.SCP_ST_PREPARE


class TestBallotProtocolEdges:
    def test_commit_abandoned_on_incompatible_prepared(self):
        """After voting commit on x@1 (nC=1,nH=1), a quorum accepting
        prepared y@2 (incompatible, higher) forces the node to accept
        prepared y@2 and CLEAR its commit votes — the 'reset c when p is
        incompatible' rule (reference: BallotProtocol::setPrepared +
        updateCurrentIfNeeded)."""
        c5 = Core5()
        A1 = ballot(1, c5.x)
        B2 = ballot(2, c5.y)

        assert c5.slot().bump_state(c5.x, True)
        c5.recv_quorum(lambda n: make_prepare(n, c5.qs_hash, 0, A1))
        c5.recv_quorum(lambda n: make_prepare(n, c5.qs_hash, 0, A1, p=A1))
        env = c5.last_emitted()
        p = env.statement.pledges.value
        assert p.nC == 1 and p.nH == 1  # voting commit x@1

        # quorum accepts prepared y@2: p := y@2, p' := x@1, commit cleared
        c5.recv_quorum(lambda n: make_prepare(n, c5.qs_hash, 0, B2, p=B2))
        env = c5.last_emitted()
        p = env.statement.pledges.value
        assert p.prepared is not None
        assert (p.prepared.counter, bytes(p.prepared.value)) == (2, c5.y)
        assert p.preparedPrime is not None
        assert (p.preparedPrime.counter,
                bytes(p.preparedPrime.value)) == (1, c5.x)
        # the commit on x is abandoned; the quorum's accepted-prepared
        # y@2 then confirms prepared, so a NEW commit legitimately forms
        # on y (nC on the current ballot, which now carries y)
        assert bytes(p.ballot.value) == c5.y
        assert p.nC in (0, 2)
        if p.nC:
            assert p.nH >= p.nC   # interval well-formed on the new commit

    def test_confirm_interval_extends_h(self):
        """In CONFIRM phase, a quorum confirming a wider commit interval
        raises the node's nH (reference: attemptConfirmCommit interval
        extension)."""
        c5 = Core5()
        A1 = ballot(1, c5.x)

        assert c5.slot().bump_state(c5.x, True)
        c5.recv_quorum(lambda n: make_prepare(n, c5.qs_hash, 0, A1))
        c5.recv_quorum(lambda n: make_prepare(n, c5.qs_hash, 0, A1, p=A1))
        c5.recv_quorum(lambda n: make_prepare(n, c5.qs_hash, 0, A1, p=A1,
                                              nC=1, nH=1))
        env = c5.last_emitted()
        assert env.statement.pledges.disc == SCPStatementType.SCP_ST_CONFIRM

        # quorum now accepts commit over [1, 3] (ballot counter 3): the
        # confirmed interval grows
        A3 = ballot(3, c5.x)
        c5.recv_quorum(lambda n: make_confirm(n, c5.qs_hash, 0, 3, A3, 1, 3))
        env = c5.last_emitted()
        pl = env.statement.pledges
        if pl.disc == SCPStatementType.SCP_ST_EXTERNALIZE:
            assert pl.value.nH == 3
            assert bytes(pl.value.commit.value) == c5.x
        else:
            assert pl.disc == SCPStatementType.SCP_ST_CONFIRM
            assert pl.value.nH == 3
