"""Multi-node SCP agreement tests: N real SCP instances wired through an
in-memory message bus (the pure-consensus analogue of the reference's
Simulation tests — every node runs the same code, no scripted envelopes).
"""

import hashlib

import pytest

from stellar_core_tpu.scp import SCP, SCPDriver, ValidationLevel
from stellar_core_tpu.scp import local_node as ln
from stellar_core_tpu.scp.ballot import SCPPhase
from stellar_core_tpu.xdr.scp import SCPQuorumSet
from stellar_core_tpu.xdr.types import PublicKey


def node(i: int) -> bytes:
    return hashlib.sha256(b"netnode-%d" % i).digest()


class BusDriver(SCPDriver):
    """Driver that posts emitted envelopes onto a shared bus and runs
    timers from a sorted virtual-time queue."""

    def __init__(self, bus, node_raw):
        self.bus = bus
        self.node_raw = node_raw
        self.externalized = {}
        self.timers = {}

    def sign_envelope(self, env):
        env.signature = b"sig:" + self.node_raw[:8]

    def emit_envelope(self, env):
        self.bus.broadcast(self.node_raw, env)

    def get_qset(self, h):
        return self.bus.qsets.get(h)

    def validate_value(self, slot_index, value, nomination):
        return ValidationLevel.kFullyValidatedValue

    def combine_candidates(self, slot_index, candidates):
        return max(candidates)

    def setup_timer(self, slot_index, timer_id, timeout, cb):
        if cb is None:
            self.timers.pop((slot_index, timer_id), None)
        else:
            self.timers[(slot_index, timer_id)] = (timeout, cb)

    def value_externalized(self, slot_index, value):
        assert slot_index not in self.externalized, "double externalize"
        self.externalized[slot_index] = value


class Bus:
    def __init__(self, n, threshold, drop=None):
        self.qsets = {}
        self.queue = []        # (from, env)
        self.drop = drop or (lambda frm, to: False)
        qset = SCPQuorumSet(
            threshold=threshold,
            validators=[PublicKey.ed25519(node(i)) for i in range(n)],
            innerSets=[])
        self.qsets[ln.qset_hash(qset)] = qset
        self.drivers = {}
        self.nodes = {}
        for i in range(n):
            d = BusDriver(self, node(i))
            self.drivers[node(i)] = d
            self.nodes[node(i)] = SCP(d, node(i), True, qset)

    def broadcast(self, frm, env):
        self.queue.append((frm, env))

    def drain(self, max_msgs=10000):
        count = 0
        while self.queue and count < max_msgs:
            frm, env = self.queue.pop(0)
            for to, scp in self.nodes.items():
                if to == frm or self.drop(frm, to):
                    continue
                scp.receive_envelope(env)
            count += 1
        return count

    def fire_timers(self, timer_id=None):
        """Fire every armed timer once (simulates simultaneous expiry)."""
        fired = 0
        for d in self.drivers.values():
            for key, (timeout, cb) in list(d.timers.items()):
                if timer_id is not None and key[1] != timer_id:
                    continue
                d.timers.pop(key, None)
                cb()
                fired += 1
        return fired


def test_five_nodes_agree():
    """5 nodes, threshold 4: all nominate different values, all
    externalize the same one."""
    bus = Bus(5, 4)
    prev = b"prev"
    for i, (nid, scp) in enumerate(sorted(bus.nodes.items())):
        scp.nominate(0, b"value-%d" % i, prev)
        bus.drain()
    for _ in range(10):
        bus.drain()
        if all(0 in d.externalized for d in bus.drivers.values()):
            break
        bus.fire_timers()
    values = {d.externalized.get(0) for d in bus.drivers.values()}
    assert len(values) == 1 and None not in values


def test_three_nodes_agree():
    bus = Bus(3, 2)
    for i, (nid, scp) in enumerate(sorted(bus.nodes.items())):
        scp.nominate(7, b"val-%d" % i, b"prev7")
        bus.drain()
    for _ in range(10):
        bus.drain()
        if all(7 in d.externalized for d in bus.drivers.values()):
            break
        bus.fire_timers()
    values = {d.externalized.get(7) for d in bus.drivers.values()}
    assert len(values) == 1 and None not in values


def test_lagging_node_catches_up_from_externalize():
    """A node that missed the whole round externalizes purely from the
    others' EXTERNALIZE messages."""
    bus = Bus(4, 3)
    lagging = node(3)
    bus.drop = lambda frm, to: to == lagging or frm == lagging
    for i, (nid, scp) in enumerate(sorted(bus.nodes.items())):
        if nid != lagging:
            scp.nominate(0, b"value-%d" % i, b"prev")
            bus.drain()
    for _ in range(10):
        bus.drain()
        done = [d for n, d in bus.drivers.items()
                if n != lagging and 0 in d.externalized]
        if len(done) == 3:
            break
        bus.fire_timers()
    assert len([d for n, d in bus.drivers.items()
                if n != lagging and 0 in d.externalized]) == 3

    # reconnect: others re-send their externalize state
    bus.drop = lambda frm, to: False
    lag_scp = bus.nodes[lagging]
    for nid, scp in bus.nodes.items():
        if nid == lagging:
            continue
        for env in scp.get_current_state(0):
            lag_scp.receive_envelope(env)
    assert 0 in bus.drivers[lagging].externalized
    assert bus.drivers[lagging].externalized[0] == \
        next(d.externalized[0] for n, d in bus.drivers.items()
             if n != lagging)


def test_successive_slots():
    """Consensus proceeds slot after slot, previous value feeding the
    next round's leader election."""
    bus = Bus(3, 2)
    prev = b"genesis"
    for slot in range(3):
        for i, (nid, scp) in enumerate(sorted(bus.nodes.items())):
            scp.nominate(slot, b"s%d-val-%d" % (slot, i), prev)
            bus.drain()
        for _ in range(10):
            bus.drain()
            if all(slot in d.externalized for d in bus.drivers.values()):
                break
            bus.fire_timers()
        values = {d.externalized.get(slot) for d in bus.drivers.values()}
        assert len(values) == 1 and None not in values
        prev = values.pop()


def test_lossy_links_still_agree():
    """Message loss on some pairs while quorums stay connected: the
    protocol still converges (reference: SCPTests' lossy simulations /
    Simulation::crankUntil with dropped connections)."""
    a, b = node(0), node(1)

    def drop(frm, to):
        # sever the 0<->1 link both ways; every other pair is healthy
        return {frm, to} == {a, b}

    bus = Bus(4, 3, drop=drop)
    for i, (nid, scp) in enumerate(sorted(bus.nodes.items())):
        scp.nominate(0, b"lossy-%d" % i, b"prev")
        bus.drain()
    for _ in range(12):
        bus.drain()
        if all(0 in d.externalized for d in bus.drivers.values()):
            break
        bus.fire_timers()
    values = {d.externalized.get(0) for d in bus.drivers.values()}
    assert len(values) == 1 and None not in values


def test_minority_partition_is_safe_not_live():
    """5 nodes, threshold 4, two nodes partitioned away: NEITHER side
    can reach threshold, so nobody externalizes — safety before
    liveness (reference: SCP's blocking-threshold guarantees)."""
    minority = {node(3), node(4)}

    def drop(frm, to):
        return (frm in minority) != (to in minority)

    bus = Bus(5, 4, drop=drop)
    for i, (nid, scp) in enumerate(sorted(bus.nodes.items())):
        scp.nominate(0, b"part-%d" % i, b"prev")
        bus.drain()
    for _ in range(8):
        bus.drain()
        bus.fire_timers()
    assert all(0 not in d.externalized for d in bus.drivers.values())


def test_partition_heals_and_agrees():
    """After the partition heals, pending envelopes + timers drive the
    whole network to one value (reference: Simulation partition tests)."""
    state = {"split": True}
    minority = {node(3), node(4)}

    def drop(frm, to):
        return state["split"] and ((frm in minority) != (to in minority))

    bus = Bus(5, 4, drop=drop)
    for i, (nid, scp) in enumerate(sorted(bus.nodes.items())):
        scp.nominate(0, b"heal-%d" % i, b"prev")
        bus.drain()
    for _ in range(4):
        bus.drain()
        bus.fire_timers()
    assert all(0 not in d.externalized for d in bus.drivers.values())
    state["split"] = False
    # re-announce current state: healed links deliver fresh envelopes
    for nid, scp in sorted(bus.nodes.items()):
        env = scp.get_latest_message(nid)
        if env is not None:
            bus.broadcast(nid, env)
    for _ in range(12):
        bus.drain()
        if all(0 in d.externalized for d in bus.drivers.values()):
            break
        bus.fire_timers()
    values = {d.externalized.get(0) for d in bus.drivers.values()}
    assert len(values) == 1 and None not in values


def test_duplicate_and_reordered_delivery_is_idempotent():
    """Envelopes delivered twice and in shuffled order must not break
    agreement or double-externalize (BusDriver asserts single
    externalize per slot; reference: envelope idempotency in
    SCPTests)."""
    import random
    rng = random.Random(7)

    class ShuffleBus(Bus):
        def drain(self, max_msgs=10000):
            count = 0
            while self.queue and count < max_msgs:
                rng.shuffle(self.queue)
                frm, env = self.queue.pop(0)
                targets = [t for t in self.nodes if t != frm]
                rng.shuffle(targets)
                for to in targets:
                    self.nodes[to].receive_envelope(env)
                    if rng.random() < 0.5:
                        self.nodes[to].receive_envelope(env)  # duplicate
                count += 1
            return count

    bus = ShuffleBus(4, 3)
    for i, (nid, scp) in enumerate(sorted(bus.nodes.items())):
        scp.nominate(0, b"dup-%d" % i, b"prev")
        bus.drain()
    for _ in range(12):
        bus.drain()
        if all(0 in d.externalized for d in bus.drivers.values()):
            break
        bus.fire_timers()
    values = {d.externalized.get(0) for d in bus.drivers.values()}
    assert len(values) == 1 and None not in values
