"""Fuzz-style robustness tests.

Reference: the AFL harness modes `tx` and `overlay` (docs/fuzzing.md,
test/FuzzerImpl.{h,cpp}) — here as deterministic random-corpus tests:
the node must never crash on malformed inputs, only reject them; plus
peer-db/ban behaviors.
"""

import random

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.overlay import LoopbackPeerConnection, PeerState
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.xdr.ledger_entries import LedgerEntry, LedgerKey
from stellar_core_tpu.xdr.scp import SCPEnvelope
from stellar_core_tpu.xdr.transaction import TransactionEnvelope

import test_standalone_app as m1
from test_overlay import make_apps, shutdown
from txtest_utils import op_create_account


RNG = random.Random(0xF055)


class TestXdrFuzz:
    """Random bytes and mutated valid bytes must raise cleanly, never
    crash or loop (reference: xdr fuzzing via load-xdr)."""

    TYPES = [TransactionEnvelope, SCPEnvelope, LedgerEntry, LedgerKey]

    def test_random_garbage_rejected(self):
        for cls in self.TYPES:
            for size in (0, 1, 3, 17, 100, 4096):
                for _ in range(20):
                    blob = bytes(RNG.getrandbits(8) for _ in range(size))
                    try:
                        cls.from_bytes(blob)
                    except Exception:
                        pass  # any clean Python exception is fine

    def test_mutated_valid_envelope(self):
        cfg = get_test_config()
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        with Application.create(clock, cfg) as app:
            app.start()
            master = m1.master_account(app)
            dest = m1.AppAccount(app, SecretKey.from_seed(b"\x43" * 32))
            frame = master.tx([op_create_account(dest.account_id, 10**10)])
            raw = frame.envelope.to_bytes()
            for _ in range(300):
                mutated = bytearray(raw)
                for _ in range(RNG.randint(1, 4)):
                    i = RNG.randrange(len(mutated))
                    mutated[i] ^= 1 << RNG.randrange(8)
                try:
                    env = TransactionEnvelope.from_bytes(bytes(mutated))
                except Exception:
                    continue
                # parsed: submission must not crash the node
                from stellar_core_tpu.tx.frame import make_frame
                try:
                    f = make_frame(env, app.config.network_id())
                except Exception:
                    continue
                app.herder.recv_transaction(f)
            # node still alive and closing ledgers
            app.manual_close()
            assert app.ledger_manager.get_last_closed_ledger_num() == 2


class TestOverlayFuzz:
    def test_peer_survives_garbage_floods(self):
        """Malformed frames drop the offending peer, never the node
        (reference: overlay fuzz mode)."""
        clock, apps = make_apps(2)
        try:
            conn = LoopbackPeerConnection(apps[0], apps[1])
            conn.crank()
            assert conn.initiator.state == PeerState.GOT_AUTH
            for _ in range(50):
                size = RNG.randint(1, 400)
                conn.initiator.out_queue.append(
                    bytes(RNG.getrandbits(8) for _ in range(size)))
            conn.crank()
            # acceptor dropped the garbage peer; its app is healthy
            assert conn.acceptor.state == PeerState.CLOSING
            apps[1].manual_close()
            assert apps[1].ledger_manager\
                .get_last_closed_ledger_num() == 2
        finally:
            shutdown(apps)


class TestPeerDbAndBans:
    def test_ban_drops_and_blocks(self):
        from stellar_core_tpu.crypto.strkey import StrKey
        clock, apps = make_apps(2)
        try:
            conn = LoopbackPeerConnection(apps[0], apps[1])
            conn.crank()
            assert len(apps[0].overlay_manager
                       .get_authenticated_peers()) == 1
            node1 = StrKey.encode_ed25519_public(
                apps[1].config.node_id())
            out = apps[0].command_handler.handle("ban", {"node": node1})
            assert out["status"] == "ok"
            assert apps[0].command_handler.handle("bans")["bans"] == \
                [node1]
            assert not apps[0].overlay_manager.get_authenticated_peers()
            # a new connection from the banned node is rejected at auth
            conn2 = LoopbackPeerConnection(apps[1], apps[0])
            conn2.crank()
            assert not apps[0].overlay_manager.get_authenticated_peers()
            apps[0].command_handler.handle("unban", {"node": node1})
            assert apps[0].command_handler.handle("bans")["bans"] == []
            conn3 = LoopbackPeerConnection(apps[1], apps[0])
            conn3.crank()
            assert len(apps[0].overlay_manager
                       .get_authenticated_peers()) == 1
        finally:
            shutdown(apps)

    def test_peer_db_backoff(self):
        cfg = get_test_config()
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        with Application.create(clock, cfg) as app:
            app.start()
            pm = app.overlay_manager.peer_manager
            pm.ensure_exists("10.0.0.1", 11625)
            assert ("10.0.0.1", 11625) in pm.candidates(5)
            pm.update_failure("10.0.0.1", 11625)
            # backed off: not offered until nextattempt passes
            assert ("10.0.0.1", 11625) not in pm.candidates(5)
            pm.update_success("10.0.0.1", 11625)
            assert ("10.0.0.1", 11625) in pm.candidates(5)


class TestFuzzHarness:
    """The gen-fuzz/fuzz CLI harness (reference: test/FuzzerImpl,
    fuzz + gen-fuzz subcommands)."""

    def test_tx_fuzzer_survives_corpus(self, tmp_path):
        from stellar_core_tpu.main.fuzzer import TransactionFuzzer
        fz = TransactionFuzzer()
        try:
            path = str(tmp_path / "input")
            interesting = 0
            for seed in range(30):
                fz.gen_fuzz(path, seed)
                if fz.inject(path):
                    interesting += 1
            # the generator emits parseable ops by construction
            assert interesting == 30
            # mutated inputs must never crash either
            raw = bytearray(open(path, "rb").read())
            for i in range(0, len(raw), 7):
                mutated = bytearray(raw)
                mutated[i] ^= 0xFF
                (tmp_path / "mut").write_bytes(bytes(mutated))
                fz.inject(str(tmp_path / "mut"))
            # node still closes ledgers
            lcl = fz.app.ledger_manager.get_last_closed_ledger_num()
            fz.app.manual_close()
            assert fz.app.ledger_manager\
                .get_last_closed_ledger_num() == lcl + 1
        finally:
            fz.shutdown()

    def test_overlay_fuzzer_survives_corpus(self, tmp_path):
        from stellar_core_tpu.main.fuzzer import OverlayFuzzer
        fz = OverlayFuzzer()
        try:
            path = str(tmp_path / "input")
            for seed in range(20):
                fz.gen_fuzz(path, seed)
                fz.inject(path)
            # both nodes alive
            for app in fz.apps:
                lcl = app.ledger_manager.get_last_closed_ledger_num()
                app.manual_close()
                assert app.ledger_manager\
                    .get_last_closed_ledger_num() == lcl + 1
        finally:
            fz.shutdown()

    def test_fuzz_cli_round_trip(self, tmp_path, capsys):
        from stellar_core_tpu.main.command_line import main
        f = str(tmp_path / "corpus")
        assert main(["gen-fuzz", f, "--mode", "tx", "--seed", "7"]) == 0
        assert main(["fuzz", f, "--mode", "tx"]) == 0
        out = capsys.readouterr().out
        assert "interesting input" in out
