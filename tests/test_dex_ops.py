"""DEX + claimable balance + sponsorship + clawback + liquidity pool
operation tests (reference behavior: OfferTests, PathPaymentTests,
ClaimableBalanceTests, RevokeSponsorshipTests, ClawbackTests,
LiquidityPoolDepositTests — core scenarios)."""

import pytest

from stellar_core_tpu.tx import tx_utils
from stellar_core_tpu.xdr.ledger_entries import (AssetType, LedgerKey,
                                                 Price, TrustLineAsset,
                                                 TrustLineFlags)
from stellar_core_tpu.xdr.results import (ManageOfferEffect,
                                          OperationResultCode)
from stellar_core_tpu.xdr.transaction import (ClaimClaimableBalanceOp,
                                              ClawbackOp,
                                              CreateClaimableBalanceOp,
                                              BeginSponsoringFutureReservesOp,
                                              LiquidityPoolDepositOp,
                                              LiquidityPoolWithdrawOp,
                                              ManageBuyOfferOp,
                                              ManageSellOfferOp,
                                              OperationType,
                                              PathPaymentStrictReceiveOp,
                                              PathPaymentStrictSendOp,
                                              RevokeSponsorshipOp,
                                              RevokeSponsorshipType,
                                              CreatePassiveSellOfferOp)
from stellar_core_tpu.xdr.ledger_entries import (Claimant, ClaimantType,
                                                 ClaimantV0, ClaimPredicate,
                                                 ClaimPredicateType)

from txtest_utils import (TestAccount, TestLedger, _op, make_asset, native,
                          op_change_trust, op_payment,
                          op_set_trustline_flags)


@pytest.fixture
def ledger():
    return TestLedger()


@pytest.fixture
def root(ledger):
    return ledger.root_account


def op_sell(selling, buying, amount, n, d, offer_id=0, source=None):
    return _op(OperationType.MANAGE_SELL_OFFER,
               ManageSellOfferOp(selling=selling, buying=buying,
                                 amount=amount, price=Price(n=n, d=d),
                                 offerID=offer_id), source)


def op_buy(selling, buying, buy_amount, n, d, offer_id=0, source=None):
    return _op(OperationType.MANAGE_BUY_OFFER,
               ManageBuyOfferOp(selling=selling, buying=buying,
                                buyAmount=buy_amount,
                                price=Price(n=n, d=d),
                                offerID=offer_id), source)


def op_passive(selling, buying, amount, n, d, source=None):
    return _op(OperationType.CREATE_PASSIVE_SELL_OFFER,
               CreatePassiveSellOfferOp(selling=selling, buying=buying,
                                        amount=amount,
                                        price=Price(n=n, d=d)), source)


def setup_issuer_and_asset(ledger, root):
    issuer = TestAccount.fresh(ledger)
    root.create(issuer, 10_000_0000000)
    issuer.sync_seq()
    usd = make_asset(b"USD", issuer.account_id)
    return issuer, usd


class TestManageOffers:
    def test_create_update_delete_offer(self, ledger, root):
        issuer, usd = setup_issuer_and_asset(ledger, root)
        alice = TestAccount.fresh(ledger)
        root.create(alice, 10_000_0000000)
        alice.sync_seq()
        assert alice.apply([op_change_trust(usd, 10**15)])
        assert issuer.apply([op_payment(alice.muxed, 1_000_0000000, usd)])

        # alice sells USD for native at 1:1
        assert alice.apply([op_sell(usd, native(), 100_0000000, 1, 1)])
        # find the created offer through the order book
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
        with LedgerTxn(ledger.root) as ltx:
            offer_le = ltx.load_best_offer(usd, native())
            assert offer_le is not None
            offer = offer_le.data.value
            assert offer.amount == 100_0000000
            offer_id = offer.offerID

        # update the amount down
        assert alice.apply([op_sell(usd, native(), 50_0000000, 1, 1,
                                    offer_id=offer_id)])
        with LedgerTxn(ledger.root) as ltx:
            offer = ltx.load_best_offer(usd, native()).data.value
            assert offer.amount == 50_0000000

        # delete
        assert alice.apply([op_sell(usd, native(), 0, 1, 1,
                                    offer_id=offer_id)])
        with LedgerTxn(ledger.root) as ltx:
            assert ltx.load_best_offer(usd, native()) is None
        # subentry count back to 1 (just the trustline)
        assert ledger.account(alice.account_id).numSubEntries == 1

    def test_offers_cross(self, ledger, root):
        issuer, usd = setup_issuer_and_asset(ledger, root)
        alice = TestAccount.fresh(ledger)
        bob = TestAccount.fresh(ledger)
        root.create(alice, 10_000_0000000)
        root.create(bob, 10_000_0000000)
        alice.sync_seq()
        bob.sync_seq()
        for acct in (alice, bob):
            assert acct.apply([op_change_trust(usd, 10**15)])
        assert issuer.apply([op_payment(alice.muxed, 1_000_0000000, usd)])

        # alice sells 100 USD at 1 XLM/USD; bob buys USD with XLM
        assert alice.apply([op_sell(usd, native(), 100_0000000, 1, 1)])
        bob_native_before = ledger.balance(bob.account_id)
        assert bob.apply([op_sell(native(), usd, 60_0000000, 1, 1)])

        # bob now holds 60 USD; alice's offer reduced to 40
        assert ledger.trustline(bob.account_id, usd).balance == 60_0000000
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
        with LedgerTxn(ledger.root) as ltx:
            offer = ltx.load_best_offer(usd, native()).data.value
            assert offer.amount == 40_0000000
        assert ledger.balance(bob.account_id) == \
            bob_native_before - 60_0000000 - 100  # amount + fee
        # alice received 60 XLM
        assert ledger.trustline(alice.account_id,
                                usd).balance == 940_0000000

    def test_buy_offer_crosses(self, ledger, root):
        issuer, usd = setup_issuer_and_asset(ledger, root)
        alice = TestAccount.fresh(ledger)
        bob = TestAccount.fresh(ledger)
        root.create(alice, 10_000_0000000)
        root.create(bob, 10_000_0000000)
        alice.sync_seq()
        bob.sync_seq()
        for acct in (alice, bob):
            assert acct.apply([op_change_trust(usd, 10**15)])
        assert issuer.apply([op_payment(alice.muxed, 1_000_0000000, usd)])
        assert alice.apply([op_sell(usd, native(), 100_0000000, 1, 1)])
        # bob wants to BUY exactly 30 USD paying XLM
        assert bob.apply([op_buy(native(), usd, 30_0000000, 1, 1)])
        assert ledger.trustline(bob.account_id, usd).balance == 30_0000000

    def test_cross_self_fails(self, ledger, root):
        issuer, usd = setup_issuer_and_asset(ledger, root)
        alice = TestAccount.fresh(ledger)
        root.create(alice, 10_000_0000000)
        alice.sync_seq()
        assert alice.apply([op_change_trust(usd, 10**15)])
        assert issuer.apply([op_payment(alice.muxed, 1_000_0000000, usd)])
        assert alice.apply([op_sell(usd, native(), 100_0000000, 1, 1)])
        # opposite side from the same account would cross itself
        assert not alice.apply([op_sell(native(), usd, 50_0000000, 1, 1)])

    def test_passive_offer_does_not_cross_equal_price(self, ledger, root):
        issuer, usd = setup_issuer_and_asset(ledger, root)
        alice = TestAccount.fresh(ledger)
        bob = TestAccount.fresh(ledger)
        root.create(alice, 10_000_0000000)
        root.create(bob, 10_000_0000000)
        alice.sync_seq()
        bob.sync_seq()
        for acct in (alice, bob):
            assert acct.apply([op_change_trust(usd, 10**15)])
        assert issuer.apply([op_payment(alice.muxed, 1_000_0000000, usd)])
        assert alice.apply([op_sell(usd, native(), 100_0000000, 1, 1)])
        # bob's passive offer at the same price must NOT cross
        assert bob.apply([op_passive(native(), usd, 50_0000000, 1, 1)])
        assert ledger.trustline(bob.account_id, usd).balance == 0
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
        with LedgerTxn(ledger.root) as ltx:
            assert ltx.load_best_offer(usd, native()) is not None
            assert ltx.load_best_offer(native(), usd) is not None


class TestPathPayments:
    def _setup_book(self, ledger, root):
        issuer, usd = setup_issuer_and_asset(ledger, root)
        mm = TestAccount.fresh(ledger)  # market maker
        root.create(mm, 10_000_0000000)
        mm.sync_seq()
        assert mm.apply([op_change_trust(usd, 10**15)])
        assert issuer.apply([op_payment(mm.muxed, 1_000_0000000, usd)])
        # mm sells USD for XLM at 1:1
        assert mm.apply([op_sell(usd, native(), 500_0000000, 1, 1)])
        return issuer, usd, mm

    def test_strict_receive_through_book(self, ledger, root):
        issuer, usd, mm = self._setup_book(ledger, root)
        alice = TestAccount.fresh(ledger)
        bob = TestAccount.fresh(ledger)
        root.create(alice, 10_000_0000000)
        root.create(bob, 10_000_0000000)
        alice.sync_seq()
        bob.sync_seq()
        assert bob.apply([op_change_trust(usd, 10**15)])
        # alice sends XLM, bob receives exactly 25 USD
        op = _op(OperationType.PATH_PAYMENT_STRICT_RECEIVE,
                 PathPaymentStrictReceiveOp(
                     sendAsset=native(), sendMax=30_0000000,
                     destination=bob.muxed, destAsset=usd,
                     destAmount=25_0000000, path=[]))
        assert alice.apply([op])
        assert ledger.trustline(bob.account_id, usd).balance == 25_0000000

    def test_strict_send_through_book(self, ledger, root):
        issuer, usd, mm = self._setup_book(ledger, root)
        alice = TestAccount.fresh(ledger)
        bob = TestAccount.fresh(ledger)
        root.create(alice, 10_000_0000000)
        root.create(bob, 10_000_0000000)
        alice.sync_seq()
        bob.sync_seq()
        assert bob.apply([op_change_trust(usd, 10**15)])
        op = _op(OperationType.PATH_PAYMENT_STRICT_SEND,
                 PathPaymentStrictSendOp(
                     sendAsset=native(), sendAmount=40_0000000,
                     destination=bob.muxed, destAsset=usd,
                     destMin=35_0000000, path=[]))
        assert alice.apply([op])
        assert ledger.trustline(bob.account_id, usd).balance == 40_0000000

    def test_over_sendmax_fails(self, ledger, root):
        issuer, usd, mm = self._setup_book(ledger, root)
        alice = TestAccount.fresh(ledger)
        bob = TestAccount.fresh(ledger)
        root.create(alice, 10_000_0000000)
        root.create(bob, 10_000_0000000)
        alice.sync_seq()
        bob.sync_seq()
        assert bob.apply([op_change_trust(usd, 10**15)])
        op = _op(OperationType.PATH_PAYMENT_STRICT_RECEIVE,
                 PathPaymentStrictReceiveOp(
                     sendAsset=native(), sendMax=10_0000000,
                     destination=bob.muxed, destAsset=usd,
                     destAmount=25_0000000, path=[]))
        assert not alice.apply([op])


def unconditional():
    return ClaimPredicate(
        ClaimPredicateType.CLAIM_PREDICATE_UNCONDITIONAL)


class TestClaimableBalances:
    def test_create_and_claim(self, ledger, root):
        alice = TestAccount.fresh(ledger)
        bob = TestAccount.fresh(ledger)
        root.create(alice, 10_000_0000000)
        root.create(bob, 10_000_0000000)
        alice.sync_seq()
        bob.sync_seq()
        op = _op(OperationType.CREATE_CLAIMABLE_BALANCE,
                 CreateClaimableBalanceOp(
                     asset=native(), amount=50_0000000,
                     claimants=[Claimant(
                         ClaimantType.CLAIMANT_TYPE_V0,
                         ClaimantV0(destination=bob.account_id,
                                    predicate=unconditional()))]))
        frame = alice.tx([op])
        assert ledger.apply_tx(frame)
        # extract balance id from result
        bid = frame.result.result.value[0].value.value.value
        bob_before = ledger.balance(bob.account_id)
        claim = _op(OperationType.CLAIM_CLAIMABLE_BALANCE,
                    ClaimClaimableBalanceOp(balanceID=bid))
        assert bob.apply([claim])
        assert ledger.balance(bob.account_id) == \
            bob_before + 50_0000000 - 100

    def test_claim_by_non_claimant_fails(self, ledger, root):
        alice = TestAccount.fresh(ledger)
        bob = TestAccount.fresh(ledger)
        eve = TestAccount.fresh(ledger)
        for a in (alice, bob, eve):
            root.create(a, 10_000_0000000)
            a.sync_seq()
        op = _op(OperationType.CREATE_CLAIMABLE_BALANCE,
                 CreateClaimableBalanceOp(
                     asset=native(), amount=50_0000000,
                     claimants=[Claimant(
                         ClaimantType.CLAIMANT_TYPE_V0,
                         ClaimantV0(destination=bob.account_id,
                                    predicate=unconditional()))]))
        frame = alice.tx([op])
        assert ledger.apply_tx(frame)
        bid = frame.result.result.value[0].value.value.value
        claim = _op(OperationType.CLAIM_CLAIMABLE_BALANCE,
                    ClaimClaimableBalanceOp(balanceID=bid))
        assert not eve.apply([claim])


class TestSponsorshipOps:
    def test_begin_end_sandwich_sponsors_account(self, ledger, root):
        sponsor = TestAccount.fresh(ledger)
        root.create(sponsor, 10_000_0000000)
        sponsor.sync_seq()
        newbie = TestAccount.fresh(ledger)
        from txtest_utils import op_create_account
        # classic sandwich: begin (sponsor) / create / end (newbie)
        begin = _op(OperationType.BEGIN_SPONSORING_FUTURE_RESERVES,
                    BeginSponsoringFutureReservesOp(
                        sponsoredID=newbie.account_id),
                    source=sponsor.muxed)
        create = op_create_account(newbie.account_id, 0)
        from stellar_core_tpu.xdr.transaction import (Operation,
                                                      _OperationBody)
        end = Operation(
            sourceAccount=newbie.muxed,
            body=_OperationBody(
                OperationType.END_SPONSORING_FUTURE_RESERVES))
        frame = sponsor.tx([begin, create, end],
                           extra_signers=[newbie.key])
        assert ledger.apply_tx(frame), frame.result
        acc = ledger.account(newbie.account_id)
        assert acc is not None and acc.balance == 0  # fully sponsored
        sp = ledger.account(sponsor.account_id)
        from stellar_core_tpu.tx.sponsorship import (num_sponsored,
                                                     num_sponsoring)
        assert num_sponsoring(sp) == 2       # account costs 2 reserves
        assert num_sponsored(acc) == 2

    def test_revoke_transfers_to_self(self, ledger, root):
        sponsor = TestAccount.fresh(ledger)
        root.create(sponsor, 10_000_0000000)
        sponsor.sync_seq()
        alice = TestAccount.fresh(ledger)
        root.create(alice, 10_000_0000000)
        alice.sync_seq()
        from txtest_utils import op_manage_data
        begin = _op(OperationType.BEGIN_SPONSORING_FUTURE_RESERVES,
                    BeginSponsoringFutureReservesOp(
                        sponsoredID=alice.account_id),
                    source=sponsor.muxed)
        md = op_manage_data(b"k", b"v", source=alice.muxed)
        from stellar_core_tpu.xdr.transaction import (Operation,
                                                      _OperationBody)
        end = Operation(
            sourceAccount=alice.muxed,
            body=_OperationBody(
                OperationType.END_SPONSORING_FUTURE_RESERVES))
        frame = sponsor.tx([begin, md, end], extra_signers=[alice.key])
        assert ledger.apply_tx(frame), frame.result
        from stellar_core_tpu.tx.sponsorship import num_sponsoring
        assert num_sponsoring(ledger.account(sponsor.account_id)) == 1

        # sponsor revokes: alice must now pay her own reserve
        key = LedgerKey.data(alice.account_id, b"k")
        revoke = _op(OperationType.REVOKE_SPONSORSHIP,
                     RevokeSponsorshipOp(
                         RevokeSponsorshipType
                         .REVOKE_SPONSORSHIP_LEDGER_ENTRY, key))
        assert sponsor.apply([revoke])
        assert num_sponsoring(ledger.account(sponsor.account_id)) == 0


class TestClawback:
    def test_clawback_flow(self, ledger, root):
        issuer = TestAccount.fresh(ledger)
        root.create(issuer, 10_000_0000000)
        issuer.sync_seq()
        from stellar_core_tpu.xdr.ledger_entries import AccountFlags
        from txtest_utils import op_set_options
        # issuer enables clawback (requires revocable too)
        assert issuer.apply([op_set_options(
            setFlags=AccountFlags.AUTH_REVOCABLE_FLAG |
            AccountFlags.AUTH_CLAWBACK_ENABLED_FLAG)])
        usd = make_asset(b"USD", issuer.account_id)
        alice = TestAccount.fresh(ledger)
        root.create(alice, 10_000_0000000)
        alice.sync_seq()
        assert alice.apply([op_change_trust(usd, 10**15)])
        assert issuer.apply([op_payment(alice.muxed, 100_0000000, usd)])
        tl = ledger.trustline(alice.account_id, usd)
        assert tl.flags & TrustLineFlags.TRUSTLINE_CLAWBACK_ENABLED_FLAG

        cb = _op(OperationType.CLAWBACK,
                 ClawbackOp(asset=usd, from_=alice.muxed,
                            amount=40_0000000))
        assert issuer.apply([cb])
        assert ledger.trustline(alice.account_id,
                                usd).balance == 60_0000000


def setup_pool_trust(ledger, root, funded_usd=1_000_0000000):
    """issuer/usd + alice with usd funds and a native/USD pool-share
    trustline (shared by the deposit/withdraw and pool-routing tiers)."""
    issuer, usd = setup_issuer_and_asset(ledger, root)
    alice = TestAccount.fresh(ledger)
    root.create(alice, 10_000_0000000)
    alice.sync_seq()
    assert alice.apply([op_change_trust(usd, 10**15)])
    assert issuer.apply([op_payment(alice.muxed, funded_usd, usd)])
    # pool-share trustline via ChangeTrust on the pool asset
    from stellar_core_tpu.xdr.transaction import (ChangeTrustAsset,
                                                  ChangeTrustOp)
    from stellar_core_tpu.xdr.ledger_entries import (
        LiquidityPoolConstantProductParameters)
    from stellar_core_tpu.tx.pool_trust import pool_id_for_params
    params = LiquidityPoolConstantProductParameters(
        assetA=native(), assetB=usd, fee=30)
    cta = ChangeTrustAsset(AssetType.ASSET_TYPE_POOL_SHARE,
                           _LPParams(params))
    op = _op(OperationType.CHANGE_TRUST,
             ChangeTrustOp(line=cta, limit=10**15))
    assert alice.apply([op]), alice
    return issuer, usd, alice, pool_id_for_params(params)


class TestLiquidityPools:
    def _setup_pool_trust(self, ledger, root):
        return setup_pool_trust(ledger, root)

    def test_deposit_and_withdraw(self, ledger, root):
        issuer, usd, alice, pool_id = self._setup_pool_trust(ledger, root)
        dep = _op(OperationType.LIQUIDITY_POOL_DEPOSIT,
                  LiquidityPoolDepositOp(
                      liquidityPoolID=pool_id,
                      maxAmountA=100_0000000, maxAmountB=100_0000000,
                      minPrice=Price(n=1, d=2), maxPrice=Price(n=2, d=1)))
        assert alice.apply([dep]), alice
        from stellar_core_tpu.tx.pool_trust import load_pool
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
        with LedgerTxn(ledger.root) as ltx:
            cp = load_pool(ltx, pool_id).data.value.body.value
            assert cp.reserveA == 100_0000000
            assert cp.reserveB == 100_0000000
            shares = cp.totalPoolShares
            assert shares == 100_0000000  # sqrt(a*b) with a==b

        wd = _op(OperationType.LIQUIDITY_POOL_WITHDRAW,
                 LiquidityPoolWithdrawOp(
                     liquidityPoolID=pool_id, amount=shares // 2,
                     minAmountA=1, minAmountB=1))
        assert alice.apply([wd])
        with LedgerTxn(ledger.root) as ltx:
            cp = load_pool(ltx, pool_id).data.value.body.value
            assert cp.reserveA == 50_0000000
            assert cp.totalPoolShares == shares - shares // 2


def _LPParams(params):
    from stellar_core_tpu.xdr.transaction import _LPParams as LPP
    from stellar_core_tpu.xdr.ledger_entries import LiquidityPoolType
    return LPP(LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT, params)
