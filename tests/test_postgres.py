"""PostgreSQL backend tests (reference: database/test/DatabaseTests.cpp
dual-backend runs).

The dialect-translation layer and the libpq binding surface are tested
unconditionally; the full node-on-postgres integration (boot, ledger
closes, restart) runs only when a server is reachable via
POSTGRES_TEST_URI — this environment ships libpq but no server, so the
integration tests SKIP LOUDLY rather than silently pass."""

import os

import pytest

from stellar_core_tpu.db.database import create_database
from stellar_core_tpu.db.postgres import translate
from stellar_core_tpu.db.libpq import PostgresError, load_libpq

PG_URI = os.environ.get("POSTGRES_TEST_URI", "")


# ------------------------------------------------------------ translation ---
def test_translate_placeholders():
    assert translate("SELECT entry FROM accounts WHERE key=?").sql == \
        "SELECT entry FROM accounts WHERE key=$1"
    assert translate(
        "SELECT a FROM t WHERE x=? AND y=? LIMIT ? OFFSET ?").sql \
        == "SELECT a FROM t WHERE x=$1 AND y=$2 LIMIT $3 OFFSET $4"


def test_translate_upsert():
    t = translate("INSERT OR REPLACE INTO accounts "
                  "(key, entry, lastmodified) VALUES (?,?,?)")
    assert t.sql == ("INSERT INTO accounts (key, entry, lastmodified) "
                     "VALUES ($1,$2,$3) ON CONFLICT (key) "
                     "DO UPDATE SET entry=EXCLUDED.entry, "
                     "lastmodified=EXCLUDED.lastmodified")
    assert not t.pre_deletes


def test_translate_upsert_composite_key():
    t = translate("INSERT OR REPLACE INTO txhistory "
                  "(txid, ledgerseq, txindex, txbody, txresult, txmeta) "
                  "VALUES (?,?,?,?,?,?)").sql
    assert "ON CONFLICT (ledgerseq, txindex)" in t
    assert "txid=EXCLUDED.txid" in t


def test_translate_upsert_all_key_columns():
    t = translate("INSERT OR REPLACE INTO ban (nodeid) VALUES (?)").sql
    assert t.endswith("ON CONFLICT (nodeid) DO NOTHING")


def test_translate_secondary_unique_predeletes():
    """sqlite OR REPLACE evicts rows conflicting on ANY unique index;
    the postgres translation must pre-delete on the secondary ones
    (ledgerheaders.ledgerseq, offers.offerid)."""
    t = translate("INSERT OR REPLACE INTO ledgerheaders "
                  "(ledgerhash, prevhash, ledgerseq, closetime, data) "
                  "VALUES (?,?,?,?,?)")
    assert len(t.pre_deletes) == 1
    dsql, idxs = t.pre_deletes[0]
    assert dsql.startswith("DELETE FROM ledgerheaders WHERE ledgerseq=$1")
    assert "NOT (ledgerhash=$2)" in dsql
    assert idxs == (2, 0)            # ledgerseq pos, ledgerhash pos
    t2 = translate("INSERT OR REPLACE INTO offers (key, entry, "
                   "lastmodified, sellerid, offerid, sellingasset, "
                   "buyingasset, pricen, priced, price) "
                   "VALUES (?,?,?,?,?,?,?,?,?,?)")
    assert t2.pre_deletes[0][1] == (4, 0)   # offerid pos, key pos


def test_translate_ddl_types():
    t = translate("CREATE TABLE IF NOT EXISTS x ("
                  "key BLOB PRIMARY KEY, n INTEGER, p REAL)").sql
    assert "BYTEA" in t and "BIGINT" in t and "DOUBLE PRECISION" in t
    assert "BLOB" not in t and "INTEGER" not in t


def test_translate_pragma_is_noop():
    assert translate("PRAGMA journal_mode=WAL").sql is None


def test_every_schema_statement_translates():
    from stellar_core_tpu.db.database import schema_statements
    for stmt in schema_statements():
        t = translate(stmt).sql
        assert t is not None and "BLOB" not in t and "?" not in t


def test_every_insert_or_replace_in_tree_has_conflict_keys():
    """Every INSERT OR REPLACE the node ever issues must be
    translatable — scan the source tree for table names."""
    import re
    import pathlib
    root = pathlib.Path(__file__).resolve().parent.parent / \
        "stellar_core_tpu"
    pat = re.compile(r"INSERT OR REPLACE INTO (\w+)")
    from stellar_core_tpu.db.database import TABLE_CONFLICT_KEYS
    tables = set()
    for p in root.rglob("*.py"):
        for chunk in pat.findall(p.read_text()):
            tables.add(chunk.lower())
    # source splits strings: also check the known-SQL builders directly
    assert tables, "scan found no INSERT OR REPLACE statements"
    missing = tables - set(TABLE_CONFLICT_KEYS)
    assert not missing, f"tables without conflict keys: {missing}"


# ---------------------------------------------------------------- binding ---
def test_libpq_loads():
    lib = load_libpq()
    assert lib is not None


def test_connect_failure_is_clean():
    from stellar_core_tpu.db.postgres import PostgresDatabase
    with pytest.raises(PostgresError, match="connection failed"):
        PostgresDatabase(
            "postgresql://nouser@127.0.0.1:1/nodb?connect_timeout=1")


def test_factory_selects_backend():
    from stellar_core_tpu.main import get_test_config
    cfg = get_test_config()
    db = create_database(cfg)
    assert type(db).__name__ == "Database"
    db.close()
    cfg.DATABASE = "postgresql://x@127.0.0.1:1/y?connect_timeout=1"
    with pytest.raises(PostgresError):
        create_database(cfg)
    cfg.DATABASE = "mysql://nope"
    with pytest.raises(ValueError, match="unsupported DATABASE"):
        create_database(cfg)


# -------------------------------------------------------------- integration ---
needs_pg = pytest.mark.skipif(
    not PG_URI, reason="POSTGRES_TEST_URI not set — no postgres server "
    "in this environment; integration skipped LOUDLY")


@needs_pg
def test_node_boots_and_closes_ledgers_on_postgres():
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock
    import test_standalone_app as m1

    cfg = get_test_config()
    cfg.DATABASE = PG_URI
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg,
                             new_db=True)
    app.start()
    try:
        master = m1.master_account(app)
        dest = m1.new_account_key(app, 1)
        from txtest_utils import op_create_account
        frame = master.tx([op_create_account(dest.public_key(), 10**9)])
        r = m1.submit(app, frame)
        assert r["status"] == "PENDING"
        app.manual_close()
        lcl = app.ledger_manager.get_last_closed_ledger_num()
        assert lcl >= 2
    finally:
        app.shutdown()


@needs_pg
def test_restart_recovers_lcl_on_postgres():
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock
    cfg = get_test_config()
    cfg.DATABASE = PG_URI
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg,
                             new_db=True)
    app.start()
    app.manual_close()
    lcl = app.ledger_manager.get_last_closed_ledger_num()
    lcl_hash = app.ledger_manager.get_last_closed_ledger_hash()
    app.shutdown()

    app2 = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app2.start()
    try:
        assert app2.ledger_manager.get_last_closed_ledger_num() == lcl
        assert app2.ledger_manager.get_last_closed_ledger_hash() == lcl_hash
    finally:
        app2.shutdown()
