"""PostgreSQL backend tests (reference: database/test/DatabaseTests.cpp
dual-backend runs).

The dialect-translation layer and the libpq binding surface are tested
unconditionally. The integration tier (connect, prepared statements,
transactions, node boot + ledger closes, restart) targets a real server
when POSTGRES_TEST_URI is set; otherwise it runs against the in-repo
wire-protocol stub (db/pg_stub.py), so the binding's network paths are
exercised in every environment — note stub runs are protocol-level
coverage, not real-postgres coverage (VERDICT r02 #8)."""

import os

import pytest

from stellar_core_tpu.db.database import create_database
from stellar_core_tpu.db.postgres import translate
from stellar_core_tpu.db.libpq import PostgresError, load_libpq

PG_URI = os.environ.get("POSTGRES_TEST_URI", "")


# ------------------------------------------------------------ translation ---
def test_translate_placeholders():
    assert translate("SELECT entry FROM accounts WHERE key=?").sql == \
        "SELECT entry FROM accounts WHERE key=$1"
    assert translate(
        "SELECT a FROM t WHERE x=? AND y=? LIMIT ? OFFSET ?").sql \
        == "SELECT a FROM t WHERE x=$1 AND y=$2 LIMIT $3 OFFSET $4"


def test_translate_upsert():
    t = translate("INSERT OR REPLACE INTO accounts "
                  "(key, entry, lastmodified) VALUES (?,?,?)")
    assert t.sql == ("INSERT INTO accounts (key, entry, lastmodified) "
                     "VALUES ($1,$2,$3) ON CONFLICT (key) "
                     "DO UPDATE SET entry=EXCLUDED.entry, "
                     "lastmodified=EXCLUDED.lastmodified")
    assert not t.pre_deletes


def test_translate_upsert_composite_key():
    t = translate("INSERT OR REPLACE INTO txhistory "
                  "(txid, ledgerseq, txindex, txbody, txresult, txmeta) "
                  "VALUES (?,?,?,?,?,?)").sql
    assert "ON CONFLICT (ledgerseq, txindex)" in t
    assert "txid=EXCLUDED.txid" in t


def test_translate_upsert_all_key_columns():
    t = translate("INSERT OR REPLACE INTO ban (nodeid) VALUES (?)").sql
    assert t.endswith("ON CONFLICT (nodeid) DO NOTHING")


def test_translate_secondary_unique_predeletes():
    """sqlite OR REPLACE evicts rows conflicting on ANY unique index;
    the postgres translation must pre-delete on the secondary ones
    (ledgerheaders.ledgerseq, offers.offerid)."""
    t = translate("INSERT OR REPLACE INTO ledgerheaders "
                  "(ledgerhash, prevhash, ledgerseq, closetime, data) "
                  "VALUES (?,?,?,?,?)")
    assert len(t.pre_deletes) == 1
    dsql, idxs = t.pre_deletes[0]
    assert dsql.startswith("DELETE FROM ledgerheaders WHERE ledgerseq=$1")
    assert "NOT (ledgerhash=$2)" in dsql
    assert idxs == (2, 0)            # ledgerseq pos, ledgerhash pos
    t2 = translate("INSERT OR REPLACE INTO offers (key, entry, "
                   "lastmodified, sellerid, offerid, sellingasset, "
                   "buyingasset, pricen, priced, price) "
                   "VALUES (?,?,?,?,?,?,?,?,?,?)")
    assert t2.pre_deletes[0][1] == (4, 0)   # offerid pos, key pos


def test_translate_ddl_types():
    t = translate("CREATE TABLE IF NOT EXISTS x ("
                  "key BLOB PRIMARY KEY, n INTEGER, p REAL)").sql
    assert "BYTEA" in t and "BIGINT" in t and "DOUBLE PRECISION" in t
    assert "BLOB" not in t and "INTEGER" not in t


def test_translate_pragma_is_noop():
    assert translate("PRAGMA journal_mode=WAL").sql is None


def test_every_schema_statement_translates():
    from stellar_core_tpu.db.database import schema_statements
    for stmt in schema_statements():
        t = translate(stmt).sql
        assert t is not None and "BLOB" not in t and "?" not in t


def test_every_insert_or_replace_in_tree_has_conflict_keys():
    """Every INSERT OR REPLACE the node ever issues must be
    translatable — scan the source tree for table names."""
    import re
    import pathlib
    root = pathlib.Path(__file__).resolve().parent.parent / \
        "stellar_core_tpu"
    pat = re.compile(r"INSERT OR REPLACE INTO (\w+)")
    from stellar_core_tpu.db.database import TABLE_CONFLICT_KEYS
    tables = set()
    for p in root.rglob("*.py"):
        for chunk in pat.findall(p.read_text()):
            tables.add(chunk.lower())
    # source splits strings: also check the known-SQL builders directly
    assert tables, "scan found no INSERT OR REPLACE statements"
    missing = tables - set(TABLE_CONFLICT_KEYS)
    assert not missing, f"tables without conflict keys: {missing}"


# ---------------------------------------------------------------- binding ---
def test_libpq_loads():
    lib = load_libpq()
    assert lib is not None


def test_connect_failure_is_clean():
    from stellar_core_tpu.db.postgres import PostgresDatabase
    with pytest.raises(PostgresError, match="connection failed"):
        PostgresDatabase(
            "postgresql://nouser@127.0.0.1:1/nodb?connect_timeout=1")


def test_factory_selects_backend():
    from stellar_core_tpu.main import get_test_config
    cfg = get_test_config()
    db = create_database(cfg)
    assert type(db).__name__ == "Database"
    db.close()
    cfg.DATABASE = "postgresql://x@127.0.0.1:1/y?connect_timeout=1"
    with pytest.raises(PostgresError):
        create_database(cfg)
    cfg.DATABASE = "mysql://nope"
    with pytest.raises(ValueError, match="unsupported DATABASE"):
        create_database(cfg)


# -------------------------------------------------------------- integration ---
# POSTGRES_TEST_URI targets a real server when one exists; otherwise the
# hermetic wire-protocol stub (db/pg_stub.py) serves the same tests so
# the libpq binding's connect/prepared/transaction paths always run
# (VERDICT r02 #8 — previously these skipped loudly in this image).


@pytest.fixture
def pg_uri():
    if PG_URI:
        yield PG_URI
        return
    from stellar_core_tpu.db.pg_stub import PGStubServer
    srv = PGStubServer().start()   # fresh store per test, like new-db
    try:
        yield srv.url()
    finally:
        srv.stop()


def test_stub_binding_roundtrip(pg_uri):
    """connect → DDL → prepared upserts → typed reads → transactions,
    straight through libpq."""
    from stellar_core_tpu.db.database import TABLE_CONFLICT_KEYS
    from stellar_core_tpu.db.postgres import PostgresDatabase
    probe_added = "probe" not in TABLE_CONFLICT_KEYS
    TABLE_CONFLICT_KEYS.setdefault("probe", ("key",))
    db = PostgresDatabase(pg_uri)
    try:
        db.execute("CREATE TABLE IF NOT EXISTS probe "
                   "(key BLOB PRIMARY KEY, num INTEGER, txt TEXT)")
        db.executemany(
            "INSERT OR REPLACE INTO probe (key, num, txt) VALUES (?,?,?)",
            [(bytes([i]) * 8, i * 10, f"row{i}") for i in range(5)])
        rows = db.execute(
            "SELECT key, num, txt FROM probe ORDER BY num")
        got = rows.fetchall()
        assert got[0] == (b"\x00" * 8, 0, "row0")
        assert got[4] == (b"\x04" * 8, 40, "row4")
        # 8-byte BLOB key equality must survive the binary protocol
        one = db.execute("SELECT num FROM probe WHERE key=?",
                         (b"\x03" * 8,)).fetchone()
        assert one == (30,)
        # upsert updates in place
        db.executemany(
            "INSERT OR REPLACE INTO probe (key, num, txt) VALUES (?,?,?)",
            [(b"\x03" * 8, 77, "updated")])
        assert db.execute("SELECT num, txt FROM probe WHERE key=?",
                          (b"\x03" * 8,)).fetchone() == (77, "updated")
        # transaction rollback
        with pytest.raises(RuntimeError):
            with db.transaction():
                db.execute("UPDATE probe SET num=? WHERE key=?",
                           (999, b"\x03" * 8))
                raise RuntimeError("boom")
        assert db.execute("SELECT num FROM probe WHERE key=?",
                          (b"\x03" * 8,)).fetchone() == (77,)
        # transaction commit
        with db.transaction():
            db.execute("UPDATE probe SET num=? WHERE key=?",
                       (1000, b"\x03" * 8))
        assert db.execute("SELECT num FROM probe WHERE key=?",
                          (b"\x03" * 8,)).fetchone() == (1000,)
        # a NULL in the first row must not drop the OTHER params'
        # declared OIDs (per-element OID 0 in Parse): the 8-byte BYTEA
        # key would be misdecoded as INT8 and the UPDATE silently
        # match nothing
        db.executemany("UPDATE probe SET txt=? WHERE key=?",
                       [(None, b"\x03" * 8), ("two", b"\x02" * 8)])
        assert db.execute("SELECT txt FROM probe WHERE key=?",
                          (b"\x03" * 8,)).fetchone() == (None,)
        assert db.execute("SELECT txt FROM probe WHERE key=?",
                          (b"\x02" * 8,)).fetchone() == ("two",)
        # a position NULL in the whole first batch must get its OID
        # declared by a later batch's value (re-prepare), not stay
        # guess-decoded forever — "12345678" is 8 bytes, the shape the
        # stub would misread as INT8 on an undeclared position
        db.executemany("UPDATE probe SET txt=? WHERE key=?",
                       [(None, b"\x00" * 8), (None, b"\x01" * 8)])
        db.executemany("UPDATE probe SET txt=? WHERE key=?",
                       [("12345678", b"\x01" * 8)])
        assert db.execute("SELECT txt FROM probe WHERE key=?",
                          (b"\x01" * 8,)).fetchone() == ("12345678",)
    finally:
        db.close()
        if probe_added:
            TABLE_CONFLICT_KEYS.pop("probe", None)


def test_node_boots_and_closes_ledgers_on_postgres(pg_uri):
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock
    import test_standalone_app as m1

    cfg = get_test_config()
    cfg.DATABASE = pg_uri
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg,
                             new_db=True)
    app.start()
    try:
        master = m1.master_account(app)
        from stellar_core_tpu.crypto.keys import SecretKey
        from stellar_core_tpu.xdr.types import PublicKey
        from txtest_utils import op_create_account
        dest = SecretKey.from_seed(b"\x31" * 32)
        frame = master.tx([op_create_account(
            PublicKey.ed25519(dest.public_key().raw), 10**9)])
        r = m1.submit(app, frame)
        assert r["status"] == "PENDING"
        app.manual_close()
        lcl = app.ledger_manager.get_last_closed_ledger_num()
        assert lcl >= 2
    finally:
        app.shutdown()


def test_restart_recovers_lcl_on_postgres(pg_uri, tmp_path):
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock
    cfg = get_test_config()
    cfg.DATABASE = pg_uri
    # buckets must outlive the first Application for assume-state
    cfg.BUCKET_DIR_PATH = str(tmp_path / "buckets")
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg,
                             new_db=True)
    app.start()
    app.manual_close()
    lcl = app.ledger_manager.get_last_closed_ledger_num()
    lcl_hash = app.ledger_manager.get_last_closed_ledger_hash()
    app.shutdown()

    app2 = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app2.start()
    try:
        assert app2.ledger_manager.get_last_closed_ledger_num() == lcl
        assert app2.ledger_manager.get_last_closed_ledger_hash() == lcl_hash
    finally:
        app2.shutdown()
