"""LedgerTxn child/parent edge cases.

Each test names the reference behavior it mirrors from
src/ledger/test/LedgerTxnTests.cpp — the nesting, sealing, and
lifecycle-fold edges VERDICT round-1 weak #6 called out as uncovered;
also pins the round-2 copy-discipline contracts (first-touch `_prev`
snapshots, shared reads, one-clone loads)."""

import pytest

from stellar_core_tpu.db import Database
from stellar_core_tpu.ledger import (InMemoryLedgerTxnRoot, LedgerTxn,
                                     LedgerTxnRoot)
from stellar_core_tpu.util.checks import AssertionFailed
from stellar_core_tpu.xdr.ledger import LedgerEntryChangeType

from test_ledger_txn import _account_entry, _acc_id, _offer_entry


@pytest.fixture(params=["memory", "sql"])
def root(request):
    if request.param == "memory":
        return InMemoryLedgerTxnRoot()
    db = Database(":memory:")
    db.initialize()
    return LedgerTxnRoot(db)


def key_of(n):
    from stellar_core_tpu.xdr.ledger_entries import LedgerKey
    return LedgerKey.account(_acc_id(n))


# ----------------------------------------------------- visibility through --
def test_child_sees_parent_uncommitted_create(root):
    """LedgerTxnTests 'create then load in child'."""
    with LedgerTxn(root) as parent:
        parent.create(_account_entry(1))
        with LedgerTxn(parent) as child:
            le = child.load(key_of(1))
            assert le is not None and le.data.value.balance == 1000
            child.rollback()
        parent.rollback()


def test_grandchild_sees_through_two_levels(root):
    with LedgerTxn(root) as l1:
        l1.create(_account_entry(1, balance=111))
        with LedgerTxn(l1) as l2:
            le = l2.load(key_of(1))
            le.data.value.balance = 222
            with LedgerTxn(l2) as l3:
                assert l3.load_without_record(
                    key_of(1)).data.value.balance == 222
                l3.rollback()
            l2.commit()
        assert l1.load_without_record(key_of(1)).data.value.balance == 222
        l1.rollback()


def test_erase_in_child_hides_from_grandchild(root):
    """LedgerTxnTests 'erase visibility': an erase at one level makes
    the entry absent below it, while the level above still sees it."""
    with LedgerTxn(root) as l1:
        l1.create(_account_entry(1))
        with LedgerTxn(l1) as l2:
            l2.erase(key_of(1))
            with LedgerTxn(l2) as l3:
                assert not l3.entry_exists(key_of(1))
                assert l3.load(key_of(1)) is None
                l3.rollback()
            l2.rollback()
        assert l1.entry_exists(key_of(1))
        l1.rollback()


def test_child_mutation_invisible_until_commit(root):
    with LedgerTxn(root) as l1:
        l1.create(_account_entry(1, balance=100))
        l1.commit()
    with LedgerTxn(root) as l1:
        with LedgerTxn(l1) as l2:
            l2.load(key_of(1)).data.value.balance = 999
            # parent is sealed while the child is open; after rollback
            # the parent must see the ORIGINAL value
            l2.rollback()
        assert l1.load_without_record(key_of(1)).data.value.balance == 100
        l1.rollback()


# ---------------------------------------------------------- lifecycle fold --
def test_create_after_erase_folds_to_update(root):
    """erase+create of an existing key at one level = UPDATE vs the
    parent (LedgerTxnTests erase/create annihilation rules)."""
    with LedgerTxn(root) as l1:
        l1.create(_account_entry(1, balance=1))
        l1.commit()
    with LedgerTxn(root) as l1:
        l1.erase(key_of(1))
        l1.create(_account_entry(1, balance=2))
        changes = l1.get_changes()
        kinds = [c.disc for c in changes]
        assert LedgerEntryChangeType.LEDGER_ENTRY_STATE in kinds
        assert LedgerEntryChangeType.LEDGER_ENTRY_UPDATED in kinds
        assert LedgerEntryChangeType.LEDGER_ENTRY_CREATED not in kinds
        delta = l1.get_delta()
        assert len(delta.live) == 1 and not delta.init and not delta.dead
        l1.rollback()


def test_child_create_parent_erase_folds_to_noop(root):
    """create in child + erase in a later child of an entry absent in
    the root folds away entirely at the parent."""
    with LedgerTxn(root) as l1:
        with LedgerTxn(l1) as l2:
            l2.create(_account_entry(7))
            l2.commit()
        with LedgerTxn(l1) as l2:
            l2.erase(key_of(7))
            l2.commit()
        delta = l1.get_delta()
        assert not delta.init and not delta.live and not delta.dead
        l1.rollback()


def test_erase_then_create_across_child_levels(root):
    with LedgerTxn(root) as l1:
        l1.create(_account_entry(1, balance=5))
        l1.commit()
    with LedgerTxn(root) as l1:
        with LedgerTxn(l1) as l2:
            l2.erase(key_of(1))
            l2.commit()
        with LedgerTxn(l1) as l2:
            l2.create(_account_entry(1, balance=6))
            l2.commit()
        delta = l1.get_delta()
        assert len(delta.live) == 1          # net UPDATE vs root
        assert delta.live[0].data.value.balance == 6
        l1.rollback()


def test_prev_snapshot_is_first_touch_value(root):
    """get_changes' STATE entry is the value at FIRST touch, even after
    repeated loads and child commits (the _prev contract)."""
    with LedgerTxn(root) as l1:
        l1.create(_account_entry(1, balance=10))
        l1.commit()
    with LedgerTxn(root) as l1:
        l1.load(key_of(1)).data.value.balance = 20
        l1.load(key_of(1)).data.value.balance = 30
        with LedgerTxn(l1) as l2:
            l2.load(key_of(1)).data.value.balance = 40
            l2.commit()
        changes = l1.get_changes()
        state = [c for c in changes
                 if c.disc == LedgerEntryChangeType.LEDGER_ENTRY_STATE][0]
        assert state.value.data.value.balance == 10
        upd = [c for c in changes
               if c.disc == LedgerEntryChangeType.LEDGER_ENTRY_UPDATED][0]
        assert upd.value.data.value.balance == 40
        l1.rollback()


# ------------------------------------------------------- sealing / misuse --
def test_parent_load_while_child_open_raises(root):
    with LedgerTxn(root) as l1:
        l1.create(_account_entry(1))
        child = LedgerTxn(l1)
        with pytest.raises(AssertionFailed, match="sealed"):
            l1.load(key_of(1))
        child.rollback()
        l1.rollback()


def test_two_open_children_rejected(root):
    with LedgerTxn(root) as l1:
        c1 = LedgerTxn(l1)
        with pytest.raises(AssertionFailed, match="already has"):
            LedgerTxn(l1)
        c1.rollback()
        l1.rollback()


def test_operations_after_commit_raise(root):
    l1 = LedgerTxn(root)
    l1.create(_account_entry(1))
    l1.commit()
    with pytest.raises(AssertionFailed, match="closed"):
        l1.load(key_of(1))
    with pytest.raises(AssertionFailed, match="closed"):
        l1.commit()


def test_rollback_cascades_to_open_child(root):
    """Rolling back a parent rolls back its open child first
    (LedgerTxnTests nested rollback)."""
    l1 = LedgerTxn(root)
    l2 = LedgerTxn(l1)
    l2.create(_account_entry(1))
    l1.rollback()
    assert not l2._open
    with LedgerTxn(root) as fresh:
        assert not fresh.entry_exists(key_of(1))
        fresh.rollback()


def test_create_duplicate_and_erase_missing_raise(root):
    with LedgerTxn(root) as l1:
        l1.create(_account_entry(1))
        with pytest.raises(AssertionFailed, match="already exists"):
            l1.create(_account_entry(1))
        with pytest.raises(AssertionFailed, match="does not exist"):
            l1.erase(key_of(9))
        l1.rollback()


def test_context_manager_rolls_back_on_exception(root):
    with pytest.raises(RuntimeError):
        with LedgerTxn(root) as l1:
            l1.create(_account_entry(1))
            raise RuntimeError("boom")
    with LedgerTxn(root) as l1:
        assert not l1.entry_exists(key_of(1))
        l1.rollback()


# ------------------------------------------------------------------ header --
def test_header_only_propagates_when_loaded(root):
    with LedgerTxn(root) as l1:
        before = l1.get_header().ledgerSeq
        with LedgerTxn(l1) as l2:
            l2.commit()                       # header untouched
        assert l1.get_header().ledgerSeq == before
        with LedgerTxn(l1) as l2:
            l2.load_header().ledgerSeq = before + 7
            l2.commit()
        assert l1.get_header().ledgerSeq == before + 7
        l1.rollback()


def test_child_header_clone_isolated_until_commit(root):
    with LedgerTxn(root) as l1:
        with LedgerTxn(l1) as l2:
            h = l2.load_header()
            h.ledgerSeq = 999
            assert l1.get_header().ledgerSeq != 999
            l2.rollback()
        assert l1.get_header().ledgerSeq != 999
        l1.rollback()


# ------------------------------------------------------------- order book --
def test_best_offer_prefers_child_improvement(root):
    """A better offer created in the child wins over the root's book
    (loadBestOffer with delta overlay)."""
    with LedgerTxn(root) as l1:
        l1.create(_offer_entry(1, 1, n=2, d=1))
        l1.commit()
    with LedgerTxn(root) as l1:
        l1.create(_offer_entry(2, 2, n=1, d=1))      # cheaper
        from stellar_core_tpu.xdr.ledger_entries import Asset
        best = l1.load_best_offer(Asset.native(), Asset.native())
        assert best.data.value.offerID == 2
        l1.rollback()


def test_best_offer_skips_child_erased_root_offer(root):
    from stellar_core_tpu.xdr.ledger_entries import Asset, LedgerKey
    with LedgerTxn(root) as l1:
        l1.create(_offer_entry(1, 1, n=1, d=1))
        l1.create(_offer_entry(1, 2, n=3, d=1))
        l1.commit()
    with LedgerTxn(root) as l1:
        l1.erase(LedgerKey.offer(_acc_id(1), 1))
        best = l1.load_best_offer(Asset.native(), Asset.native())
        assert best.data.value.offerID == 2
        l1.rollback()


def test_best_offer_sees_child_price_worsening(root):
    """Modifying an offer in the child must override the root's copy in
    the comparison (the exclude-set of the SQL fast path)."""
    from stellar_core_tpu.xdr.ledger_entries import Asset, LedgerKey, Price
    with LedgerTxn(root) as l1:
        l1.create(_offer_entry(1, 1, n=1, d=1))
        l1.create(_offer_entry(1, 2, n=2, d=1))
        l1.commit()
    with LedgerTxn(root) as l1:
        le = l1.load(LedgerKey.offer(_acc_id(1), 1))
        le.data.value.price = Price(n=5, d=1)         # now worst
        best = l1.load_best_offer(Asset.native(), Asset.native())
        assert best.data.value.offerID == 2
        l1.rollback()


def test_offers_by_account_overlays_deltas(root):
    from stellar_core_tpu.xdr.ledger_entries import LedgerKey
    with LedgerTxn(root) as l1:
        l1.create(_offer_entry(1, 1, n=1, d=1))
        l1.create(_offer_entry(2, 2, n=1, d=1))
        l1.commit()
    with LedgerTxn(root) as l1:
        l1.erase(LedgerKey.offer(_acc_id(1), 1))
        l1.create(_offer_entry(1, 3, n=1, d=1))
        offers = l1.load_offers_by_account(_acc_id(1))
        assert {o.data.value.offerID for o in offers} == {3}
        l1.rollback()


def test_load_without_record_does_not_join_delta(root):
    with LedgerTxn(root) as l1:
        l1.create(_account_entry(1))
        l1.commit()
    with LedgerTxn(root) as l1:
        assert l1.load_without_record(key_of(1)) is not None
        assert not l1.get_changes()
        assert not l1._delta
        l1.rollback()


def test_backend_equivalence_random_sequence():
    """The same op sequence yields identical final state on the
    in-memory and SQL roots (the dual-backend sweep of
    LedgerTxnTests)."""
    import random

    def run(root):
        rng = random.Random(42)
        with LedgerTxn(root) as l1:
            live = set()
            for step in range(120):
                n = rng.randint(1, 8)
                action = rng.random()
                if n not in live and action < 0.6:
                    l1.create(_account_entry(n, balance=step))
                    live.add(n)
                elif n in live and action < 0.8:
                    l1.load(key_of(n)).data.value.balance = step
                elif n in live:
                    l1.erase(key_of(n))
                    live.discard(n)
            out = {n: l1.load_without_record(
                key_of(n)).data.value.balance for n in live}
            l1.commit()
        return out

    mem = run(InMemoryLedgerTxnRoot())
    db = Database(":memory:")
    db.initialize()
    sql = run(LedgerTxnRoot(db))
    assert mem == sql and mem
