"""XDR layer tests: runtime round-trip, strictness, and cross-checks against
the `stellar_sdk`-free hand-computed encodings.

Mirrors the reference's XDR round-trip coverage (xdrpp's own tests plus
util/test/XDRStreamTests.cpp) and adds strict-decode cases.
"""

import hashlib

import pytest

from stellar_core_tpu.xdr import (
    Reader, Writer, XdrError, xdr_sha256,
)
from stellar_core_tpu.xdr.runtime import (
    Array, Int32, Int64, Opaque, Optional, Struct, Uint32, Uint64, Union,
    VarArray, VarOpaque,
)
from stellar_core_tpu.xdr.types import (
    EnvelopeType, PublicKey, PublicKeyType, SignerKey, SignerKeyType,
)
from stellar_core_tpu.xdr.ledger_entries import (
    AccountEntry, Asset, AssetType, ClaimPredicate, ClaimPredicateType,
    LedgerEntry, LedgerEntryType, LedgerKey, Price, TrustLineEntry,
    ledger_entry_key,
)
from stellar_core_tpu.xdr.transaction import (
    DecoratedSignature, Memo, MemoType, MuxedAccount, Operation,
    OperationType, PaymentOp, Preconditions, PreconditionType, TimeBounds,
    Transaction, TransactionEnvelope, TransactionV1Envelope,
    TransactionSignaturePayload,
)
from stellar_core_tpu.xdr.results import (
    OperationResult, OperationResultCode, TransactionResult,
    TransactionResultCode,
)
from stellar_core_tpu.xdr.ledger import (
    BucketEntry, BucketEntryType, LedgerHeader, StellarValue, TransactionSet,
)
from stellar_core_tpu.xdr.scp import (
    SCPBallot, SCPEnvelope, SCPQuorumSet, SCPStatement, SCPStatementType,
)
from stellar_core_tpu.xdr.overlay import (
    AuthenticatedMessage, Hello, MessageType, StellarMessage,
)


def _pk(b: int) -> PublicKey:
    return PublicKey.ed25519(bytes([b]) * 32)


class TestPrimitives:
    def test_padding(self):
        w = Writer()
        VarOpaque().pack(w, b"abcde")
        assert bytes(w.buf) == b"\x00\x00\x00\x05abcde\x00\x00\x00"

    def test_nonzero_padding_rejected(self):
        r = Reader(b"\x00\x00\x00\x01a\x00\x00\x01")
        with pytest.raises(XdrError):
            VarOpaque().unpack(r)

    def test_int_ranges(self):
        w = Writer()
        with pytest.raises(XdrError):
            w.u32(-1)
        with pytest.raises(XdrError):
            w.i32(2**31)
        w.i32(-1)
        assert bytes(w.buf) == b"\xff\xff\xff\xff"

    def test_bool_strict(self):
        from stellar_core_tpu.xdr.runtime import Bool
        with pytest.raises(XdrError):
            Bool.unpack(Reader(b"\x00\x00\x00\x02"))

    def test_optional(self):
        t = Optional(Uint32)
        w = Writer()
        t.pack(w, None)
        t.pack(w, 7)
        r = Reader(bytes(w.buf))
        assert t.unpack(r) is None
        assert t.unpack(r) == 7

    def test_var_array_max(self):
        t = VarArray(Uint32, 2)
        with pytest.raises(XdrError):
            t.pack(Writer(), [1, 2, 3])


class TestStructUnion:
    def test_public_key_roundtrip(self):
        pk = _pk(3)
        b = pk.to_bytes()
        assert b[:4] == b"\x00\x00\x00\x00"  # PUBLIC_KEY_TYPE_ED25519
        assert len(b) == 36
        assert PublicKey.from_bytes(b) == pk

    def test_unknown_enum_rejected(self):
        with pytest.raises(XdrError):
            PublicKey.from_bytes(b"\x00\x00\x00\x09" + b"\x00" * 32)

    def test_trailing_bytes_rejected(self):
        pk = _pk(1)
        with pytest.raises(XdrError):
            PublicKey.from_bytes(pk.to_bytes() + b"\x00")

    def test_void_arm(self):
        a = Asset.native()
        assert a.to_bytes() == b"\x00\x00\x00\x00"
        assert Asset.from_bytes(a.to_bytes()) == a

    def test_recursive_predicate(self):
        p = ClaimPredicate(
            ClaimPredicateType.CLAIM_PREDICATE_NOT,
            ClaimPredicate(ClaimPredicateType.CLAIM_PREDICATE_UNCONDITIONAL))
        assert ClaimPredicate.from_bytes(p.to_bytes()) == p

    def test_recursive_qset(self):
        q = SCPQuorumSet(
            threshold=2,
            validators=[_pk(1), _pk(2)],
            innerSets=[SCPQuorumSet(threshold=1, validators=[_pk(3)],
                                    innerSets=[])])
        assert SCPQuorumSet.from_bytes(q.to_bytes()) == q

    def test_struct_defaults(self):
        e = AccountEntry()
        assert e.balance == 0
        assert e.signers == []
        assert AccountEntry.from_bytes(e.to_bytes()) == e

    def test_canonical_ordering(self):
        a, b = _pk(1), _pk(2)
        assert a < b
        assert sorted([b, a]) == [a, b]


class TestTransaction:
    def _payment_tx(self) -> Transaction:
        return Transaction(
            sourceAccount=MuxedAccount.from_ed25519(b"\x01" * 32),
            fee=100,
            seqNum=7,
            cond=Preconditions(PreconditionType.PRECOND_TIME,
                               TimeBounds(minTime=0, maxTime=0)),
            memo=Memo(MemoType.MEMO_TEXT, b"hello"),
            operations=[Operation(
                sourceAccount=None,
                body=__import__(
                    "stellar_core_tpu.xdr.transaction",
                    fromlist=["_OperationBody"])._OperationBody(
                        OperationType.PAYMENT,
                        PaymentOp(
                            destination=MuxedAccount.from_ed25519(b"\x02" * 32),
                            asset=Asset.native(),
                            amount=1000)))],
        )

    def test_envelope_roundtrip(self):
        tx = self._payment_tx()
        env = TransactionEnvelope(
            EnvelopeType.ENVELOPE_TYPE_TX,
            TransactionV1Envelope(
                tx=tx,
                signatures=[DecoratedSignature(hint=b"\x00" * 4,
                                               signature=b"\x01" * 64)]))
        assert TransactionEnvelope.from_bytes(env.to_bytes()) == env

    def test_signature_payload_hash_domain(self):
        tx = self._payment_tx()
        net = hashlib.sha256(b"test network").digest()
        from stellar_core_tpu.xdr.transaction import _TaggedTransaction
        payload = TransactionSignaturePayload(
            networkId=net,
            taggedTransaction=_TaggedTransaction(
                EnvelopeType.ENVELOPE_TYPE_TX, tx))
        h = xdr_sha256(payload)
        # envelope-type discriminant must land right after the network id
        assert payload.to_bytes()[:32] == net
        assert payload.to_bytes()[32:36] == b"\x00\x00\x00\x02"
        assert len(h) == 32

    def test_tx_result(self):
        tr = TransactionResult(
            feeCharged=100,
            result=__import__(
                "stellar_core_tpu.xdr.results",
                fromlist=["_TxResultResult"])._TxResultResult(
                    TransactionResultCode.txSUCCESS,
                    [OperationResult(OperationResultCode.opBAD_AUTH)]),
        )
        assert TransactionResult.from_bytes(tr.to_bytes()) == tr


class TestLedger:
    def test_header_roundtrip(self):
        h = LedgerHeader(ledgerSeq=5, ledgerVersion=19)
        assert LedgerHeader.from_bytes(h.to_bytes()) == h
        assert len(xdr_sha256(h)) == 32

    def test_bucket_entry_meta_negative_disc(self):
        from stellar_core_tpu.xdr.ledger import BucketMetadata
        be = BucketEntry(BucketEntryType.METAENTRY,
                         BucketMetadata(ledgerVersion=11))
        assert be.to_bytes()[:4] == b"\xff\xff\xff\xff"
        assert BucketEntry.from_bytes(be.to_bytes()) == be

    def test_ledger_entry_key(self):
        e = LedgerEntry()
        e.data = type(e.data)(LedgerEntryType.ACCOUNT,
                              AccountEntry(accountID=_pk(9)))
        k = ledger_entry_key(e)
        assert k.disc == LedgerEntryType.ACCOUNT
        assert k.value.accountID == _pk(9)


class TestOverlayScp:
    def test_scp_envelope(self):
        st = SCPStatement(nodeID=_pk(1), slotIndex=42)
        env = SCPEnvelope(statement=st, signature=b"\x05" * 64)
        assert SCPEnvelope.from_bytes(env.to_bytes()) == env

    def test_stellar_message_txset(self):
        m = StellarMessage(MessageType.GET_TX_SET, b"\x07" * 32)
        assert StellarMessage.from_bytes(m.to_bytes()) == m

    def test_authenticated_message(self):
        from stellar_core_tpu.xdr.overlay import _AuthenticatedMessageV0
        from stellar_core_tpu.xdr.types import HmacSha256Mac
        am = AuthenticatedMessage(
            0, _AuthenticatedMessageV0(
                sequence=9,
                message=StellarMessage(MessageType.GET_PEERS),
                mac=HmacSha256Mac(mac=b"\x01" * 32)))
        assert AuthenticatedMessage.from_bytes(am.to_bytes()) == am


def test_clone_is_deep_and_equal():
    """Struct/Union.clone(): byte-identical, fully independent copies
    (the LedgerTxn aliasing-protection path uses this instead of a
    serialize/parse roundtrip)."""
    import random
    from stellar_core_tpu.main.fuzzer import XdrGenerator
    from stellar_core_tpu.xdr.transaction import TransactionEnvelope
    from stellar_core_tpu.xdr.ledger_entries import LedgerEntry
    for seed in range(12):
        gen = XdrGenerator(random.Random(seed))
        for t in (TransactionEnvelope, LedgerEntry):
            v = gen.gen(t)
            c = v.clone()
            assert c is not v
            assert c.to_bytes() == v.to_bytes()
    # mutation independence through a nested MUTABLE path: mutate a
    # nested struct field and a list element on the original; the clone
    # must be unaffected (a shallow copy would fail here)
    from stellar_core_tpu.xdr.ledger_entries import (
        AccountEntry, LedgerEntryType, Signer, _LedgerEntryData)
    from stellar_core_tpu.xdr.types import (PublicKey, SignerKey,
                                            SignerKeyType)
    signer = Signer(key=SignerKey(SignerKeyType.SIGNER_KEY_TYPE_ED25519,
                                  b"\x05" * 32), weight=1)
    le = LedgerEntry(
        lastModifiedLedgerSeq=1,
        data=_LedgerEntryData(LedgerEntryType.ACCOUNT, AccountEntry(
            accountID=PublicKey.ed25519(b"\x07" * 32), balance=5,
            thresholds=bytearray(b"\x01\x00\x00\x00"),
            signers=[signer])))
    c = le.clone()
    before = c.to_bytes()
    le.data.value.balance = 999
    le.data.value.signers[0].weight = 200
    le.data.value.thresholds[0] = 77        # mutate the live bytearray
    assert c.to_bytes() == before
