"""Flight-recorder tests (ISSUE 3): span tracing, Chrome-trace export,
Prometheus exposition, tx end-to-end latency, and the observability
satellites (clearmetrics+zones, per-peer counters, Meter EWMA windows,
the tracing-disabled cost contract)."""

import json
import re
import threading
import tracemalloc

import pytest

from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.simulation import LoadGenerator, topologies
from stellar_core_tpu.util import tracing
from stellar_core_tpu.util.metrics import (Meter, MetricsRegistry,
                                           render_prometheus)
from stellar_core_tpu.util.perf import ZoneRegistry
from stellar_core_tpu.util.timer import ClockMode, VirtualClock

import test_overlay as ovl


@pytest.fixture(autouse=True)
def _no_leftover_tracing():
    """Every test starts and ends with tracing disabled (a leaked
    active recorder would make every other test pay for spans)."""
    yield
    with tracing._state_lock:
        tracing._active_count = 0
        tracing.ENABLED = False


# ------------------------------------------------------------ recorder --

def test_enabled_refcounts_across_recorders():
    a, b = tracing.FlightRecorder(), tracing.FlightRecorder()
    assert tracing.ENABLED is False
    a.start()
    b.start()
    assert tracing.ENABLED
    a.stop()
    assert tracing.ENABLED          # b still recording
    b.stop()
    assert tracing.ENABLED is False
    # double stop is a no-op, not an underflow
    b.stop()
    a.start()
    assert tracing.ENABLED
    a.stop()
    assert tracing.ENABLED is False


def test_disabled_path_is_one_constant_check_no_alloc():
    """The cost contract (mirrors chaos.ENABLED): with no recorder
    active, an instrumented span site runs one module-constant check —
    no recorder call, no event, no allocation attributable to the
    tracing module."""
    assert tracing.ENABLED is False
    rec = tracing.FlightRecorder()
    reg = ZoneRegistry()
    reg.tracer = rec

    def span_site():
        # the exact guard pattern every instrumented hot path uses
        if tracing.ENABLED:
            rec.begin("x")
            rec.end("x")

    span_site()                       # warm anything lazy
    tracemalloc.start()
    before = tracemalloc.take_snapshot()
    for _ in range(2000):
        span_site()
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    grown = sum(
        st.size_diff for st in after.compare_to(before, "filename")
        if st.traceback[0].filename == tracing.__file__)
    assert grown == 0, "tracing-disabled span site allocated memory"
    assert len(rec) == 0 and rec._appended == 0
    # the zone path records nothing either (and aggregates as before)
    with reg.zone("z"):
        pass
    assert len(rec) == 0
    assert reg.report()["z"]["count"] == 1


def test_zone_routes_spans_into_recorder():
    rec = tracing.FlightRecorder()
    reg = ZoneRegistry()
    reg.tracer = rec
    rec.start()
    try:
        with reg.zone("outer", targs={"seq": 7}):
            with reg.zone("inner"):
                pass
    finally:
        rec.stop()
    doc = rec.to_chrome_trace()
    spans = [e for e in doc["traceEvents"] if e["ph"] in "BE"]
    assert [(e["ph"], e["name"]) for e in spans] == [
        ("B", "outer"), ("B", "inner"), ("E", "inner"), ("E", "outer")]
    assert spans[0]["args"] == {"seq": 7}
    assert spans[0]["tid"] == threading.get_ident()
    # zone aggregation unaffected by the trace ride-along
    assert reg.report()["outer"]["count"] == 1


def test_ring_buffer_bounds_and_reconciliation():
    rec = tracing.FlightRecorder(capacity=8)
    rec.start()
    for i in range(20):
        rec.begin("span-%d" % i)
        rec.end("span-%d" % i)
    rec.stop()
    assert rec.dropped == 32
    events = rec.to_chrome_trace()["traceEvents"]
    # eviction can orphan an E whose B was overwritten; the dump must
    # still emit only matched pairs
    assert sum(1 for e in events if e["ph"] == "B") == \
        sum(1 for e in events if e["ph"] == "E")


def test_unclosed_span_is_closed_at_dump():
    rec = tracing.FlightRecorder()
    rec.start()
    rec.begin("open-forever", {"seq": 1})
    rec.instant("tick")
    rec.stop()
    events = rec.to_chrome_trace()["traceEvents"]
    bs = [e for e in events if e["ph"] == "B"]
    es = [e for e in events if e["ph"] == "E"]
    assert len(bs) == len(es) == 1
    assert es[0]["name"] == "open-forever"
    assert es[0]["ts"] >= bs[0]["ts"]


def test_async_track_correlates_by_id():
    rec = tracing.FlightRecorder()
    rec.start()
    rec.async_begin("tx.e2e", "cafe1234")
    rec.async_end("tx.e2e", "cafe1234", {"seq": 3})
    rec.stop()
    ev = [e for e in rec.to_chrome_trace()["traceEvents"]
          if e["ph"] in ("b", "e")]
    assert [e["ph"] for e in ev] == ["b", "e"]
    assert all(e["id"] == "cafe1234" and e["cat"] == "tx" for e in ev)


# ------------------------------------------------- chrome-trace checks --

def _validate_chrome_events(events):
    """Structural validation: JSON round-trips, per-thread matched B/E
    nesting, per-thread non-decreasing timestamps. Returns spans by
    name for further assertions."""
    events = json.loads(json.dumps(events))     # serializable
    last_ts = {}
    stacks = {}
    spans = {}
    for e in events:
        assert {"ph", "name", "pid", "tid"} <= set(e), e
        if e["ph"] == "M":
            continue
        key = (e["pid"], e["tid"])
        assert e["ts"] >= last_ts.get(key, 0.0), \
            f"timestamps regress on {key}"
        last_ts[key] = e["ts"]
        if e["ph"] == "B":
            stacks.setdefault(key, []).append(e)
        elif e["ph"] == "E":
            assert stacks.get(key), f"E with no open B on {key}"
            opened = stacks[key].pop()
            spans.setdefault(opened["name"], []).append(
                (opened, e["ts"] - opened["ts"],
                 len(stacks[key])))        # (begin, dur, depth)
    for key, stack in stacks.items():
        assert not stack, f"unclosed spans in dump on {key}: {stack}"
    return spans


def test_traced_four_node_simulation():
    """Acceptance: a traced 4-node simulation produces Chrome
    trace-event JSON validated structurally — nesting, threads,
    ledger-seq args — plus the tx e2e latency track."""
    sim = topologies.core(4)
    try:
        for a in sim.apps():
            a.flight_recorder.start()
        sim.start_all_nodes()
        assert sim.crank_until(lambda: sim.have_all_externalized(2))
        app = sim.apps()[0]
        lg = LoadGenerator(app)
        assert lg.generate_accounts(4) == 4
        target = app.ledger_manager.get_last_closed_ledger_num() + 2
        assert sim.crank_until(lambda: sim.have_all_externalized(target))
        lg.sync_account_seqs()
        assert lg.generate_payments(4) == 4     # 4 distinct e2e tracks
        target = app.ledger_manager.get_last_closed_ledger_num() + 2
        assert sim.crank_until(lambda: sim.have_all_externalized(target))
        assert lg.failed == 0

        doc = app.command_handler.handle("dumptrace")["trace"]
        events = doc["traceEvents"]
        spans = _validate_chrome_events(events)

        # ledger-seq args on the close spans (Tracy zone-value parity)
        closes = spans.get("ledger.closeLedger")
        assert closes, "no closeLedger spans in trace"
        seqs = [c[0]["args"]["seq"] for c in closes]
        assert all(isinstance(s, int) and s >= 2 for s in seqs)

        # nesting: close phases recorded INSIDE closeLedger (depth > 0)
        assert any(depth > 0 for _, _, depth
                   in spans.get("ledger.close.applyTx", [])), \
            "close phases are not nested under closeLedger"

        # threads: every tid that emitted events has thread metadata
        tids = {e["tid"] for e in events if e["ph"] != "M"}
        named = {e["tid"] for e in events
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert tids and tids <= named

        # cross-subsystem spans: overlay + SCP lifecycle all present
        names = {e["name"] for e in events}
        assert "overlay.recv" in names
        assert "overlay.send" in names
        assert "scp.envelope.emit" in names
        assert "herder.recvSCPEnvelope" in names

        # the tx e2e track: async begin/end pairs + the timer samples
        phs = {e["ph"] for e in events if e["name"] == "tx.e2e"}
        assert phs == {"b", "e"}
        e2e = app.metrics.to_json()["ledger.transaction.e2e"]
        assert e2e["count"] >= 4 and e2e["median"] > 0

        # node labels separate the processes in the merged view
        assert app.flight_recorder.label
    finally:
        sim.stop_all_nodes()
    # stop_all_nodes released every recorder refcount
    assert tracing.ENABLED is False


def test_admin_trace_routes_roundtrip():
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             get_test_config())
    app.start()
    try:
        h = app.command_handler
        assert "exception" in h.handle("stoptrace")   # nothing recording
        out = h.handle("starttrace", {"capacity": "4096"})
        assert out["status"] == "ok" and out["capacity"] == 4096
        app.manual_close()
        out = h.handle("stoptrace")
        assert out["status"] == "ok" and out["events"] > 0
        doc = h.handle("dumptrace")["trace"]
        spans = _validate_chrome_events(doc["traceEvents"])
        assert "ledger.closeLedger" in spans
        # dump to a file path too
        import tempfile
        path = tempfile.mktemp(suffix=".json")
        out = h.handle("dumptrace", {"path": path})
        assert out["status"] == "ok"
        with open(path) as f:
            assert json.load(f)["traceEvents"]
        # create-only: the route must refuse to truncate existing files
        assert "exception" in h.handle("dumptrace", {"path": path})
        import os
        os.unlink(path)
    finally:
        app.shutdown()


# ------------------------------------------------------------ satellites --

def test_clearmetrics_also_resets_zones():
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             get_test_config())
    app.start()
    try:
        h = app.command_handler
        app.manual_close()
        assert h.handle("metrics")["perf_zones"]
        assert h.handle("metrics")["metrics"][
            "ledger.ledger.close"]["count"] >= 1
        assert h.handle("clearmetrics")["status"] == "ok"
        out = h.handle("metrics")
        assert out["perf_zones"] == {}
        # metrics reset IN PLACE: the families survive with zeroed
        # values — subsystems cache metric objects at construction, so
        # deregistering would orphan them (counting, never reported)
        assert out["metrics"]["ledger.ledger.close"]["count"] == 0
        assert out["metrics"]["ledger.transaction.e2e"]["count"] == 0
        # a close after clear counts into the SAME cached timer
        app.manual_close()
        assert h.handle("metrics")["metrics"][
            "ledger.ledger.close"]["count"] == 1
        # perf?reset=1 clears the same registry (symmetry)
        assert h.handle("perf", {"reset": "1"})["perf"]
        assert h.handle("perf")["perf"] == {}
    finally:
        app.shutdown()


def test_meter_exposes_all_ewma_windows_and_ticks_catch_up():
    m = Meter()
    m.mark(100)
    # simulate a 10-minute idle gap: the next read must seed the EWMAs
    # and replay the missed 5 s ticks (capped), not return stale zeros
    m._last_tick -= 600.0
    j = m.to_json()
    assert {"1_min_rate", "5_min_rate", "15_min_rate"} <= set(j)
    # decay order after an idle gap: the short window forgets fastest
    assert j["1_min_rate"] < j["5_min_rate"] < j["15_min_rate"]
    assert j["15_min_rate"] > 0
    assert j["count"] == 100
    # a pathological gap hits the tick cap instead of spinning
    m.mark(1)
    m._last_tick -= 1e6
    assert m.to_json()["1_min_rate"] >= 0.0


def test_peers_route_reports_per_peer_counters_and_drops():
    from stellar_core_tpu.overlay import LoopbackPeerConnection
    clock, apps = ovl.make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        out = apps[0].command_handler.handle("peers")
        peers = out["authenticated_peers"]
        one = (peers["inbound"] + peers["outbound"])[0]
        assert one["messages_sent"] > 0 and one["messages_received"] > 0
        assert one["bytes_sent"] > 0 and one["bytes_received"] > 0
        # aggregate overlay.peer.* meters registered and counting
        mets = apps[0].metrics.to_json()
        assert mets["overlay.peer.message.sent"]["count"] > 0
        assert mets["overlay.peer.byte.received"]["count"] > 0
        # drop reasons tallied (keyed on the stable prefix) + counter
        conn.initiator.drop("test reason: detail goes here")
        out = apps[0].command_handler.handle("peers")
        assert out["authenticated_peers"]["drop_reasons"] == {
            "test reason": 1}
        assert apps[0].metrics.to_json()[
            "overlay.peer.drop.test-reason"]["count"] == 1
    finally:
        ovl.shutdown(apps)


# ----------------------------------------------------------- prometheus --

_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*='
    r'"[^"]*")*\})?'
    r' -?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?$')


def _lint_exposition(text: str) -> None:
    """Prometheus text-format lint: HELP/TYPE precede their family,
    every sample line parses, no family is TYPEd twice, and histogram
    families are well-formed (cumulative non-decreasing buckets, the
    +Inf bucket equal to _count)."""
    seen_types = {}
    buckets = {}          # family -> [(le, value)]
    counts = {}           # family -> _count value
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, fam, mtype = line.split(" ", 3)
            assert fam not in seen_types, f"duplicate TYPE for {fam}"
            assert mtype in ("counter", "gauge", "summary", "histogram")
            seen_types[fam] = mtype
        else:
            assert _SAMPLE_RE.match(line), f"bad sample line: {line!r}"
            name = line.split("{")[0].split(" ")[0]
            base = re.sub(r"_(count|sum|total|bucket)$", "", name)
            assert name in seen_types or base in seen_types, \
                f"sample {name} has no TYPE"
            if name.endswith("_bucket"):
                assert seen_types.get(base) == "histogram", \
                    f"_bucket sample outside a histogram family: {line!r}"
                le, val = line.split('le="', 1)[1].split('"} ')
                buckets.setdefault(base, []).append(
                    (float("inf") if le == "+Inf" else float(le),
                     float(val)))
            elif name.endswith("_count") and \
                    seen_types.get(base) == "histogram":
                counts[base] = float(line.rsplit(" ", 1)[1])
    for fam, bs in buckets.items():
        les = [le for le, _ in bs]
        vals = [v for _, v in bs]
        assert les == sorted(les), f"{fam}: le bounds out of order"
        assert vals == sorted(vals), f"{fam}: buckets not cumulative"
        assert les[-1] == float("inf"), f"{fam}: missing +Inf bucket"
        assert vals[-1] == counts.get(fam), \
            f"{fam}: +Inf bucket != _count"
    assert seen_types, "empty exposition"


def test_prometheus_exposition_format():
    m = MetricsRegistry()
    m.new_counter("ledger.age.closed").inc(3)
    m.new_meter("scp.envelope.receive").mark(10)
    t = m.new_timer("ledger.transaction.apply")
    t.update(0.25)
    t.update(0.5)
    m.new_histogram("2bad.name$with/chars").update(42.0)
    zones = {"ledger.close.seal": {"count": 2, "total_ms": 10.0,
                                   "mean_ms": 5.0, "max_ms": 7.5}}
    text = render_prometheus(m.to_json(), zones)
    _lint_exposition(text)
    # dotted-name sanitization
    assert "ledger_age_closed 3" in text
    assert "scp_envelope_receive_total 10" in text
    # a leading digit cannot start a metric name
    assert "\n_2bad_name_with_chars" in text
    # timer quantiles as labeled samples, in seconds
    assert 'ledger_transaction_apply_seconds{quantile="0.5"}' in text
    assert 'ledger_transaction_apply_seconds{quantile="0.99"}' in text
    assert "ledger_transaction_apply_seconds_count 2" in text
    # meter rate windows labeled
    assert 'scp_envelope_receive_rate{window="15m"}' in text
    # zones as labeled gauge families
    assert 'perf_zone_total_seconds{zone="ledger.close.seal"} 0.01' \
        in text
    assert 'perf_zone_max_seconds{zone="ledger.close.seal"}' in text


def test_timer_bucket_histogram_exposition():
    """Satellite (ISSUE 8): timers additionally export cumulative
    `_bucket` histogram families — summaries with quantile labels
    cannot be aggregated across nodes, fixed-bound buckets can. The
    summary form stays for back-compat."""
    m = MetricsRegistry()
    t = m.new_timer("ledger.transaction.apply")
    for v in (0.0001, 0.003, 0.003, 0.040, 2.0, 60.0):
        t.update(v)
    text = render_prometheus(m.to_json())
    _lint_exposition(text)
    # summary form survives unchanged
    assert 'ledger_transaction_apply_seconds{quantile="0.5"}' in text
    # cumulative histogram family beside it
    assert "# TYPE ledger_transaction_apply_seconds_hist histogram" \
        in text
    assert 'ledger_transaction_apply_seconds_hist_bucket{le="0.0005"}'\
        ' 1' in text
    assert 'ledger_transaction_apply_seconds_hist_bucket{le="0.005"}'\
        ' 3' in text
    assert 'ledger_transaction_apply_seconds_hist_bucket{le="10"} 5' \
        in text
    # the 60 s sample only lands in +Inf
    assert 'ledger_transaction_apply_seconds_hist_bucket{le="+Inf"} 6'\
        in text
    assert "ledger_transaction_apply_seconds_hist_count 6" in text
    # reset zeroes the buckets with everything else
    t.reset()
    text = render_prometheus(m.to_json())
    _lint_exposition(text)
    assert 'ledger_transaction_apply_seconds_hist_bucket{le="+Inf"} 0'\
        in text


def test_metrics_route_prometheus_format():
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             get_test_config())
    app.start()
    try:
        app.manual_close()
        out = app.command_handler.handle("metrics",
                                         {"format": "prometheus"})
        assert "_raw_body" in out
        assert out["_content_type"].startswith("text/plain")
        _lint_exposition(out["_raw_body"])
        # the close pipeline's zones are scrapable
        assert 'perf_zone_count{zone="ledger.closeLedger"}' \
            in out["_raw_body"]
        # e2e timer family present (registered at herder construction)
        assert "ledger_transaction_e2e_seconds" in out["_raw_body"]
    finally:
        app.shutdown()


def test_bench_e2e_report_shape():
    import bench
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             get_test_config())
    app.start()
    try:
        assert bench._tx_e2e_report(app) == {}     # no samples yet
        app.herder.tx_e2e_timer.update(0.100)
        app.herder.tx_e2e_timer.update(0.300)
        rep = bench._tx_e2e_report(app)
        assert rep["count"] == 2
        assert rep["median_ms"] in (100.0, 300.0)
        assert rep["p99_ms"] >= rep["median_ms"]
    finally:
        app.shutdown()
