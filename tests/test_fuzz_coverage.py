"""Coverage-guided fuzzing tests (reference: docs/fuzzing.md's AFL
workflow — instrumented feedback must grow a corpus, not just mutate
blindly)."""

import random
import sys

import pytest

if not hasattr(sys, "monitoring"):   # sys.monitoring is python >= 3.12
    pytest.skip("coverage-guided fuzzing needs sys.monitoring (3.12+)",
                allow_module_level=True)

from stellar_core_tpu.main.fuzz_coverage import (CoverageMonitor,
                                                 Mutator,
                                                 run_coverage_fuzz)


def test_coverage_monitor_reports_new_locations_once():
    # probe function compiled under a synthetic filename so ONLY its
    # lines are attributed (the test file itself must not count)
    ns = {}
    exec(compile("def f(x):\n    if x:\n        return 1\n    return 2\n",
                 "<fuzz-cov-probe>", "exec"), ns)
    f = ns["f"]
    cov = CoverageMonitor(prefix="<fuzz-cov-probe>")
    cov.start()
    try:
        cov.begin_input()
        f(1)
        assert cov.new_coverage() > 0
        cov.begin_input()
        f(1)                              # same path: locations disabled
        assert cov.new_coverage() == 0
        cov.begin_input()
        f(0)                              # new branch: new coverage
        assert cov.new_coverage() > 0
    finally:
        cov.stop()


def test_mutator_changes_and_terminates():
    rng = random.Random(1)
    m = Mutator(rng)
    data = bytes(range(64))
    outs = {m.mutate(data, b"other") for _ in range(50)}
    assert len(outs) > 40                 # actually mutating
    assert m.mutate(b"") != b""           # empty input grows


def test_tx_fuzz_loop_grows_corpus_via_feedback():
    """The VERDICT acceptance shape: over a bounded run, coverage
    feedback must promote inputs into the corpus (novel edges), with
    zero crashes on the tx surface."""
    s = run_coverage_fuzz("tx", runs=30, seed=11)
    assert s.runs == 30
    assert s.total_locations > 500        # instrumentation live
    assert s.corpus_size > 8              # grew beyond the seeds
    assert s.interesting > 0
    assert not s.crashes, [c.hex()[:40] for c in s.crashes]


def test_overlay_fuzz_loop_survives():
    s = run_coverage_fuzz("overlay", runs=12, seed=5)
    assert s.runs == 12
    assert s.total_locations > 0
    assert not s.crashes, [c.hex()[:40] for c in s.crashes]
