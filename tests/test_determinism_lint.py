"""Determinism lint (reference: test/check-nondet) — now a thin
wrapper over the AST analyzer (stellar_core_tpu/analysis/,
docs/ANALYSIS.md).

The original regex lints greps DETERMINISTIC_DIRS file lists; the
analyzer replaces them with call-graph reachability from the consensus
roots, so a wall-clock read in a ``util/`` helper imported into
``ledger/`` is caught too. The four historical test names are kept —
each asserts its slice of the analyzer's findings is empty, so a
failure still names the lint that used to own the rule.

Suppressions live in stellar_core_tpu/analysis/ALLOWLIST, one
justification per line; this file has no hand-maintained banned-call
or file lists anymore.
"""

import functools
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from stellar_core_tpu import analysis

# the old lint's consensus-critical scope, now expressed as module
# prefixes over analyzer findings (xdr/invariant/soroban are covered
# by reachability rather than directory membership)
_APPLY_PATH_PREFIXES = ("scp.", "tx.", "ledger.", "bucket.", "xdr.",
                        "invariant.", "soroban.")

_RANDOM_TOKENS = ("random", "urandom", "secrets", "uuid")
_WALLCLOCK_TOKENS = ("time.time", "datetime.")


@functools.lru_cache(maxsize=1)
def _findings():
    """One determinism-pass run shared by all four tests; live
    findings only (ALLOWLIST suppressions reviewed separately by
    test_analysis.py)."""
    return analysis.run_all(passes=("determinism",)).findings


def _source(f):
    return f.key.rsplit(":", 1)[-1]


def _module(f):
    parts = f.key.split(":")
    return parts[1] if len(parts) >= 3 else ""


def _render(fs):
    return "\n".join(f.render() for f in fs)


def test_no_unseeded_randomness_in_deterministic_subsystems():
    offenders = [
        f for f in _findings()
        if any(t in _source(f) for t in _RANDOM_TOKENS)
    ]
    assert not offenders, (
        "nondeterministic randomness reachable from a consensus root "
        "(use the seeded helpers in util/rand.py):\n"
        + _render(offenders))


def test_no_wall_clock_in_apply_path():
    offenders = [
        f for f in _findings()
        if any(_source(f).startswith(t) for t in _WALLCLOCK_TOKENS)
        and (_module(f).startswith(_APPLY_PATH_PREFIXES)
             or f.key.startswith("determinism:root-missing:"))
    ]
    assert not offenders, (
        "wall-clock reads reachable from the apply path (close times "
        "come from the externalized StellarValue; use the "
        "VirtualClock):\n" + _render(offenders))


def test_no_wall_clock_in_adaptive_controller():
    # strict module: ANY clock read (monotonic/perf_counter included)
    # — controller decisions must replay from sample `t` alone
    offenders = [f for f in _findings() if _module(f) == "ops.controller"]
    assert not offenders, (
        "clock reads in the adaptive controller (decisions must "
        "replay deterministically from sample `t` on the "
        "VirtualClock):\n" + _render(offenders))


def test_no_real_sleep_in_simulation_reachable_chaos_paths():
    # strengthened from the old file list to the whole package: every
    # un-allowlisted time.sleep is an offense, wherever it lives
    offenders = [f for f in _findings() if _source(f) == "time.sleep"]
    assert not offenders, (
        "real time.sleep without an ALLOWLIST justification (chaos "
        "delays and link latency are virtual-time only):\n"
        + _render(offenders))
