"""Determinism lint (reference: test/check-nondet — greps the tree for
platform-varying randomness/time sources that would break consensus
determinism; here a real test instead of a shell script)."""

import os
import re

PKG = os.path.join(os.path.dirname(__file__), "..", "stellar_core_tpu")

# consensus-critical subsystems that must be deterministic given inputs
DETERMINISTIC_DIRS = ("scp", "tx", "ledger", "bucket", "xdr", "invariant",
                      "soroban")

# sources of nondeterminism (reference check-nondet: std::rand,
# uniform_int_distribution, shuffle); python analogues + wall-clock
_BANNED = re.compile(
    r"\brandom\.(random|randint|randrange|choice|choices|sample|shuffle"
    r"|getrandbits|uniform|gauss|normalvariate|betavariate|expovariate"
    r"|Random)\b"
    r"|\bos\.urandom\b"
    r"|\bnp\.random\.\w+\(")

# wall-clock reads are banned in apply-path modules (close results must
# not depend on when they run); time.monotonic/perf_counter for metrics
# timing are fine
_WALLCLOCK = re.compile(
    r"\btime\.time(_ns)?\(\)"
    r"|\bdatetime\.(now|utcnow|today)\(")


def _py_files(*dirs):
    for d in dirs:
        root = os.path.join(PKG, d)
        assert os.path.isdir(root), \
            f"lint scope '{d}' vanished — update DETERMINISTIC_DIRS"
        for base, _, files in os.walk(root):
            for f in files:
                if f.endswith(".py"):
                    yield os.path.join(base, f)


def test_no_unseeded_randomness_in_deterministic_subsystems():
    offenders = []
    for path in _py_files(*DETERMINISTIC_DIRS):
        src = open(path).read()
        for i, line in enumerate(src.splitlines(), 1):
            if _BANNED.search(line):
                offenders.append(f"{path}:{i}: {line.strip()}")
    assert not offenders, (
        "nondeterministic randomness in consensus-critical code "
        "(use the seeded helpers in util/rand.py):\n"
        + "\n".join(offenders))


def test_no_wall_clock_in_apply_path():
    offenders = []
    for path in _py_files("scp", "tx", "ledger", "bucket", "xdr"):
        src = open(path).read()
        for i, line in enumerate(src.splitlines(), 1):
            if _WALLCLOCK.search(line):
                offenders.append(f"{path}:{i}: {line.strip()}")
    assert not offenders, (
        "wall-clock reads in the apply path (close times come from the "
        "externalized StellarValue; use the VirtualClock):\n"
        + "\n".join(offenders))


# real sleeps are banned in every chaos path a virtual-time simulation
# can reach: a wall sleep in a single-process sim blocks ALL nodes at
# once and burns wall time proportional to nodes × latency — delay
# faults and the latency model must ride the VirtualClock instead
# (chaos.Delay / LoopbackPeer._schedule_delivery)
_SLEEP = re.compile(r"\b_?time\.sleep\(")

# files a Simulation crank can execute chaos logic in
_SIM_REACHABLE_CHAOS_PATHS = (
    ("util", "chaos.py"),
    ("overlay", "loopback.py"),
    ("simulation", "simulation.py"),
    ("simulation", "topologies.py"),
    ("simulation", "byzantine.py"),
    ("simulation", "chaos.py"),
    # the adaptive control plane ticks on the sim clock (ISSUE 11)
    ("ops", "controller.py"),
)


# the adaptive controller's decisions must replay bit-identically on
# the VirtualClock: every timing read comes from the telemetry
# sample's own `t`, never the wall (ISSUE 11 — the decision-log
# determinism test depends on it). perf_counter/monotonic are banned
# here too, unlike the metrics-timing exemption above: the controller
# has no legitimate wall measurement of its own.
_CONTROLLER_WALLCLOCK = re.compile(
    r"\btime\.(time(_ns)?|monotonic(_ns)?|perf_counter(_ns)?)\(\)"
    r"|\bdatetime\.(now|utcnow|today)\(")


def test_no_wall_clock_in_adaptive_controller():
    path = os.path.join(PKG, "ops", "controller.py")
    assert os.path.isfile(path), \
        "ops/controller.py vanished — update the lint"
    offenders = []
    for i, line in enumerate(open(path).read().splitlines(), 1):
        if _CONTROLLER_WALLCLOCK.search(line):
            offenders.append(f"{path}:{i}: {line.strip()}")
    assert not offenders, (
        "wall-clock reads in the adaptive controller (decisions must "
        "replay deterministically from sample `t` on the "
        "VirtualClock):\n" + "\n".join(offenders))


def test_no_real_sleep_in_simulation_reachable_chaos_paths():
    offenders = []
    for parts in _SIM_REACHABLE_CHAOS_PATHS:
        path = os.path.join(PKG, *parts)
        assert os.path.isfile(path), \
            f"lint scope {parts} vanished — update the list"
        for i, line in enumerate(open(path).read().splitlines(), 1):
            if _SLEEP.search(line):
                offenders.append(f"{path}:{i}: {line.strip()}")
    assert not offenders, (
        "real time.sleep in a simulation-reachable chaos path (use "
        "VirtualClock scheduling — chaos delays and link latency are "
        "virtual-time only):\n" + "\n".join(offenders))
