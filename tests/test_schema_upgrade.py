"""Stepwise DB schema upgrades (reference: Database.cpp:208-265
MIN_SCHEMA_VERSION -> SCHEMA_VERSION with per-step applySchemaUpgrade)
and the opt-in real-PostgreSQL exposure."""

import os

import pytest

from stellar_core_tpu.db.database import (Database, SCHEMA_VERSION,
                                          SCHEMA_V2_STATEMENTS)
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.util.timer import ClockMode, VirtualClock


def _downgrade_to_v1(db: Database) -> None:
    """Reshape a fresh DB into what a v1-era node left on disk."""
    for name in ("histbytxid", "feehistbytxid", "scpenvsbyseq"):
        db.execute(f"DROP INDEX IF EXISTS {name}")
    db.execute("DROP TABLE IF EXISTS publishqueue")
    db.put_schema_version(1)


def _index_names(db: Database):
    return {r[0] for r in db.query_all(
        "SELECT name FROM sqlite_master WHERE type='index'")}


def test_stepwise_upgrade_v1_to_current(tmp_path):
    path = str(tmp_path / "node.db")
    db = Database(path)
    db.initialize()
    assert db.get_schema_version() == SCHEMA_VERSION == 3
    _downgrade_to_v1(db)
    assert db.get_schema_version() == 1
    assert "histbytxid" not in _index_names(db)

    db.upgrade_to_current_schema()
    assert db.get_schema_version() == SCHEMA_VERSION
    names = _index_names(db)
    for stmt in SCHEMA_V2_STATEMENTS:
        idx = stmt.split("EXISTS ")[1].split(" ")[0]
        assert idx in names, idx
    # v3: the durable publish queue table exists again
    assert db.query_one(
        "SELECT name FROM sqlite_master WHERE type='table' "
        "AND name='publishqueue'") is not None
    db.close()


def test_node_upgrades_old_db_on_start(tmp_path):
    """A node opening a v1-era database upgrades it in place
    (reference: Database ctor applying pending schema upgrades)."""
    path = str(tmp_path / "node.db")
    cfg = get_test_config()
    cfg.DATABASE = f"sqlite3://{path}"
    cfg.BUCKET_DIR_PATH = str(tmp_path / "buckets")
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    app.manual_close()
    lcl = app.ledger_manager.get_last_closed_ledger_num()
    _downgrade_to_v1(app.database)
    app.shutdown()

    cfg2 = get_test_config()
    cfg2.DATABASE = f"sqlite3://{path}"
    cfg2.BUCKET_DIR_PATH = cfg.BUCKET_DIR_PATH
    cfg2.NETWORK_PASSPHRASE = cfg.NETWORK_PASSPHRASE
    app2 = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg2)
    app2.start()
    try:
        assert app2.database.get_schema_version() == SCHEMA_VERSION
        assert "histbytxid" in _index_names(app2.database)
        assert app2.ledger_manager.get_last_closed_ledger_num() == lcl
    finally:
        app2.shutdown()


def test_upgrade_db_command(tmp_path):
    from stellar_core_tpu.main.command_line import main as cli_main
    path = str(tmp_path / "node.db")
    db = Database(path)
    db.initialize()
    _downgrade_to_v1(db)
    db.close()
    conf = tmp_path / "node.cfg"
    conf.write_text(f'DATABASE = "sqlite3://{path}"\n')
    assert cli_main(["--conf", str(conf), "upgrade-db"]) == 0
    db = Database(path)
    assert db.get_schema_version() == SCHEMA_VERSION
    db.close()


def test_newer_schema_refused(tmp_path):
    db = Database(str(tmp_path / "node.db"))
    db.initialize()
    db.put_schema_version(SCHEMA_VERSION + 1)
    with pytest.raises(RuntimeError, match="newer than supported"):
        db.upgrade_to_current_schema()
    db.close()


# ------------------------------------------------- real-postgres opt-in --

@pytest.mark.skipif(
    not os.environ.get("PGHOST"),
    reason="real-PostgreSQL exposure needs PGHOST (plus PGUSER/PGDATABASE"
           "/PGPASSWORD as applicable) pointing at a live server; the "
           "hermetic suite otherwise covers the dialect through the "
           "in-repo wire stub only (VERDICT r03 weak #5)")
def test_postgres_against_real_server():
    """The dialect translator (upsert rewriting, $n placeholders,
    secondary-unique pre-DELETEs) against a real PostgreSQL — the
    reference CIs this way (ci-build.sh:173-174)."""
    from stellar_core_tpu.db.postgres import PostgresDatabase
    host = os.environ["PGHOST"]
    user = os.environ.get("PGUSER", "postgres")
    dbname = os.environ.get("PGDATABASE", "postgres")
    pw = os.environ.get("PGPASSWORD", "")
    uri = f"postgresql://{user}:{pw}@{host}:" \
          f"{os.environ.get('PGPORT', '5432')}/{dbname}"
    db = PostgresDatabase(uri)
    try:
        db.initialize()
        assert db.get_schema_version() == SCHEMA_VERSION
        # upsert path (INSERT OR REPLACE translation) + secondary-unique
        # pre-delete: two headers sharing a ledgerseq must not collide
        db.execute(
            "INSERT OR REPLACE INTO ledgerheaders "
            "(ledgerhash, prevhash, ledgerseq, closetime, data) "
            "VALUES (?,?,?,?,?)", (b"h1", b"p", 7, 1, b"d1"))
        db.execute(
            "INSERT OR REPLACE INTO ledgerheaders "
            "(ledgerhash, prevhash, ledgerseq, closetime, data) "
            "VALUES (?,?,?,?,?)", (b"h2", b"p", 7, 2, b"d2"))
        rows = db.query_all(
            "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=?",
            (7,))
        assert [bytes(r[0]) for r in rows] == [b"h2"]
    finally:
        db.close()
