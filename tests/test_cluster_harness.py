"""Multi-process cluster harness (ISSUE 9): real node processes, real
TCP, chaos and verdicts over HTTP.

Tier-1 legs: config-rendering round trips, the multi-process trace
merge, a 1-node port-file/SIGTERM/restart lifecycle, and the 3-process
smoke (spawn on ephemeral ports, converge over real sockets,
clusterstatus_ok everywhere, raw `tx`-route submission, clean
teardown). The ≥9-node tiered chaos leg (bad-sig flood over the chaos
route + kill -9 churn with catchup over the wire) is marked `slow`.
"""

import base64
import os
import time

import pytest

from stellar_core_tpu.main.config import Config
from stellar_core_tpu.simulation.cluster import (Cluster,
                                                 run_cluster_scenario)
from stellar_core_tpu.simulation import topologies

pytestmark = pytest.mark.cluster


# ------------------------------------------------------------- unit legs --
def test_config_rendering_round_trips(tmp_path):
    """Every rendered TOML must load back through Config.load into the
    identity/quorum/storage shape the node process will actually run."""
    c = Cluster(3, 3, str(tmp_path))
    assert len(c.nodes) == 9
    assert len({n.peer_port for n in c.nodes}) == 9      # unique ports
    assert len({n.data_dir for n in c.nodes}) == 9
    for node in c.nodes:
        cfg = Config.load(node.cfg_path)
        assert cfg.NODE_SEED.public_key().raw == node.node_id
        assert cfg.NODE_IS_VALIDATOR and cfg.FORCE_SCP
        assert cfg.HTTP_PORT == 0                        # ephemeral
        assert cfg.PEER_PORT == node.peer_port
        assert cfg.ALLOW_CHAOS_INJECTION                 # harness-only
        assert cfg.DATABASE.startswith("sqlite3://")
        assert node.data_dir in cfg.DATABASE
        assert node.data_dir in cfg.BUCKET_DIR_PATH
        # the tiered quorum structure survives the TOML round trip
        assert cfg.QUORUM_SET.threshold == c.qset.threshold
        assert len(cfg.QUORUM_SET.inner_sets) == 3
        for got, want in zip(cfg.QUORUM_SET.inner_sets,
                             c.qset.inner_sets):
            assert got.threshold == want.threshold
            assert got.validators == want.validators
        # KNOWN_PEERS point at topology neighbors' overlay ports
        ports = {n.peer_port for n in c.nodes}
        for addr in cfg.KNOWN_PEERS:
            assert int(addr.rsplit(":", 1)[1]) in ports


def test_tiered_links_match_topology_degrees():
    """tiered_links is the SAME edge list the in-process builder wires:
    intra-org complete graphs + braided inter-org ring (+ watcher
    uplinks), no self-links, no duplicates."""
    org_ids = [[bytes([o, i]) for i in range(3)] for o in range(3)]
    links = topologies.tiered_links(org_ids)
    assert len(links) == 9 + 9                     # 3×C(3,2) + 9 cross
    assert all(a != b for a, b, _ in links)
    assert len({frozenset((a, b)) for a, b, _ in links}) == len(links)
    watchers = [bytes([9, w]) for w in range(2)]
    wlinks = topologies.tiered_links(org_ids, watchers)
    assert len(wlinks) == len(links) + 2 * len(watchers)
    # a 1-org column must not self-link on the wrap-around ring
    solo = topologies.tiered_links([[b"a"], [b"b"], [b"c"]])
    assert all(a != b for a, b, _ in solo)
    # a 2-org braid emits each wrap-around cross pair from both sides;
    # the undirected dedupe must keep exactly one (the harness reads
    # its expected mesh degree off this list)
    two = topologies.tiered_links([[b"a0", b"a1"], [b"b0", b"b1"]])
    assert len({frozenset((x, y)) for x, y, _ in two}) == len(two)
    assert len(two) == 2 + 2        # 1 intra per org + 2 cross pairs


def test_merge_trace_docs_wall_clock_alignment():
    """The multi-process merge: dumptrace exports from separate
    processes align on the wall-clock anchor, keep distinct lanes, and
    stitch hash-keyed flood hops into cross-lane flow chains."""
    from stellar_core_tpu.util.tracemerge import merge_trace_docs

    def doc(t0_wall, pid, label, ts_us, name):
        return {"traceEvents": [
            {"ph": "i", "name": name, "pid": pid, "tid": 1,
             "ts": ts_us, "args": {"hash": "abcd1234"}},
            {"ph": "b", "name": "tx.e2e", "cat": "tx", "pid": pid,
             "tid": 1, "ts": ts_us, "id": "abcd1234", "args": {}},
        ], "otherData": {"t0_wall": t0_wall, "pid": pid,
                         "label": label, "dropped_events": 0}}

    a = doc(100.0, 7, "node00", 50.0, "flood.send")
    b = doc(100.5, 7, "node01", 10.0, "flood.recv")   # colliding pid
    merged = merge_trace_docs([a, b])
    evs = merged["traceEvents"]
    pids = {e["pid"] for e in evs}
    assert len(pids) == 2                          # collision resolved
    # node01 started 0.5s later: its events shift +500000us, so the
    # recv lands AFTER the send despite a smaller local ts
    send = next(e for e in evs if e.get("name") == "flood.send")
    recv = next(e for e in evs if e.get("name") == "flood.recv")
    assert recv["ts"] == pytest.approx(500010.0)
    assert send["ts"] == pytest.approx(50.0)
    # the hash crossed two lanes -> one s→f flow chain in ts order
    flows = [e for e in evs if e.get("cat") == "flood"
             and e.get("ph") in ("s", "t", "f")]
    assert [f["ph"] for f in sorted(flows, key=lambda e: e["ts"])] \
        == ["s", "f"]
    # async ids are label-scoped so the two tx.e2e tracks stay apart
    ids = {e["id"] for e in evs if e.get("ph") == "b"}
    assert ids == {"node00:abcd1234", "node01:abcd1234"}
    # caller's documents were not mutated
    assert a["traceEvents"][0]["pid"] == 7
    # and both original docs still carry their own anchor
    assert merged["otherData"]["nodes"] == ["node00", "node01"]

    # an empty doc must not shift later lanes onto the wrong label,
    # and an unanchored doc (recorder never start()ed → t0_wall 0.0,
    # e.g. a churn-restarted process) must not poison the base anchor
    unanchored = {"traceEvents": [
        {"ph": "i", "name": "boot", "pid": 3, "tid": 1, "ts": 5.0,
         "args": {}}],
        "otherData": {"t0_wall": 0.0, "pid": 3, "label": "",
                      "dropped_events": 0}}
    m2 = merge_trace_docs([{"traceEvents": []}, a, unanchored],
                          labels=["dead", "node00", "fresh"])
    assert m2["otherData"]["nodes"] == ["node00", "fresh"]
    send2 = next(e for e in m2["traceEvents"]
                 if e.get("name") == "flood.send")
    boot = next(e for e in m2["traceEvents"]
                if e.get("name") == "boot")
    assert send2["ts"] == pytest.approx(50.0)   # base = node00's anchor
    assert boot["ts"] == pytest.approx(5.0)     # unanchored: offset 0


# ---------------------------------------------------------- process legs --
def test_single_node_port_file_sigterm_and_restart(tmp_path):
    """The `run` lifecycle satellites on one real subprocess: ephemeral
    HTTP_PORT=0 reported via --port-file and the `info` route, graceful
    SIGTERM (exit 0 through the drain path), and a restart from the
    persisted data_dir that keeps the closed chain."""
    c = Cluster(1, 1, str(tmp_path), close_time=0.3)
    with c:
        c.start_all(90.0)
        node = c.nodes[0]
        # the satellite contract: port file exists and matches info
        assert os.path.exists(node.port_file)
        info = node.get("info")["info"]
        assert info["http_port"] == node.http_port
        c.wait_slot(3, 45.0)
        lcl_before = c.lcl(node)
        rcs = c.stop_all(graceful=True)
        assert rcs[node.name] == 0, rcs
        # restart from persisted state: the chain continues, no new-db
        c.spawn(node)
        c.wait_ready(60.0, nodes=[node])
        c.wait_slot(lcl_before + 1, 45.0)
        assert c.lcl(node) >= lcl_before
        rcs = c.stop_all(graceful=True)
        assert rcs[node.name] == 0, rcs


def test_cluster_smoke_3_processes(tmp_path):
    """Tier-1 acceptance smoke: three real node processes on ephemeral
    ports converge ≥3 slots over real TCP with byte-identical headers,
    every node serves a healthy clusterstatus, a raw envelope rides
    the `tx` route end to end, and teardown is clean."""
    c = Cluster(3, 1, str(tmp_path), close_time=0.4)
    with c:
        c.start_all(120.0)
        c.wait_mesh(60.0)
        c.wait_slot(3, 60.0)

        # every node: healthy clusterstatus + identical header chains
        upto = c.min_lcl()
        statuses = c.collect_clusterstatus(20.0, headers=f"2-{upto}")
        assert all(doc is not None and doc["healthy"]
                   for doc in statuses.values()), statuses
        assert c.headers_agree(upto, statuses)

        # raw tx route: a root self-payment built harness-side, seq
        # fetched over getledgerentry — both operator routes exercised
        node0 = c.nodes[0]
        res = c.submit_tx(node0, _root_self_payment(c, node0))
        assert res["status"] in ("PENDING", "DUPLICATE"), res
        assert c.drain_pending(node0, 45.0)

        # telemetry scrape over HTTP (ISSUE 10): `run` nodes sample on
        # the wall clock by default; two incremental sweeps must not
        # re-serve old samples, and the merged summary + SLO sweep
        # cover every node
        got = c.poll_timeseries(20.0)
        assert got > 0, "no telemetry samples scraped"
        first_counts = {n.name: len(n.ts_samples) for n in c.nodes}
        assert all(v > 0 for v in first_counts.values()), first_counts
        c.poll_timeseries(10.0)
        for n in c.nodes:
            cursors = [s["cursor"] for s in n.ts_samples]
            assert cursors == sorted(cursors)
            assert len(cursors) == len(set(cursors)), \
                f"{n.name}: duplicate samples re-served"
        summary = c.series_summary()
        assert summary["nodes"] == 3 and summary["samples"] > 0
        assert summary["host_load"] is not None
        slo = c.collect_slo(15.0)
        assert set(slo["per_node"]) == {n.name for n in c.nodes}
        assert slo["overall"] in ("OK", "WARN", "BREACH")

        rcs = c.stop_all(graceful=True)
        assert all(rc == 0 for rc in rcs.values()), rcs


def _root_self_payment(cluster, node) -> str:
    """Base64 TransactionEnvelope: the network root pays itself 1
    stroop, seqnum read over the admin API (getledgerentry)."""
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.crypto.sha import sha256
    from stellar_core_tpu.tx.frame import make_frame
    from stellar_core_tpu.xdr.ledger_entries import (Asset, AssetType,
                                                     LedgerEntry,
                                                     LedgerKey)
    from stellar_core_tpu.xdr.transaction import (
        DecoratedSignature, Memo, MemoType, MuxedAccount, Operation,
        OperationType, PaymentOp, Preconditions, PreconditionType,
        Transaction, TransactionEnvelope, TransactionV1Envelope,
        _OperationBody, _TxExt)
    from stellar_core_tpu.xdr.types import EnvelopeType, PublicKey

    network_id = sha256(cluster.passphrase.encode())
    root = SecretKey.from_seed(network_id)
    key = LedgerKey.account(PublicKey.ed25519(root.public_key().raw))
    doc = node.get("getledgerentry", {
        "key": base64.b64encode(key.to_bytes()).decode()})
    assert doc["state"] == "live", doc
    entry = LedgerEntry.from_bytes(base64.b64decode(doc["entry"]))
    seq = entry.data.value.seqNum + 1

    muxed = MuxedAccount.from_ed25519(root.public_key().raw)
    tx = Transaction(
        sourceAccount=muxed, fee=100, seqNum=seq,
        cond=Preconditions(PreconditionType.PRECOND_NONE),
        memo=Memo(MemoType.MEMO_NONE),
        operations=[Operation(sourceAccount=None, body=_OperationBody(
            OperationType.PAYMENT, PaymentOp(
                destination=muxed,
                asset=Asset(AssetType.ASSET_TYPE_NATIVE),
                amount=1)))],
        ext=_TxExt(0))
    env = TransactionEnvelope(
        EnvelopeType.ENVELOPE_TYPE_TX,
        TransactionV1Envelope(tx=tx, signatures=[]))
    probe = make_frame(env, network_id)
    env.value.signatures = [DecoratedSignature(
        hint=root.public_key().hint(),
        signature=root.sign(probe.contents_hash()))]
    return base64.b64encode(env.to_bytes()).decode()


@pytest.mark.slow
def test_cluster_partition_minority_stalls_and_rejoins(tmp_path):
    """Real-socket partition (ISSUE 20): sever one org off a 3-org
    mesh — the majority keeps externalizing through the window, the
    minority node stalls WITHOUT crashing, and after heal it rejoins
    within a bounded window with a byte-identical header chain."""
    c = Cluster(3, 1, str(tmp_path), close_time=0.4)
    with c:
        c.start_all(120.0)
        c.wait_mesh(60.0)
        c.wait_slot(2, 60.0)
        minority, majority = [c.nodes[0]], c.nodes[1:]
        # window_s=0: the cut holds until the explicit heal below, so
        # the stall observation can't race a scheduled self-heal on a
        # slow host (the scheduled-window path is the matrix cell's)
        per = c.partition_schedules(minority, window_s=0.0)
        assert c.install_schedules(per, seed=20) > 0
        lcl0 = c.min_lcl(majority)
        # the quorum-holding side rides through the window
        c.wait_slot(lcl0 + 3, 120.0, nodes=majority)
        # the minority process is alive (stalled, not crashed)
        assert c.nodes[0].alive
        minority_lcl = c.lcl(c.nodes[0])
        # heal explicitly (clear beats waiting out the window) and let
        # the jittered redial re-knit the mesh
        c.clear_all_chaos()
        c.wait_mesh(120.0)
        # bounded rejoin: the minority catches up to the network LCL
        net = c.min_lcl(majority)
        assert net > minority_lcl          # majority really advanced
        c.wait_slot(net, 150.0, nodes=minority)
        # byte-identical chains across the healed mesh, zero crashes
        upto = c.min_lcl()
        statuses = c.collect_clusterstatus(30.0, headers=f"2-{upto}")
        assert c.headers_agree(upto, statuses, expected=3), statuses
        assert all(n.alive for n in c.nodes)
        rcs = c.stop_all(graceful=True)
        assert all(rc == 0 for rc in rcs.values()), rcs


@pytest.mark.slow
def test_cluster_9_nodes_tiered_chaos(tmp_path):
    """The full ≥9-node leg: tiered 3×3 quorum of real processes, pay
    load over the wire, seeded bad-sig flood installed over the chaos
    route, a REAL kill -9 churn with restart-from-data_dir and catchup
    over the overlay — every verdict must pass."""
    res = run_cluster_scenario(str(tmp_path), n_orgs=3,
                               validators_per_org=3, close_time=0.5,
                               target_slots=5, load_rounds=2,
                               txs_per_round=200)
    assert res["safety_ok"], res
    assert res["liveness_ok"], res
    assert res["clusterstatus_ok"], res
    assert res["chaos"]["flooder_dropped"], res["chaos"]
    assert res["churn"]["caught_up"], res["churn"]
    assert res["graceful_shutdown_ok"], res["shutdown_rcs"]
    assert res["slots_externalized"] >= 7
    assert res["tps"] > 0
    assert res["ok"], res
