"""Real soroban-env ABI tests (VERDICT r02 #2).

Three tiers:
 1. Val-encoding unit tests against the facts recovered from the
    reference's SDK-built binaries (tags in the low 4 bits, U32 tag 3,
    symbol tag 9, `return 5` void idiom).
 2. The in-repo hand-assembled env-ABI counter contract
    (soroban/env_contract.py) through the SAME upload→create→invoke
    scenario matrix the scvm/wasm twins run in tests/test_soroban.py —
    storage, traps, auth, events, budget — plus bulk-memory coverage.
 3. Acceptance: the reference's ACTUAL vendored SDK-built wasm binaries
    (read at test time from /root/reference, never copied into the
    repo) deploy and execute on this VM — the "run a real-ecosystem
    contract" capability. Loud skip when the reference tree is absent.
"""

import os

import pytest

from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.soroban import env_abi
from stellar_core_tpu.soroban.env_contract import (COPY_HASH_PREIMAGE,
                                                   build_env_counter)
from stellar_core_tpu.xdr import contract as cx

import test_soroban as ts

REF_TESTDATA = "/root/reference/src/testdata"


# ---------------------------------------------------------------- tier 1 --
def test_val_encoding_ground_truth():
    # the observed constants: tag 3 = I32 (the reference invokes
    # add_i32 with makeI32; the contract overflow-checks SIGNED add)
    assert env_abi.TAG_I32 == 3 and env_abi.TAG_SYMBOL == 9
    assert env_abi.VAL_VOID == 5            # both reference contracts
    v = (12345 << 4) | 3
    assert env_abi.EnvCtx(None, None, [None]).from_val(v) == \
        cx.SCVal(cx.SCValType.SCV_I32, 12345)
    neg = ((-7 & 0xFFFFFFFF) << 4) | 3
    assert env_abi.EnvCtx(None, None, [None]).from_val(neg) == \
        cx.SCVal(cx.SCValType.SCV_I32, -7)


def test_symbol_roundtrip():
    for name in (b"count", b"a", b"_", b"Z9z_", b"abcdefghij"):
        val = env_abi.symbol_to_val(name)
        assert val is not None and val & 0xF == env_abi.TAG_SYMBOL
        assert env_abi.val_to_symbol(val) == name
    assert env_abi.symbol_to_val(b"elevenchars") is None      # too long
    assert env_abi.symbol_to_val(b"sp ace") is None           # bad char


def test_scval_val_bridge_roundtrip():
    ectx = env_abi.EnvCtx(None, None, [cx.SCVal(cx.SCValType.SCV_VOID)])
    cases = [
        cx.SCVal(cx.SCValType.SCV_VOID),
        cx.SCVal(cx.SCValType.SCV_BOOL, True),
        cx.SCVal(cx.SCValType.SCV_BOOL, False),
        cx.SCVal(cx.SCValType.SCV_U32, 0),
        cx.SCVal(cx.SCValType.SCV_U32, 0xFFFFFFFF),
        cx.SCVal(cx.SCValType.SCV_I32, -1),
        cx.SCVal(cx.SCValType.SCV_I32, 2**31 - 1),
        cx.SCVal(cx.SCValType.SCV_SYMBOL, b"hello"),
        cx.SCVal(cx.SCValType.SCV_U64, 2**40),      # via object handle
        cx.SCVal(cx.SCValType.SCV_BYTES, b"\x00\x01"),
    ]
    for v in cases:
        assert ectx.from_val(ectx.to_val(v)) == v


def test_env_abi_module_detection():
    from stellar_core_tpu.soroban.env_abi import is_env_abi_module
    from stellar_core_tpu.soroban.wasm import decode
    m = decode.decode_module(build_env_counter())
    assert is_env_abi_module(m)
    # the scvm_wasm twin uses the bespoke long-name module
    m2 = decode.decode_module(ts.CODE_BUILDS["wasm"])
    assert not is_env_abi_module(m2)


# ---------------------------------------------------------------- tier 2 --
@pytest.fixture
def app():
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock

    old = ts.COUNTER_CODE
    ts.COUNTER_CODE = build_env_counter()
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    cfg = get_test_config()
    try:
        with Application.create(clock, cfg) as a:
            a.start()
            yield a
    finally:
        ts.COUNTER_CODE = old


def test_env_counter_full_matrix(app):
    """upload → create → invoke ×2 → trap — mirroring the twins."""
    master, cid = ts.deploy(app)
    ro, rw = ts.invoke_footprints(cid)

    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "increment"), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "increment"), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res

    # stored count is a real SCVal in the contract-data entry
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    with LedgerTxn(app.ledger_manager.root) as ltx:
        le = ltx.load_without_record(ts.counter_key(cid))
        assert le is not None
        assert le.data.value.val == cx.SCVal(cx.SCValType.SCV_U32, 2)

    # get_count returns it
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "get_count"), ro + rw, []))
    assert res.result.result.disc.name == "txSUCCESS", res

    # boom traps the tx (fail_with_error path)
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "boom"), ro, rw))
    assert res.result.result.disc.name == "txFAILED", res


def test_env_counter_budget_exhaustion(app):
    master, cid = ts.deploy(app)
    ro, rw = ts.invoke_footprints(cid)
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "increment"), ro, rw,
        instructions=10))
    assert res.result.result.disc.name == "txFAILED", res


def test_env_counter_auth_and_event(app):
    master, cid = ts.deploy(app)
    ro, rw = ts.invoke_footprints(cid)
    addr_val = cx.SCVal(
        cx.SCValType.SCV_ADDRESS,
        cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_ACCOUNT,
                     master.account_id))
    body = ts.invoke_op(cid, "auth_bump", [addr_val])
    op = body.value
    op.auth = [cx.SorobanAuthorizationEntry(
        credentials=cx.SorobanCredentials(
            cx.SorobanCredentialsType.SOROBAN_CREDENTIALS_SOURCE_ACCOUNT),
        rootInvocation=cx.SorobanAuthorizedInvocation(
            function=cx.SorobanAuthorizedFunction(
                cx.SorobanAuthorizedFunctionType
                .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN,
                cx.InvokeContractArgs(
                    contractAddress=cx.SCAddress(
                        cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid),
                    functionName=b"auth_bump", args=[addr_val])),
            subInvocations=[]))]
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, body, ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res


def test_env_counter_bulk_memory(app):
    """memory.init / fill / copy feed bytes_new + sha256; data.drop
    then memory.init traps."""
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    from stellar_core_tpu.xdr.ledger_entries import LedgerKey

    master, cid = ts.deploy(app)
    ro, rw = ts.invoke_footprints(cid)
    addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid)
    hash_key = LedgerKey.contract_data(
        addr, cx.SCVal(cx.SCValType.SCV_SYMBOL, b"hash"),
        cx.ContractDataDurability.PERSISTENT)
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "copy_hash"), ro,
        rw + [hash_key]))
    assert res.result.result.disc.name == "txSUCCESS", res
    with LedgerTxn(app.ledger_manager.root) as ltx:
        le = ltx.load_without_record(hash_key)
        assert le is not None
        assert le.data.value.val == cx.SCVal(
            cx.SCValType.SCV_BYTES, sha256(COPY_HASH_PREIMAGE))

    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "drop_then_init"), ro, rw))
    assert res.result.result.disc.name == "txFAILED", res


# ---------------------------------------------------------------- tier 3 --
needs_reference = pytest.mark.skipif(
    not os.path.isdir(REF_TESTDATA),
    reason="SKIPPED LOUDLY: /root/reference testdata not present — the "
           "SDK-built wasm acceptance tier needs the reference snapshot")


@needs_reference
def test_reference_sdk_contract_add_i32_direct():
    """The reference's actual SDK-built example_add_i32.wasm executes
    on this VM (it imports nothing, so the raw Instance + Val encoding
    suffices): add(U32Val 5, U32Val 7) == U32Val 12, and u32 overflow
    hits the contract's own `unreachable`."""
    from stellar_core_tpu.soroban.wasm import (Instance, WasmTrap,
                                               decode_module,
                                               validate_module)
    with open(os.path.join(REF_TESTDATA, "example_add_i32.wasm"),
              "rb") as f:
        code = f.read()
    m = decode_module(code)
    validate_module(m)
    assert env_abi.is_env_abi_module(m)
    inst = Instance(m, imports={})
    i32 = lambda n: ((n & 0xFFFFFFFF) << 4) | env_abi.TAG_I32  # noqa: E731
    out = inst.invoke("add", [i32(5), i32(7)])
    assert out == [i32(12)]
    with pytest.raises(WasmTrap):                  # INT32_MAX + 1
        Instance(m, imports={}).invoke(
            "add", [i32(2**31 - 1), i32(1)])
    # non-I32 tag rejected by the contract's own check
    with pytest.raises(WasmTrap):
        Instance(m, imports={}).invoke("add", [env_abi.VAL_VOID, i32(1)])


@needs_reference
def test_reference_sdk_contract_add_i32_deployed(app):
    """Same binary through the full upload→create→invoke tx flow."""
    with open(os.path.join(REF_TESTDATA, "example_add_i32.wasm"),
              "rb") as f:
        ts.COUNTER_CODE = f.read()
    master, cid = ts.deploy(app)
    ro, _rw = ts.invoke_footprints(cid)
    args = [cx.SCVal(cx.SCValType.SCV_I32, 5),
            cx.SCVal(cx.SCValType.SCV_I32, 7)]
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "add", args), ro, []))
    assert res.result.result.disc.name == "txSUCCESS", res

    # the reference's "failed invocation with diagnostics" scenario:
    # INT32_MAX + 7 overflows and the invocation fails
    args = [cx.SCVal(cx.SCValType.SCV_I32, 2**31 - 1),
            cx.SCVal(cx.SCValType.SCV_I32, 7)]
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "add", args), ro, []))
    assert res.result.result.disc.name == "txFAILED", res


@needs_reference
def test_reference_sdk_contract_contract_data(app):
    """example_contract_data.wasm: put/del through ("l","_")/("l","2")
    — the imports that pinned the ledger-module function order."""
    with open(os.path.join(REF_TESTDATA, "example_contract_data.wasm"),
              "rb") as f:
        ts.COUNTER_CODE = f.read()
    master, cid = ts.deploy(app)
    ro, _rw = ts.invoke_footprints(cid)
    addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid)
    key = cx.SCVal(cx.SCValType.SCV_SYMBOL, b"key")
    val = cx.SCVal(cx.SCValType.SCV_SYMBOL, b"val")
    from stellar_core_tpu.xdr.ledger_entries import LedgerKey
    dk = LedgerKey.contract_data(
        addr, key, cx.ContractDataDurability.PERSISTENT)

    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "put", [key, val]), ro, [dk]))
    assert res.result.result.disc.name == "txSUCCESS", res
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    with LedgerTxn(app.ledger_manager.root) as ltx:
        le = ltx.load_without_record(dk)
        assert le is not None and le.data.value.val == val

    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "del", [key]), ro, [dk]))
    assert res.result.result.disc.name == "txSUCCESS", res
    with LedgerTxn(app.ledger_manager.root) as ltx:
        assert ltx.load_without_record(dk) is None

    # non-symbol key: the contract's own tag check hits `unreachable`
    bad = cx.SCVal(cx.SCValType.SCV_U32, 1)
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "put", [bad, val]), ro, [dk]))
    assert res.result.result.disc.name == "txFAILED", res


# ------------------------------------------------- extended env surface ----
def _table_ctx(app, footprint_keys_rw=()):
    """A live SorobanHost + EnvCtx + env table for table-level tests."""
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    from stellar_core_tpu.soroban.host import Budget, SorobanHost
    from stellar_core_tpu.soroban.network_config import SorobanNetworkConfig
    from stellar_core_tpu.xdr.contract import LedgerFootprint
    from stellar_core_tpu.xdr.types import PublicKey

    ltx = LedgerTxn(app.ledger_manager.root)
    header = app.ledger_manager.get_last_closed_ledger_header()
    config = SorobanNetworkConfig(ltx)
    fp = LedgerFootprint(readOnly=[], readWrite=list(footprint_keys_rw))
    host = SorobanHost(ltx, header, config, fp, Budget(10**9),
                       app.config.network_id(),
                       PublicKey.ed25519(b"\x01" * 32))
    contract = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT,
                            b"\x07" * 32)
    ectx = env_abi.EnvCtx(host, contract, [cx.SCVal(cx.SCValType.SCV_VOID)])
    table = env_abi.env_host_table(ectx, lambda f: f)
    fns = {}
    for (mod, name), hf in table.items():
        fns[(mod, name)] = hf.fn
    return ltx, host, ectx, fns


class _FakeInst:
    def __init__(self, size=65536):
        self.memory = bytearray(size)


def test_map_module_semantics(app):
    ltx, host, ectx, fns = _table_ctx(app)
    try:
        inst = _FakeInst()
        u32 = lambda n: (n << 4) | env_abi.TAG_U32
        sym = env_abi.symbol_to_val
        m = fns[("m", "_")](inst)
        m = fns[("m", "0")](inst, m, sym(b"zz"), u32(26))
        m = fns[("m", "0")](inst, m, sym(b"aa"), u32(1))
        m = fns[("m", "0")](inst, m, sym(b"mm"), u32(13))
        # sorted iteration order regardless of insertion order
        keys = ectx.get_obj(fns[("m", "5")](inst, m))
        assert [bytes(k.value) for k in keys.value] == [b"aa", b"mm", b"zz"]
        vals = ectx.get_obj(fns[("m", "6")](inst, m))
        assert [v.value for v in vals.value] == [1, 13, 26]
        # replace keeps length; get returns the new value
        m = fns[("m", "0")](inst, m, sym(b"mm"), u32(99))
        assert fns[("m", "4")](inst, m) == u32(3)
        assert fns[("m", "1")](inst, m, sym(b"mm")) == u32(99)
        # has / del / missing-key error
        assert fns[("m", "2")](inst, m, sym(b"aa")) == env_abi.VAL_TRUE
        m = fns[("m", "3")](inst, m, sym(b"aa"))
        assert fns[("m", "2")](inst, m, sym(b"aa")) == env_abi.VAL_FALSE
        from stellar_core_tpu.soroban.host import HostError
        with pytest.raises(HostError):
            fns[("m", "1")](inst, m, sym(b"aa"))
        with pytest.raises(HostError):
            fns[("m", "3")](inst, m, sym(b"aa"))
    finally:
        ltx.rollback()


def test_vec_and_bytes_extensions(app):
    ltx, host, ectx, fns = _table_ctx(app)
    try:
        inst = _FakeInst()
        u32 = lambda n: (n << 4) | env_abi.TAG_U32
        v = fns[("v", "_")](inst)
        for n in (10, 20, 30):
            v = fns[("v", "0")](inst, v, u32(n))
        assert fns[("v", "3")](inst, v) == u32(10)        # front
        assert fns[("v", "4")](inst, v) == u32(30)        # back
        v2 = fns[("v", "5")](inst, v, u32(1), u32(15))    # insert
        assert [x.value for x in ectx.get_obj(v2).value] == [10, 15, 20, 30]
        v3 = fns[("v", "6")](inst, v2, u32(0))            # del
        assert [x.value for x in ectx.get_obj(v3).value] == [15, 20, 30]
        v4 = fns[("v", "7")](inst, v3, v)                 # append
        assert len(ectx.get_obj(v4).value) == 6
        v5 = fns[("v", "8")](inst, v4, u32(1), u32(4))    # slice
        assert [x.value for x in ectx.get_obj(v5).value] == [20, 30, 10]

        b0 = fns[("b", "2")](inst)                        # bytes_new
        assert bytes(ectx.get_obj(b0).value) == b""
        inst.memory[0:4] = b"\xde\xad\xbe\xef"
        b1 = fns[("b", "_")](inst, u32(0), u32(4))
        b2 = fns[("b", "3")](inst, b1, b1)                # append
        assert bytes(ectx.get_obj(b2).value) == b"\xde\xad\xbe\xef" * 2
        b3 = fns[("b", "4")](inst, b2, u32(2), u32(6))    # slice
        assert bytes(ectx.get_obj(b3).value) == b"\xbe\xef\xde\xad"
        b4 = fns[("b", "5")](inst, b3, u32(0x7F))         # push
        assert fns[("b", "6")](inst, b4, u32(4)) == u32(0x7F)   # get
        b5 = fns[("b", "7")](inst, b4, u32(0), u32(1))    # put
        assert bytes(ectx.get_obj(b5).value)[0] == 1
        inst.memory[100:103] = b"xyz"
        b6 = fns[("b", "8")](inst, b5, u32(1), u32(100), u32(3))
        assert bytes(ectx.get_obj(b6).value)[1:4] == b"xyz"
    finally:
        ltx.rollback()


def test_i128_string_timepoint_objects(app):
    ltx, host, ectx, fns = _table_ctx(app)
    try:
        inst = _FakeInst()
        u32 = lambda n: (n << 4) | env_abi.TAG_U32
        h = fns[("i", "3")](inst, (1 << 64) - 1, 7)   # hi=-1 (signed), lo=7
        assert fns[("i", "4")](inst, h) == 7
        assert fns[("i", "5")](inst, h) == (1 << 64) - 1
        v = ectx.get_obj(h)
        assert v.disc == cx.SCValType.SCV_I128 and v.value.hi == -1
        hu = fns[("i", "6")](inst, 2**63, 3)
        vu = ectx.get_obj(hu)
        assert vu.disc == cx.SCValType.SCV_U128 and vu.value.hi == 2**63
        hi64 = fns[("i", "1")](inst, (1 << 64) - 5)   # obj_from_i64 → -5
        assert ectx.get_obj(hi64).value == -5
        assert fns[("i", "2")](inst, hi64) == (1 << 64) - 5
        tp = fns[("i", "9")](inst, 1234567)
        assert ectx.get_obj(tp).disc == cx.SCValType.SCV_TIMEPOINT
        assert fns[("i", "A")](inst, tp) == 1234567

        inst.memory[10:15] = b"hello"
        s = fns[("s", "_")](inst, u32(10), u32(5))
        assert fns[("s", "0")](inst, s) == u32(5)
        fns[("s", "1")](inst, s, u32(1), u32(50), u32(4))
        assert bytes(inst.memory[50:54]) == b"ello"
    finally:
        ltx.rollback()


def test_prng_deterministic_and_log(app):
    from stellar_core_tpu.soroban.host import HostError

    def run_stream():
        """Draws + a shuffle from a FRESH host at the same ledger —
        two invocations must see the identical deterministic stream."""
        ltx, host, ectx, fns = _table_ctx(app)
        try:
            inst = _FakeInst()
            u32 = lambda n: (n << 4) | env_abi.TAG_U32
            draws = [ectx.get_obj(fns[("p", "0")](inst, 10, 20)).value
                     for _ in range(8)]
            v = fns[("v", "_")](inst)
            for n in range(10):
                v = fns[("v", "0")](inst, v, u32(n))
            shuffled = [x.value for x in ectx.get_obj(
                fns[("p", "1")](inst, v)).value]
            return draws, shuffled
        finally:
            ltx.rollback()

    a, s1 = run_stream()
    b, s2 = run_stream()
    assert a == b and all(10 <= x <= 20 for x in a)
    assert sorted(s1) == list(range(10)) and s1 == s2

    # ... but two invocation FRAMES on the SAME host (a repeated
    # cross-contract call within one tx) draw different streams
    ltx, host, ectx, fns = _table_ctx(app)
    try:
        inst = _FakeInst()
        contract = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT,
                                b"\x07" * 32)
        ectx2 = env_abi.EnvCtx(host, contract,
                               [cx.SCVal(cx.SCValType.SCV_VOID)])
        fns2 = {k: hf.fn for k, hf in
                env_abi.env_host_table(ectx2, lambda f: f).items()}
        d1 = [ectx.get_obj(fns[("p", "0")](inst, 0, 2**32)).value
              for _ in range(4)]
        d2 = [ectx2.get_obj(fns2[("p", "0")](inst, 0, 2**32)).value
              for _ in range(4)]
        assert d1 != d2
    finally:
        ltx.rollback()

    ltx, host, ectx, fns = _table_ctx(app)
    try:
        inst = _FakeInst()
        u32 = lambda n: (n << 4) | env_abi.TAG_U32
        with pytest.raises(HostError):
            fns[("p", "0")](inst, 21, 20)                 # empty range
        # log_from_linear_memory lands in host.diagnostics, off-state
        inst.memory[0:5] = b"debug"
        import struct as _s
        inst.memory[8:16] = _s.pack("<Q", u32(77))
        fns[("x", "6")](inst, u32(0), u32(5), u32(8), u32(1))
        assert host.diagnostics == [(b"debug",
                                     [cx.SCVal(cx.SCValType.SCV_U32, 77)])]
    finally:
        ltx.rollback()


def test_ledger_context_and_ttl(app):
    from stellar_core_tpu.soroban.host import HostError, ttl_key_for
    from stellar_core_tpu.xdr.ledger_entries import LedgerKey
    # storage fns need the key in the footprint: build it first
    contract = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT,
                            b"\x07" * 32)
    sym_k = cx.SCVal(cx.SCValType.SCV_SYMBOL, b"k")
    lk = LedgerKey.contract_data(contract, sym_k,
                                 cx.ContractDataDurability.PERSISTENT)
    ltx, host, ectx, fns = _table_ctx(app, footprint_keys_rw=[lk])
    try:
        inst = _FakeInst()
        u32 = lambda n: (n << 4) | env_abi.TAG_U32
        assert ectx.get_obj(fns[("x", "4")](inst)).disc == \
            cx.SCValType.SCV_TIMEPOINT
        nid = ectx.get_obj(fns[("x", "5")](inst))
        assert bytes(nid.value) == app.config.network_id()

        kval = env_abi.symbol_to_val(b"k")
        fns[("l", "_")](inst, kval, u32(5))               # put
        ttl0 = ltx.load(ttl_key_for(lk)).data.value.liveUntilLedgerSeq
        # far-future threshold forces the extension; verify liveUntil
        fns[("l", "3")](inst, kval, u32(10**6), u32(10**6))
        ttl1 = ltx.load(ttl_key_for(lk)).data.value.liveUntilLedgerSeq
        assert ttl1 > ttl0
        assert host.rent_changes[-1]["new_live_until"] == ttl1
        # threshold below remaining TTL → no-op
        fns[("l", "3")](inst, kval, u32(1), u32(10**6))
        assert ltx.load(ttl_key_for(lk)).data.value.liveUntilLedgerSeq \
            == ttl1
        with pytest.raises(HostError):                    # bad args
            fns[("l", "3")](inst, kval, u32(10), u32(5))
    finally:
        ltx.rollback()


def test_verify_sig_ed25519_host_fn(app):
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.soroban.host import HostError
    ltx, host, ectx, fns = _table_ctx(app)
    try:
        inst = _FakeInst()
        sk = SecretKey.pseudo_random_for_testing(99)
        msg = b"soroban-env verify"
        sig = sk.sign(msg)
        mk = lambda b: ectx.put_obj(cx.SCVal(cx.SCValType.SCV_BYTES, b))
        assert fns[("c", "0")](inst, mk(sk.public_key().raw), mk(msg),
                               mk(sig)) == env_abi.VAL_VOID
        bad = sig[:-1] + bytes([sig[-1] ^ 1])
        with pytest.raises(HostError):
            fns[("c", "0")](inst, mk(sk.public_key().raw), mk(msg),
                            mk(bad))
        with pytest.raises(HostError):                    # length check
            fns[("c", "0")](inst, mk(b"\x00" * 31), mk(msg), mk(sig))
    finally:
        ltx.rollback()


def test_env_toolkit_contract_end_to_end(app):
    """The second hand-assembled env-ABI contract: map/i128/string/
    verify_sig through real wasm, upload → create → invoke."""
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.soroban.env_contract import build_env_toolkit
    import test_soroban as ts_mod

    old = ts_mod.COUNTER_CODE
    ts_mod.COUNTER_CODE = build_env_toolkit()
    try:
        master, cid = ts_mod.deploy(app)
        ro, rw = ts_mod.invoke_footprints(cid)
        for fn, want in (("map_demo", cx.SCVal(cx.SCValType.SCV_U32, 1)),
                         ("i128_demo", cx.SCVal(cx.SCValType.SCV_U32, 42)),
                         ("str_demo", cx.SCVal(cx.SCValType.SCV_U32, 7))):
            res = ts_mod.submit_and_close(app, ts_mod.soroban_tx(
                app, master, ts_mod.invoke_op(cid, fn), ro, rw))
            assert res.result.result.disc.name == "txSUCCESS", (fn, res)

        sk = SecretKey.pseudo_random_for_testing(7)
        msg = b"toolkit message"
        sig = sk.sign(msg)
        mkb = lambda b: cx.SCVal(cx.SCValType.SCV_BYTES, b)
        res = ts_mod.submit_and_close(app, ts_mod.soroban_tx(
            app, master, ts_mod.invoke_op(
                cid, "sig_demo",
                [mkb(sk.public_key().raw), mkb(msg), mkb(sig)]), ro, rw))
        assert res.result.result.disc.name == "txSUCCESS", res
        bad = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        res = ts_mod.submit_and_close(app, ts_mod.soroban_tx(
            app, master, ts_mod.invoke_op(
                cid, "sig_demo",
                [mkb(sk.public_key().raw), mkb(msg), mkb(bad)]), ro, rw))
        assert res.result.result.disc.name == "txFAILED", res
    finally:
        ts_mod.COUNTER_CODE = old


def test_u256_i256_env_family(app):
    """The 256-bit host-fn families vs python-int oracles: pieces and
    be-bytes round trips, checked arithmetic (overflow / div0 / shift
    >=256 error), Euclidean remainder, arithmetic right shift
    (reference embeds these via the bridge, rust/src/contract.rs +
    Cargo.toml:27-56)."""
    from stellar_core_tpu.soroban.host import HostError

    ltx, host, ectx, fns = _table_ctx(app)
    try:
        inst = _FakeInst()
        u32 = lambda n: (n << 4) | env_abi.TAG_U32
        M64 = (1 << 64) - 1
        U256_MAX = (1 << 256) - 1

        def u256(x):
            return fns[("i", "B")](inst, (x >> 192) & M64,
                                   (x >> 128) & M64, (x >> 64) & M64,
                                   x & M64)

        def u256_val(h):
            v = ectx.get_obj(h)
            assert v.disc == cx.SCValType.SCV_U256
            p = v.value
            return (int(p.hi_hi) << 192) | (int(p.hi_lo) << 128) | \
                (int(p.lo_hi) << 64) | int(p.lo_lo)

        def i256(x):
            u = x & U256_MAX
            return fns[("i", "I")](inst, (u >> 192) & M64,
                                   (u >> 128) & M64, (u >> 64) & M64,
                                   u & M64)

        def i256_val(h):
            v = ectx.get_obj(h)
            assert v.disc == cx.SCValType.SCV_I256
            p = v.value
            u = ((int(p.hi_hi) & M64) << 192) | (int(p.hi_lo) << 128) | \
                (int(p.lo_hi) << 64) | int(p.lo_lo)
            return u - (1 << 256) if u >> 255 else u

        import random
        rng = random.Random(20260801)
        # --- u256 arithmetic vs oracle ---
        for _ in range(40):
            a = rng.getrandbits(256)
            bb = rng.getrandbits(rng.choice([8, 64, 128, 256]))
            assert u256_val(fns[("i", "P")](inst, u256(a), u256(bb))) \
                == (a + bb) if a + bb <= U256_MAX else True
            if a + bb > U256_MAX:
                with pytest.raises(HostError):
                    fns[("i", "P")](inst, u256(a), u256(bb))
            if a >= bb:
                assert u256_val(fns[("i", "Q")](inst, u256(a),
                                                u256(bb))) == a - bb
            else:
                with pytest.raises(HostError):
                    fns[("i", "Q")](inst, u256(a), u256(bb))
            if a * bb <= U256_MAX:
                assert u256_val(fns[("i", "R")](inst, u256(a),
                                                u256(bb))) == a * bb
            if bb:
                assert u256_val(fns[("i", "S")](inst, u256(a),
                                                u256(bb))) == a // bb
                assert u256_val(fns[("i", "T")](inst, u256(a),
                                                u256(bb))) == a % bb
        with pytest.raises(HostError):
            fns[("i", "S")](inst, u256(1), u256(0))     # div by zero
        with pytest.raises(HostError):
            fns[("i", "R")](inst, u256(1 << 200), u256(1 << 200))
        # pow / shl / shr
        assert u256_val(fns[("i", "U")](inst, u256(3), u32(100))) \
            == 3 ** 100
        with pytest.raises(HostError):
            fns[("i", "U")](inst, u256(2), u32(256))    # overflow
        assert u256_val(fns[("i", "V")](inst, u256(1), u32(255))) \
            == 1 << 255
        assert u256_val(fns[("i", "W")](inst, u256(1 << 255),
                                        u32(200))) == 1 << 55
        for name in ("V", "W"):
            with pytest.raises(HostError):
                fns[("i", name)](inst, u256(1), u32(256))
        # be-bytes round trip
        x = rng.getrandbits(256)
        bh = fns[("i", "D")](inst, u256(x))
        assert bytes(ectx.get_obj(bh).value) == x.to_bytes(32, "big")
        assert u256_val(fns[("i", "C")](inst, bh)) == x
        # pieces getters
        h = u256(x)
        got = [fns[("i", nm)](inst, h) for nm in "EFGH"]
        assert got == [(x >> s) & M64 for s in (192, 128, 64, 0)]

        # --- i256 ---
        I_MIN, I_MAX = -(1 << 255), (1 << 255) - 1
        for _ in range(40):
            a = rng.getrandbits(255) - (1 << 254)
            bb = rng.getrandbits(128) - (1 << 127)
            assert i256_val(fns[("i", "X")](inst, i256(a),
                                            i256(bb))) == a + bb
            assert i256_val(fns[("i", "Y")](inst, i256(a),
                                            i256(bb))) == a - bb
            if I_MIN <= a * bb <= I_MAX:
                assert i256_val(fns[("i", "Z")](inst, i256(a),
                                                i256(bb))) == a * bb
            if bb:
                q = abs(a) // abs(bb)
                if (a < 0) != (bb < 0):
                    q = -q
                assert i256_val(fns[("i", "a")](inst, i256(a),
                                                i256(bb))) == q
                r = a % abs(bb)
                assert i256_val(fns[("i", "b")](inst, i256(a),
                                                i256(bb))) == r
                assert r >= 0
        with pytest.raises(HostError):                  # overflow
            fns[("i", "X")](inst, i256(I_MAX), i256(1))
        with pytest.raises(HostError):                  # MIN / -1
            fns[("i", "a")](inst, i256(I_MIN), i256(-1))
        # arithmetic right shift sign-extends
        assert i256_val(fns[("i", "e")](inst, i256(-8), u32(2))) == -2
        assert i256_val(fns[("i", "e")](inst, i256(I_MIN),
                                        u32(255))) == -1
        # i256 be-bytes round trip (negative)
        nh = fns[("i", "K")](inst, i256(-12345))
        assert bytes(ectx.get_obj(nh).value) == \
            (-12345).to_bytes(32, "big", signed=True)
        assert i256_val(fns[("i", "J")](inst, nh)) == -12345
        # i256 pieces: hi_hi is the SIGNED limb
        hp = i256(-1)
        assert all(fns[("i", nm)](inst, hp) == M64 for nm in "LMNO")

        # duration round trip
        dh = fns[("i", "f")](inst, 86400)
        assert ectx.get_obj(dh).disc == cx.SCValType.SCV_DURATION
        assert fns[("i", "g")](inst, dh) == 86400
    finally:
        ltx.rollback()


def test_env_u256_contract_end_to_end(app):
    """A hand-assembled env-ABI contract computing with u256/i256
    through upload -> create -> invoke (the VERDICT r04 #5 'done'
    condition)."""
    from stellar_core_tpu.soroban.env_contract import build_env_u256
    import test_soroban as ts_mod

    old = ts_mod.COUNTER_CODE
    ts_mod.COUNTER_CODE = build_env_u256()
    try:
        master, cid = ts_mod.deploy(app)
        ro, rw = ts_mod.invoke_footprints(cid)
        res = ts_mod.submit_and_close(app, ts_mod.soroban_tx(
            app, master, ts_mod.invoke_op(cid, "u256_demo"), ro, rw))
        assert res.result.result.disc.name == "txSUCCESS", res
        # the host-fn return value travels in sorobanMeta (V3 meta)
        from stellar_core_tpu.xdr.ledger import TransactionMeta
        row = app.database.query_one(
            "SELECT txmeta FROM txhistory WHERE txid=?",
            (bytes(res.transactionHash),))
        ret = TransactionMeta.from_bytes(
            bytes(row[0])).value.sorobanMeta.returnValue
        assert ret.disc == cx.SCValType.SCV_VEC and len(ret.value) == 2
        uv, iv = ret.value
        assert uv.disc == cx.SCValType.SCV_U256
        got = (int(uv.value.hi_hi) << 192) | (int(uv.value.hi_lo) << 128) \
            | (int(uv.value.lo_hi) << 64) | int(uv.value.lo_lo)
        assert got == (((1 << 192) + (2 << 128) + (3 << 64) + 9) << 7)
        assert iv.disc == cx.SCValType.SCV_I256
        u = ((int(iv.value.hi_hi) & ((1 << 64) - 1)) << 192) | \
            (int(iv.value.hi_lo) << 128) | \
            (int(iv.value.lo_hi) << 64) | int(iv.value.lo_lo)
        assert u - (1 << 256) == -(1 << 255) >> 3
        # checked division: div-by-zero becomes a failed tx, not a wrong
        # answer
        res = ts_mod.submit_and_close(app, ts_mod.soroban_tx(
            app, master, ts_mod.invoke_op(cid, "div_zero"), ro, rw))
        assert res.result.result.disc.name == "txFAILED", res
    finally:
        ts_mod.COUNTER_CODE = old
