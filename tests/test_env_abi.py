"""Real soroban-env ABI tests (VERDICT r02 #2).

Three tiers:
 1. Val-encoding unit tests against the facts recovered from the
    reference's SDK-built binaries (tags in the low 4 bits, U32 tag 3,
    symbol tag 9, `return 5` void idiom).
 2. The in-repo hand-assembled env-ABI counter contract
    (soroban/env_contract.py) through the SAME upload→create→invoke
    scenario matrix the scvm/wasm twins run in tests/test_soroban.py —
    storage, traps, auth, events, budget — plus bulk-memory coverage.
 3. Acceptance: the reference's ACTUAL vendored SDK-built wasm binaries
    (read at test time from /root/reference, never copied into the
    repo) deploy and execute on this VM — the "run a real-ecosystem
    contract" capability. Loud skip when the reference tree is absent.
"""

import os

import pytest

from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.soroban import env_abi
from stellar_core_tpu.soroban.env_contract import (COPY_HASH_PREIMAGE,
                                                   build_env_counter)
from stellar_core_tpu.xdr import contract as cx

import test_soroban as ts

REF_TESTDATA = "/root/reference/src/testdata"


# ---------------------------------------------------------------- tier 1 --
def test_val_encoding_ground_truth():
    # the observed constants: tag 3 = I32 (the reference invokes
    # add_i32 with makeI32; the contract overflow-checks SIGNED add)
    assert env_abi.TAG_I32 == 3 and env_abi.TAG_SYMBOL == 9
    assert env_abi.VAL_VOID == 5            # both reference contracts
    v = (12345 << 4) | 3
    assert env_abi.EnvCtx(None, None, [None]).from_val(v) == \
        cx.SCVal(cx.SCValType.SCV_I32, 12345)
    neg = ((-7 & 0xFFFFFFFF) << 4) | 3
    assert env_abi.EnvCtx(None, None, [None]).from_val(neg) == \
        cx.SCVal(cx.SCValType.SCV_I32, -7)


def test_symbol_roundtrip():
    for name in (b"count", b"a", b"_", b"Z9z_", b"abcdefghij"):
        val = env_abi.symbol_to_val(name)
        assert val is not None and val & 0xF == env_abi.TAG_SYMBOL
        assert env_abi.val_to_symbol(val) == name
    assert env_abi.symbol_to_val(b"elevenchars") is None      # too long
    assert env_abi.symbol_to_val(b"sp ace") is None           # bad char


def test_scval_val_bridge_roundtrip():
    ectx = env_abi.EnvCtx(None, None, [cx.SCVal(cx.SCValType.SCV_VOID)])
    cases = [
        cx.SCVal(cx.SCValType.SCV_VOID),
        cx.SCVal(cx.SCValType.SCV_BOOL, True),
        cx.SCVal(cx.SCValType.SCV_BOOL, False),
        cx.SCVal(cx.SCValType.SCV_U32, 0),
        cx.SCVal(cx.SCValType.SCV_U32, 0xFFFFFFFF),
        cx.SCVal(cx.SCValType.SCV_I32, -1),
        cx.SCVal(cx.SCValType.SCV_I32, 2**31 - 1),
        cx.SCVal(cx.SCValType.SCV_SYMBOL, b"hello"),
        cx.SCVal(cx.SCValType.SCV_U64, 2**40),      # via object handle
        cx.SCVal(cx.SCValType.SCV_BYTES, b"\x00\x01"),
    ]
    for v in cases:
        assert ectx.from_val(ectx.to_val(v)) == v


def test_env_abi_module_detection():
    from stellar_core_tpu.soroban.env_abi import is_env_abi_module
    from stellar_core_tpu.soroban.wasm import decode
    m = decode.decode_module(build_env_counter())
    assert is_env_abi_module(m)
    # the scvm_wasm twin uses the bespoke long-name module
    m2 = decode.decode_module(ts.CODE_BUILDS["wasm"])
    assert not is_env_abi_module(m2)


# ---------------------------------------------------------------- tier 2 --
@pytest.fixture
def app():
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock

    old = ts.COUNTER_CODE
    ts.COUNTER_CODE = build_env_counter()
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    cfg = get_test_config()
    try:
        with Application.create(clock, cfg) as a:
            a.start()
            yield a
    finally:
        ts.COUNTER_CODE = old


def test_env_counter_full_matrix(app):
    """upload → create → invoke ×2 → trap — mirroring the twins."""
    master, cid = ts.deploy(app)
    ro, rw = ts.invoke_footprints(cid)

    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "increment"), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "increment"), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res

    # stored count is a real SCVal in the contract-data entry
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    with LedgerTxn(app.ledger_manager.root) as ltx:
        le = ltx.load_without_record(ts.counter_key(cid))
        assert le is not None
        assert le.data.value.val == cx.SCVal(cx.SCValType.SCV_U32, 2)

    # get_count returns it
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "get_count"), ro + rw, []))
    assert res.result.result.disc.name == "txSUCCESS", res

    # boom traps the tx (fail_with_error path)
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "boom"), ro, rw))
    assert res.result.result.disc.name == "txFAILED", res


def test_env_counter_budget_exhaustion(app):
    master, cid = ts.deploy(app)
    ro, rw = ts.invoke_footprints(cid)
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "increment"), ro, rw,
        instructions=10))
    assert res.result.result.disc.name == "txFAILED", res


def test_env_counter_auth_and_event(app):
    master, cid = ts.deploy(app)
    ro, rw = ts.invoke_footprints(cid)
    addr_val = cx.SCVal(
        cx.SCValType.SCV_ADDRESS,
        cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_ACCOUNT,
                     master.account_id))
    body = ts.invoke_op(cid, "auth_bump", [addr_val])
    op = body.value
    op.auth = [cx.SorobanAuthorizationEntry(
        credentials=cx.SorobanCredentials(
            cx.SorobanCredentialsType.SOROBAN_CREDENTIALS_SOURCE_ACCOUNT),
        rootInvocation=cx.SorobanAuthorizedInvocation(
            function=cx.SorobanAuthorizedFunction(
                cx.SorobanAuthorizedFunctionType
                .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN,
                cx.InvokeContractArgs(
                    contractAddress=cx.SCAddress(
                        cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid),
                    functionName=b"auth_bump", args=[addr_val])),
            subInvocations=[]))]
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, body, ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res


def test_env_counter_bulk_memory(app):
    """memory.init / fill / copy feed bytes_new + sha256; data.drop
    then memory.init traps."""
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    from stellar_core_tpu.xdr.ledger_entries import LedgerKey

    master, cid = ts.deploy(app)
    ro, rw = ts.invoke_footprints(cid)
    addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid)
    hash_key = LedgerKey.contract_data(
        addr, cx.SCVal(cx.SCValType.SCV_SYMBOL, b"hash"),
        cx.ContractDataDurability.PERSISTENT)
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "copy_hash"), ro,
        rw + [hash_key]))
    assert res.result.result.disc.name == "txSUCCESS", res
    with LedgerTxn(app.ledger_manager.root) as ltx:
        le = ltx.load_without_record(hash_key)
        assert le is not None
        assert le.data.value.val == cx.SCVal(
            cx.SCValType.SCV_BYTES, sha256(COPY_HASH_PREIMAGE))

    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "drop_then_init"), ro, rw))
    assert res.result.result.disc.name == "txFAILED", res


# ---------------------------------------------------------------- tier 3 --
needs_reference = pytest.mark.skipif(
    not os.path.isdir(REF_TESTDATA),
    reason="SKIPPED LOUDLY: /root/reference testdata not present — the "
           "SDK-built wasm acceptance tier needs the reference snapshot")


@needs_reference
def test_reference_sdk_contract_add_i32_direct():
    """The reference's actual SDK-built example_add_i32.wasm executes
    on this VM (it imports nothing, so the raw Instance + Val encoding
    suffices): add(U32Val 5, U32Val 7) == U32Val 12, and u32 overflow
    hits the contract's own `unreachable`."""
    from stellar_core_tpu.soroban.wasm import (Instance, WasmTrap,
                                               decode_module,
                                               validate_module)
    with open(os.path.join(REF_TESTDATA, "example_add_i32.wasm"),
              "rb") as f:
        code = f.read()
    m = decode_module(code)
    validate_module(m)
    assert env_abi.is_env_abi_module(m)
    inst = Instance(m, imports={})
    i32 = lambda n: ((n & 0xFFFFFFFF) << 4) | env_abi.TAG_I32  # noqa: E731
    out = inst.invoke("add", [i32(5), i32(7)])
    assert out == [i32(12)]
    with pytest.raises(WasmTrap):                  # INT32_MAX + 1
        Instance(m, imports={}).invoke(
            "add", [i32(2**31 - 1), i32(1)])
    # non-I32 tag rejected by the contract's own check
    with pytest.raises(WasmTrap):
        Instance(m, imports={}).invoke("add", [env_abi.VAL_VOID, i32(1)])


@needs_reference
def test_reference_sdk_contract_add_i32_deployed(app):
    """Same binary through the full upload→create→invoke tx flow."""
    with open(os.path.join(REF_TESTDATA, "example_add_i32.wasm"),
              "rb") as f:
        ts.COUNTER_CODE = f.read()
    master, cid = ts.deploy(app)
    ro, _rw = ts.invoke_footprints(cid)
    args = [cx.SCVal(cx.SCValType.SCV_I32, 5),
            cx.SCVal(cx.SCValType.SCV_I32, 7)]
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "add", args), ro, []))
    assert res.result.result.disc.name == "txSUCCESS", res

    # the reference's "failed invocation with diagnostics" scenario:
    # INT32_MAX + 7 overflows and the invocation fails
    args = [cx.SCVal(cx.SCValType.SCV_I32, 2**31 - 1),
            cx.SCVal(cx.SCValType.SCV_I32, 7)]
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "add", args), ro, []))
    assert res.result.result.disc.name == "txFAILED", res


@needs_reference
def test_reference_sdk_contract_contract_data(app):
    """example_contract_data.wasm: put/del through ("l","_")/("l","2")
    — the imports that pinned the ledger-module function order."""
    with open(os.path.join(REF_TESTDATA, "example_contract_data.wasm"),
              "rb") as f:
        ts.COUNTER_CODE = f.read()
    master, cid = ts.deploy(app)
    ro, _rw = ts.invoke_footprints(cid)
    addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid)
    key = cx.SCVal(cx.SCValType.SCV_SYMBOL, b"key")
    val = cx.SCVal(cx.SCValType.SCV_SYMBOL, b"val")
    from stellar_core_tpu.xdr.ledger_entries import LedgerKey
    dk = LedgerKey.contract_data(
        addr, key, cx.ContractDataDurability.PERSISTENT)

    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "put", [key, val]), ro, [dk]))
    assert res.result.result.disc.name == "txSUCCESS", res
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    with LedgerTxn(app.ledger_manager.root) as ltx:
        le = ltx.load_without_record(dk)
        assert le is not None and le.data.value.val == val

    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "del", [key]), ro, [dk]))
    assert res.result.result.disc.name == "txSUCCESS", res
    with LedgerTxn(app.ledger_manager.root) as ltx:
        assert ltx.load_without_record(dk) is None

    # non-symbol key: the contract's own tag check hits `unreachable`
    bad = cx.SCVal(cx.SCValType.SCV_U32, 1)
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "put", [bad, val]), ro, [dk]))
    assert res.result.result.disc.name == "txFAILED", res
