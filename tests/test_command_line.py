"""CLI + HTTP admin server smoke tests (reference: main/CommandLine.cpp
subcommands, CommandHandler HTTP binding)."""

import json
import urllib.request

from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.main.command_handler import run_http_server
from stellar_core_tpu.main.command_line import main
from stellar_core_tpu.util.timer import ClockMode, VirtualClock


def test_version_and_keys(capsys):
    assert main(["version"]) == 0
    assert main(["gen-seed"]) == 0
    out = capsys.readouterr().out
    assert "Secret seed: S" in out and "Public: G" in out


def test_convert_id_roundtrip(capsys):
    main(["gen-seed"])
    pub = capsys.readouterr().out.splitlines()[1].split()[-1]
    assert main(["convert-id", pub]) == 0
    info = json.loads(capsys.readouterr().out)
    assert info["strkey"] == pub
    assert main(["convert-id", info["hex"]]) == 0
    info2 = json.loads(capsys.readouterr().out)
    assert info2["strkey"] == pub


def test_new_db(tmp_path, capsys):
    import tomllib  # ensure toml config path parses

    conf = tmp_path / "node.cfg"
    conf.write_text(
        f'DATABASE = "sqlite3://{tmp_path}/x.db"\n'
        'NETWORK_PASSPHRASE = "test net"\n')
    assert main(["--conf", str(conf), "new-db"]) == 0
    assert (tmp_path / "x.db").exists()


def test_http_server_round_trip():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    cfg = get_test_config()
    with Application.create(clock, cfg) as app:
        app.start()
        thread = run_http_server(app.command_handler, 0)
        try:
            port = thread.server.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/info") as resp:
                info = json.loads(resp.read())
            assert info["info"]["ledger"]["num"] == 1
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/manualclose") as resp:
                json.loads(resp.read())
            assert app.ledger_manager.get_last_closed_ledger_num() == 2
        finally:
            thread.server.shutdown()
