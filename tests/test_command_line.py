"""CLI + HTTP admin server smoke tests (reference: main/CommandLine.cpp
subcommands, CommandHandler HTTP binding)."""

import json
import urllib.request

from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.main.command_handler import run_http_server
from stellar_core_tpu.main.command_line import main
from stellar_core_tpu.util.timer import ClockMode, VirtualClock


def test_version_and_keys(capsys):
    assert main(["version"]) == 0
    assert main(["gen-seed"]) == 0
    out = capsys.readouterr().out
    assert "Secret seed: S" in out and "Public: G" in out


def test_convert_id_roundtrip(capsys):
    main(["gen-seed"])
    pub = capsys.readouterr().out.splitlines()[1].split()[-1]
    assert main(["convert-id", pub]) == 0
    info = json.loads(capsys.readouterr().out)
    assert info["strkey"] == pub
    assert main(["convert-id", info["hex"]]) == 0
    info2 = json.loads(capsys.readouterr().out)
    assert info2["strkey"] == pub


def test_new_db(tmp_path, capsys):
    from stellar_core_tpu.main.config import tomllib
    if tomllib is None:   # no TOML parser on this interpreter (<3.11)
        import pytest
        pytest.skip("no tomllib/tomli available")

    conf = tmp_path / "node.cfg"
    conf.write_text(
        f'DATABASE = "sqlite3://{tmp_path}/x.db"\n'
        'NETWORK_PASSPHRASE = "test net"\n')
    assert main(["--conf", str(conf), "new-db"]) == 0
    assert (tmp_path / "x.db").exists()


def test_http_server_round_trip():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    cfg = get_test_config()
    with Application.create(clock, cfg) as app:
        app.start()
        thread = run_http_server(app.command_handler, 0)
        try:
            port = thread.server.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/info") as resp:
                info = json.loads(resp.read())
            assert info["info"]["ledger"]["num"] == 1
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/manualclose") as resp:
                json.loads(resp.read())
            assert app.ledger_manager.get_last_closed_ledger_num() == 2
        finally:
            thread.server.shutdown()


def _file_node_cfg(tmp_path):
    conf = tmp_path / "node.cfg"
    conf.write_text(
        f'DATABASE = "sqlite3://{tmp_path}/node.db"\n'
        f'BUCKET_DIR_PATH = "{tmp_path}/buckets"\n'
        'NETWORK_PASSPHRASE = "cli test net"\n'
        'RUN_STANDALONE = true\nMANUAL_CLOSE = true\n')
    return conf


def _populated_node(tmp_path):
    """Close a few ledgers into a file-backed DB and return the conf."""
    import test_standalone_app as m1
    from txtest_utils import op_create_account, op_payment
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.main.config import Config

    conf = _file_node_cfg(tmp_path)
    cfg = Config.load(str(conf))
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    master = m1.master_account(app)
    dest = m1.AppAccount(app, SecretKey.from_seed(b"\x21" * 32))
    m1.submit(app, master.tx([op_create_account(dest.account_id, 10**9)]))
    app.manual_close()
    dest.sync_seq()
    m1.submit(app, dest.tx([op_payment(master.muxed, 77)]))
    app.manual_close()
    app.shutdown()
    return conf


def test_encode_asset(capsys):
    import base64
    from stellar_core_tpu.xdr.ledger_entries import Asset, AssetType

    assert main(["encode-asset"]) == 0
    out = capsys.readouterr().out.strip()
    assert Asset.from_bytes(base64.b64decode(out)).disc == \
        AssetType.ASSET_TYPE_NATIVE

    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.crypto.strkey import StrKey
    issuer = StrKey.encode_ed25519_public(
        SecretKey.from_seed(b"\x01" * 32).public_key().raw)
    assert main(["encode-asset", "--code", "USD",
                 "--issuer", issuer]) == 0
    out = capsys.readouterr().out.strip()
    a = Asset.from_bytes(base64.b64decode(out))
    assert a.disc == AssetType.ASSET_TYPE_CREDIT_ALPHANUM4
    assert bytes(a.value.assetCode).rstrip(b"\x00") == b"USD"

    assert main(["encode-asset", "--code", "USD"]) == 1


def test_sign_transaction(tmp_path, capsys):
    import base64
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.crypto.strkey import StrKey
    from stellar_core_tpu.tx.frame import TransactionFrame
    from stellar_core_tpu.xdr.transaction import TransactionEnvelope
    from txtest_utils import op_payment
    import test_standalone_app as m1

    # unsigned single-payment envelope from the shared test helpers
    cfg = get_test_config()
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    master = m1.master_account(app)
    frame = master.tx([op_payment(master.muxed, 1)])
    env = frame.envelope
    env.value.signatures.clear()
    f = tmp_path / "tx.b64"
    f.write_text(base64.b64encode(env.to_bytes()).decode())
    app.shutdown()

    seed = StrKey.encode_ed25519_seed(b"\x01" * 32)
    assert main(["sign-transaction", str(f), "--netid",
                 cfg.NETWORK_PASSPHRASE, "--base64",
                 "--seed", seed]) == 0
    out = capsys.readouterr().out.strip()
    signed = TransactionEnvelope.from_bytes(base64.b64decode(out))
    assert len(signed.value.signatures) == 1
    # signature verifies against the tx contents hash
    from stellar_core_tpu.crypto.keys import PubKeyUtils
    sk = SecretKey.from_seed(b"\x01" * 32)
    tf = TransactionFrame(signed, cfg.network_id())
    assert PubKeyUtils.verify_sig(
        sk.public_key().raw,
        bytes(signed.value.signatures[0].signature),
        tf.contents_hash())


def test_offline_info(tmp_path, capsys):
    conf = _populated_node(tmp_path)
    assert main(["--conf", str(conf), "offline-info"]) == 0
    info = json.loads(capsys.readouterr().out)
    assert info["ledger"]["num"] == 3


def test_dump_ledger_filter_and_agg(tmp_path, capsys):
    conf = _populated_node(tmp_path)
    out_file = tmp_path / "dump.json"

    # full dump
    assert main(["--conf", str(conf), "dump-ledger",
                 "--output-file", str(out_file)]) == 0
    lines = [json.loads(l) for l in out_file.read_text().splitlines()]
    types = {l["data"]["type"] for l in lines}
    assert "ACCOUNT" in types
    assert len(lines) >= 2  # master + dest

    # filtered
    assert main(["--conf", str(conf), "dump-ledger",
                 "--output-file", str(out_file),
                 "--filter-query",
                 "data.account.balance < 1000000000"]) == 0
    lines = [json.loads(l) for l in out_file.read_text().splitlines()]
    assert all(l["data"]["account"]["balance"] < 10**9 for l in lines)

    # aggregated by type
    assert main(["--conf", str(conf), "dump-ledger",
                 "--output-file", str(out_file),
                 "--group-by", "data.type",
                 "--agg", "count(), sum(data.account.balance)"]) == 0
    rows = [json.loads(l) for l in out_file.read_text().splitlines()]
    acct = [r for r in rows if r["data.type"] == "ACCOUNT"]
    assert acct and acct[0]["count"] >= 2

    # --group-by without --agg is rejected
    assert main(["--conf", str(conf), "dump-ledger",
                 "--group-by", "data.type"]) == 1


def test_dump_ledger_last_modified_count(tmp_path):
    conf = _populated_node(tmp_path)  # LCL = 3
    out_file = tmp_path / "dump.json"
    # count=1 → only entries touched in ledger 3 (the payment pair)
    assert main(["--conf", str(conf), "dump-ledger",
                 "--output-file", str(out_file),
                 "--last-modified-ledger-count", "1"]) == 0
    lines = [json.loads(l) for l in out_file.read_text().splitlines()]
    assert lines and all(l["lastModifiedLedgerSeq"] == 3 for l in lines)


def test_dump_ledger_bad_query_preserves_output(tmp_path):
    conf = _populated_node(tmp_path)
    out_file = tmp_path / "dump.json"
    out_file.write_text("precious\n")
    from stellar_core_tpu.util.xdrquery import XDRQueryError
    import pytest as _pytest
    with _pytest.raises(XDRQueryError):
        main(["--conf", str(conf), "dump-ledger",
              "--output-file", str(out_file),
              "--filter-query", "data.bogus == 1"])
    assert out_file.read_text() == "precious\n"


def test_history_diag_commands(tmp_path, capsys):
    """new-hist / report-last-history-checkpoint / verify-checkpoints /
    diag-bucket-stats / merge-bucketlist / rebuild-ledger-from-buckets
    (reference: CommandLine.cpp subcommand list :1638-1698)."""
    import os
    import test_standalone_app as m1
    from txtest_utils import op_create_account
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.main.config import Config

    archive_root = tmp_path / "archive"
    conf = tmp_path / "node.cfg"
    conf.write_text(
        f'DATABASE = "sqlite3://{tmp_path}/node.db"\n'
        f'BUCKET_DIR_PATH = "{tmp_path}/buckets"\n'
        'NETWORK_PASSPHRASE = "diag test net"\n'
        'RUN_STANDALONE = true\nMANUAL_CLOSE = true\n'
        '[HISTORY.test]\n'
        f'get = "cp {archive_root}/{{0}} {{1}}"\n'
        f'put = "mkdir -p $(dirname {archive_root}/{{1}}) && '
        f'cp {{0}} {archive_root}/{{1}}"\n')

    # new-hist initializes, double-init refuses
    assert main(["--conf", str(conf), "new-hist", "test"]) == 0
    capsys.readouterr()
    assert (archive_root / ".well-known/stellar-history.json").exists()
    assert main(["--conf", str(conf), "new-hist", "test"]) == 1
    capsys.readouterr()

    # close past one checkpoint so a real publish lands
    cfg = Config.load(str(conf))
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    master = m1.master_account(app)
    dest = m1.AppAccount(app, SecretKey.from_seed(b"\x31" * 32))
    m1.submit(app, master.tx([op_create_account(dest.account_id, 10**9)]))
    for _ in range(2, 65):
        app.manual_close()
    assert app.history_manager.published_count >= 1
    app.shutdown()

    # report-last-history-checkpoint
    assert main(["--conf", str(conf),
                 "report-last-history-checkpoint"]) == 0
    has = json.loads(capsys.readouterr().out)
    assert has["currentLedger"] == 63

    # verify-checkpoints writes trusted pairs
    out = tmp_path / "trusted.json"
    assert main(["--conf", str(conf), "verify-checkpoints",
                 "--output-file", str(out)]) == 0
    capsys.readouterr()
    pairs = json.loads(out.read_text())
    assert [63, ] == [p[0] for p in pairs][-1:] and len(pairs[0][1]) == 64

    # diag-bucket-stats on a published bucket file
    import glob
    bucket_files = glob.glob(str(tmp_path / "buckets" / "bucket-*.xdr"))
    assert bucket_files
    assert main(["diag-bucket-stats", bucket_files[0],
                 "--aggregate-account-stats"]) == 0
    stats = json.loads(capsys.readouterr().out)
    assert sum(stats["bucketEntries"].values()) > 0

    # merge-bucketlist
    outdir = tmp_path / "merged"
    os.makedirs(outdir)
    assert main(["--conf", str(conf), "merge-bucketlist",
                 "--output-dir", str(outdir)]) == 0
    capsys.readouterr()
    merged = glob.glob(str(outdir / "bucket-*.xdr"))
    assert len(merged) == 1

    # rebuild-ledger-from-buckets reproduces the SQL state
    cfg2 = Config.load(str(conf))
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg2)
    app.start()
    before = app.database.query_one("SELECT COUNT(*) FROM accounts")[0]
    app.shutdown()
    assert main(["--conf", str(conf),
                 "rebuild-ledger-from-buckets"]) == 0
    capsys.readouterr()
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             Config.load(str(conf)))
    app.start()
    after = app.database.query_one("SELECT COUNT(*) FROM accounts")[0]
    balance = m1.app_account_entry(app, dest.account_id).balance
    # lastModifiedLedgerSeq must be preserved from the buckets, not
    # restamped to the LCL (dest was created in ledger 2; ltx.load()
    # would stamp, so read the raw SQL column)
    assert set(app.database.query_all(
        "SELECT lastmodified FROM accounts")) == {(2,)}
    app.shutdown()
    assert after == before
    assert balance == 10**9


def test_replay_debug_meta_and_upgrade_db(tmp_path, capsys):
    """Debug-meta rotation + replay-debug-meta round trip (reference:
    FlushAndRotateMetaDebugWork, ReplayDebugMetaWork) and upgrade-db."""
    import os
    import shutil
    import test_standalone_app as m1
    from txtest_utils import op_create_account, op_payment
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.main.config import Config

    def write_conf(d):
        conf = d / "node.cfg"
        conf.write_text(
            f'DATABASE = "sqlite3://{d}/node.db"\n'
            f'BUCKET_DIR_PATH = "{d}/buckets"\n'
            'NETWORK_PASSPHRASE = "meta test net"\n'
            'RUN_STANDALONE = true\nMANUAL_CLOSE = true\n'
            'METADATA_DEBUG_LEDGERS = 256\n')
        return conf

    d1 = tmp_path / "node1"
    d2 = tmp_path / "node2"
    os.makedirs(d1)
    conf1 = write_conf(d1)

    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             Config.load(str(conf1)))
    app.start()
    master = m1.master_account(app)
    dest = m1.AppAccount(app, SecretKey.from_seed(b"\x51" * 32))
    m1.submit(app, master.tx([op_create_account(dest.account_id, 10**9)]))
    for _ in range(2, 5):
        app.manual_close()  # LCL 4
    app.shutdown()

    # snapshot at ledger 4 → node2
    shutil.copytree(d1, d2)
    conf2 = write_conf(d2)

    # node1 continues to ledger 12 with a few payments
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             Config.load(str(conf1)), new_db=False)
    app.start()
    dest2 = m1.AppAccount(app, SecretKey.from_seed(b"\x51" * 32))
    dest2.sync_seq()
    for i in range(5, 13):
        if i % 2:
            m1.submit(app, dest2.tx([op_payment(
                m1.master_account(app).muxed, 100)]))
        app.manual_close()
    final_lcl = app.ledger_manager.get_last_closed_ledger_num()
    final_hash = app.ledger_manager.get_last_closed_ledger_hash()
    assert final_lcl == 12
    # debug meta exists
    assert os.path.isdir(d1 / "buckets" / "meta-debug")
    app.shutdown()

    # bring node1's debug meta over and replay on the snapshot
    shutil.rmtree(d2 / "buckets" / "meta-debug", ignore_errors=True)
    shutil.copytree(d1 / "buckets" / "meta-debug",
                    d2 / "buckets" / "meta-debug")
    assert main(["--conf", str(conf2), "replay-debug-meta",
                 "--meta-dir", str(d2 / "buckets")]) == 0
    out = capsys.readouterr().out
    assert "replayed 8 ledgers" in out

    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             Config.load(str(conf2)), new_db=False)
    app.start()
    assert app.ledger_manager.get_last_closed_ledger_num() == final_lcl
    assert app.ledger_manager.get_last_closed_ledger_hash() == final_hash
    app.shutdown()

    # upgrade-db reports current schema
    assert main(["--conf", str(conf1), "upgrade-db"]) == 0
    assert "schema version" in capsys.readouterr().out


def test_debug_meta_survives_crash_truncated_tail(tmp_path, capsys):
    """A partial tail record (hard kill mid-write) is dropped on reopen
    so post-restart records stay readable by replay."""
    import os
    import test_standalone_app as m1  # noqa: F401  (env init)
    from stellar_core_tpu.main.config import Config

    d = tmp_path / "node"
    os.makedirs(d)
    conf = d / "node.cfg"
    conf.write_text(
        f'DATABASE = "sqlite3://{d}/node.db"\n'
        f'BUCKET_DIR_PATH = "{d}/buckets"\n'
        'NETWORK_PASSPHRASE = "crash net"\n'
        'RUN_STANDALONE = true\nMANUAL_CLOSE = true\n'
        'METADATA_DEBUG_LEDGERS = 256\n')
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             Config.load(str(conf)))
    app.start()
    for _ in range(3):
        app.manual_close()  # LCL 4
    app.shutdown()

    # simulate a crash that left half a record at the tail
    meta_dir = d / "buckets" / "meta-debug"
    seg = sorted(meta_dir.iterdir())[0]
    with open(seg, "ab") as f:
        f.write(b"\x00\x00\x01")  # partial length prefix

    # restart and close more ledgers (appends after tail cleanup)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             Config.load(str(conf)), new_db=False)
    app.start()
    for _ in range(3):
        app.manual_close()  # LCL 7
    final_hash = app.ledger_manager.get_last_closed_ledger_hash()
    app.shutdown()

    # a fresh node replays the whole file through ledger 7
    d2 = tmp_path / "node2"
    os.makedirs(d2)
    conf2 = d2 / "node.cfg"
    conf2.write_text(
        f'DATABASE = "sqlite3://{d2}/node.db"\n'
        f'BUCKET_DIR_PATH = "{d2}/buckets"\n'
        'NETWORK_PASSPHRASE = "crash net"\n'
        'RUN_STANDALONE = true\nMANUAL_CLOSE = true\n')
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             Config.load(str(conf2)))
    app.start()
    app.shutdown()
    import shutil
    shutil.copytree(meta_dir, d2 / "buckets" / "meta-debug")
    assert main(["--conf", str(conf2), "replay-debug-meta",
                 "--meta-dir", str(d2 / "buckets")]) == 0
    assert "replayed 6 ledgers" in capsys.readouterr().out
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             Config.load(str(conf2)), new_db=False)
    app.start()
    assert app.ledger_manager.get_last_closed_ledger_num() == 7
    assert app.ledger_manager.get_last_closed_ledger_hash() == final_hash
    app.shutdown()


def test_admin_routes_scp_ledgerentry_load_perf(tmp_path):
    """New admin routes: scp, getledgerentry, generateload, droppeer,
    perf (reference: CommandHandler routes :87-125)."""
    import base64
    from stellar_core_tpu.simulation import topologies
    from stellar_core_tpu.util.perf import reset_zones

    reset_zones()
    # SCP network of 3 for the scp route
    sim = topologies.core(3)
    sim.start_all_nodes()
    try:
        sim.crank_until(lambda: sim.have_all_externalized(2), 60)
        app = sim.apps()[0]
        out = app.command_handler.handle("scp", {"limit": "1"})
        assert "slots" in out["scp"] and out["scp"]["slots"]
        slot = next(iter(out["scp"]["slots"].values()))
        assert slot["phase"] == "SCP_PHASE_EXTERNALIZE"

        # getledgerentry on the master account
        from stellar_core_tpu.crypto.keys import SecretKey
        from stellar_core_tpu.xdr.ledger_entries import (LedgerEntry,
                                                         LedgerEntryType,
                                                         LedgerKey,
                                                         _LedgerKeyAccount)
        from stellar_core_tpu.xdr.types import PublicKey
        master = SecretKey.from_seed(app.config.network_id())
        key = LedgerKey(LedgerEntryType.ACCOUNT, _LedgerKeyAccount(
            accountID=PublicKey.ed25519(master.public_key().raw)))
        out = app.command_handler.handle(
            "getledgerentry",
            {"key": base64.b64encode(key.to_bytes()).decode()})
        assert out["state"] == "live"
        le = LedgerEntry.from_bytes(base64.b64decode(out["entry"]))
        assert le.data.value.balance > 0

        # a bogus key reports dead
        key2 = LedgerKey(LedgerEntryType.ACCOUNT, _LedgerKeyAccount(
            accountID=PublicKey.ed25519(b"\x99" * 32)))
        out = app.command_handler.handle(
            "getledgerentry",
            {"key": base64.b64encode(key2.to_bytes()).decode()})
        assert out["state"] == "dead"

        # generateload create + pay
        out = app.command_handler.handle(
            "generateload", {"mode": "create", "accounts": "5"})
        assert out["status"] == "ok" and out["submitted"] == 5
        sim.crank_until(lambda: False, 3)  # let a ledger close
        out = app.command_handler.handle(
            "generateload", {"mode": "pay", "txs": "5"})
        assert out["status"] == "ok"

        # perf zones populated by the consensus traffic above
        out = app.command_handler.handle("perf", {})
        assert "herder.recvSCPEnvelope" in out["perf"]
        assert "ledger.closeLedger" in out["perf"]
        assert out["perf"]["ledger.closeLedger"]["count"] >= 2

        # droppeer on an unknown id is a no-op success
        from stellar_core_tpu.crypto.strkey import StrKey
        out = app.command_handler.handle("droppeer", {
            "node": StrKey.encode_ed25519_public(b"\x77" * 32)})
        assert out["status"] == "ok" and out["dropped"] == 0
    finally:
        sim.stop_all_nodes()


def test_diff_perf_script(tmp_path):
    """scripts/diff_perf.py (DiffTracyCSV analogue) diffs two perf-route
    dumps."""
    import json as _json
    import pathlib
    import subprocess
    import sys as _sys
    script = pathlib.Path(__file__).resolve().parents[1] / "scripts" / \
        "diff_perf.py"
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(_json.dumps({"perf": {
        "myzone": {"count": 1, "total_ms": 10.0, "mean_ms": 10.0,
                   "max_ms": 10.0}}}))
    b.write_text(_json.dumps({"perf": {
        "myzone": {"count": 3, "total_ms": 40.0, "mean_ms": 13.3,
                   "max_ms": 20.0}}}))
    out = subprocess.run(
        [_sys.executable, str(script), str(a), str(b)],
        capture_output=True, text=True)
    assert out.returncode == 0
    assert "+30.000" in out.stdout and "myzone" in out.stdout
