"""Real-socket overlay tests: handshake + consensus over localhost TCP
(reference: Simulation OVER_TCP mode)."""

import os

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.main import Application, Config, QuorumSetConfig
from stellar_core_tpu.util.timer import ClockMode, VirtualClock

PASSPHRASE = "tcp overlay test"


def make_tcp_apps(n, threshold, base_port):
    clock = VirtualClock(ClockMode.REAL_TIME)
    seeds = [SecretKey.from_seed(sha256(b"tcp-%d-%d" % (base_port, i)))
             for i in range(n)]
    node_ids = [s.public_key().raw for s in seeds]
    apps = []
    for i in range(n):
        cfg = Config()
        cfg.NETWORK_PASSPHRASE = PASSPHRASE
        cfg.NODE_SEED = seeds[i]
        cfg.NODE_IS_VALIDATOR = True
        cfg.RUN_STANDALONE = False       # TCP overlay active
        cfg.FORCE_SCP = True
        cfg.MANUAL_CLOSE = False
        cfg.EXPECTED_LEDGER_CLOSE_TIME = 0.3
        cfg.INVARIANT_CHECKS = [".*"]
        cfg.ALLOW_LOCALHOST_FOR_TESTING = True
        cfg.PEER_PORT = base_port + i
        # later nodes dial earlier ones
        cfg.KNOWN_PEERS = [f"127.0.0.1:{base_port + j}" for j in range(i)]
        cfg.QUORUM_SET = QuorumSetConfig(threshold=threshold,
                                         validators=list(node_ids))
        apps.append(Application.create(clock, cfg))
    return clock, apps


def crank_real(clock, pred, timeout_s=15.0):
    import time
    deadline = time.monotonic() + timeout_s
    while not pred() and time.monotonic() < deadline:
        clock.crank(True)
    return pred()


def test_tcp_handshake_and_consensus():
    clock, apps = make_tcp_apps(3, 2, 36100)
    try:
        for app in apps:
            app.start()
        # all peers authenticate over real sockets
        assert crank_real(clock, lambda: all(
            len(a.overlay_manager.get_authenticated_peers()) == 2
            for a in apps), timeout_s=10)
        # and the network closes ledgers
        assert crank_real(clock, lambda: all(
            a.ledger_manager.get_last_closed_ledger_num() >= 3
            for a in apps), timeout_s=20)
        hashes = set()
        for app in apps:
            row = app.database.query_one(
                "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=2")
            hashes.add(bytes(row[0]))
        assert len(hashes) == 1
    finally:
        for app in apps:
            app.shutdown()


def test_overlay_survey_script_walks_network(tmp_path):
    """scripts/overlay_survey.py walks a live 3-node TCP network via the
    admin HTTP endpoints (reference: scripts/OverlaySurvey.py)."""
    import json
    import subprocess
    import sys
    import threading

    from stellar_core_tpu.main.command_handler import run_http_server

    clock, apps = make_tcp_apps(3, 2, 36300)
    try:
        for app in apps:
            app.start()
        assert crank_real(clock, lambda: all(
            len(a.overlay_manager.get_authenticated_peers()) == 2
            for a in apps), timeout_s=10)
        http = run_http_server(apps[0].command_handler, 0)
        port = http.server.server_address[1]
        stop = threading.Event()

        def crank_loop():
            while not stop.is_set():
                clock.crank(True)

        t = threading.Thread(target=crank_loop, daemon=True)
        t.start()
        try:
            out_file = tmp_path / "graph.json"
            script = os.path.join(os.path.dirname(__file__), "..",
                                  "scripts", "overlay_survey.py")
            res = subprocess.run(
                [sys.executable, script,
                 "--node", f"http://127.0.0.1:{port}",
                 "--out", str(out_file),
                 "--max-rounds", "4", "--wait", "1.0"],
                capture_output=True, text=True, timeout=60)
            assert res.returncode == 0, res.stderr
            graph = json.loads(out_file.read_text())
            # both peers of node 0 appear; at least one responded
            assert graph["stats"]["nodes"] >= 2
            assert graph["stats"]["responses"] >= 1
            assert graph["edges"]
        finally:
            stop.set()
            http.server.shutdown()
            t.join(timeout=5)
    finally:
        for app in apps:
            app.shutdown()


def test_blackholed_peer_dropped_by_handshake_deadline():
    """A peer that connects and then goes silent (black hole) must not
    pin a connection slot forever: the per-peer deadline timer drops it
    through the standard path once PEER_AUTHENTICATION_TIMEOUT passes
    without the handshake completing (ISSUE 5 satellite)."""
    import socket

    clock = VirtualClock(ClockMode.REAL_TIME)
    cfg = Config()
    cfg.NETWORK_PASSPHRASE = PASSPHRASE
    cfg.NODE_SEED = SecretKey.from_seed(sha256(b"blackhole-0"))
    cfg.NODE_IS_VALIDATOR = True
    cfg.RUN_STANDALONE = False
    cfg.FORCE_SCP = True
    cfg.MANUAL_CLOSE = True
    cfg.PEER_PORT = 36700
    cfg.ALLOW_LOCALHOST_FOR_TESTING = True
    cfg.PEER_AUTHENTICATION_TIMEOUT = 0.5
    cfg.QUORUM_SET = QuorumSetConfig(
        threshold=1, validators=[cfg.node_id()])
    cfg.UNSAFE_QUORUM = True
    app = Application.create(clock, cfg)
    mute = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        app.start()
        om = app.overlay_manager
        mute.connect(("127.0.0.1", 36700))   # dial, then say nothing
        assert crank_real(clock, lambda: len(om._tcp_peers) == 1,
                          timeout_s=5)
        # the deadline timer fires; the peer is dropped and the slot
        # freed — never authenticated
        assert crank_real(clock, lambda: len(om._tcp_peers) == 0,
                          timeout_s=5)
        assert len(om.get_authenticated_peers()) == 0
        assert om.drop_reasons.get("handshake timeout", 0) >= 1
    finally:
        mute.close()
        app.shutdown()


def test_authenticated_peers_survive_the_deadline_timer():
    """The deadline timer must not shoot healthy peers: an
    authenticated pair with a tight handshake deadline (and a sane
    idle timeout) stays connected well past the handshake window.
    (threshold=2: neither node may externalize alone — with a 0.3s
    close cadence a threshold-1 pair races consensus against the
    handshake and diverges before the links merge)"""
    clock, apps = make_tcp_apps(2, 2, 36750)
    for a in apps:
        a.config.PEER_AUTHENTICATION_TIMEOUT = 0.5
        a.config.PEER_TIMEOUT = 30.0
    try:
        for a in apps:
            a.start()
        assert crank_real(clock, lambda: all(
            len(a.overlay_manager.get_authenticated_peers()) == 1
            for a in apps), timeout_s=10)
        # sit well past the handshake deadline: nobody gets dropped
        crank_real(clock, lambda: False, timeout_s=1.5)
        for a in apps:
            assert len(a.overlay_manager.get_authenticated_peers()) == 1
            assert "handshake timeout" not in \
                a.overlay_manager.drop_reasons
            assert "idle timeout" not in a.overlay_manager.drop_reasons
    finally:
        for a in apps:
            a.shutdown()


def test_idle_link_kept_alive_by_keepalive():
    """A healthy-but-quiet authenticated link must outlive
    PEER_TIMEOUT: past half the idle deadline the peer sends a
    GET_PEERS keepalive whose PEERS reply refreshes the read clock on
    both ends — only a genuinely black-holed peer hits the deadline."""
    clock, apps = make_tcp_apps(2, 2, 36800)
    for a in apps:
        a.config.FORCE_SCP = False       # quiet network: no SCP chatter
        a.config.PEER_TIMEOUT = 2.0
    try:
        for a in apps:
            a.start()
        assert crank_real(clock, lambda: all(
            len(a.overlay_manager.get_authenticated_peers()) == 1
            for a in apps), timeout_s=10)
        read0 = [a.overlay_manager.get_authenticated_peers()[0]
                 .messages_read for a in apps]
        # idle well past PEER_TIMEOUT: keepalives keep the link up
        crank_real(clock, lambda: False, timeout_s=3.0)
        for a, r0 in zip(apps, read0):
            peers = a.overlay_manager.get_authenticated_peers()
            assert len(peers) == 1
            assert "idle timeout" not in a.overlay_manager.drop_reasons
            # traffic flowed during the idle window (the keepalive
            # exchange), proving the link survived by design, not by
            # an unexpectedly chatty test network
            assert peers[0].messages_read > r0
    finally:
        for a in apps:
            a.shutdown()


def test_wrong_network_passphrase_rejected():
    """A node on a different network must fail the authenticated
    handshake: its HELLO carries a different networkID (reference:
    Peer::recvHello's network check, OverlayTests 'wrong network')."""
    clock = VirtualClock(ClockMode.REAL_TIME)
    base_port = 36500
    seeds = [SecretKey.from_seed(sha256(b"wrongnet-%d" % i))
             for i in range(2)]
    node_ids = [s.public_key().raw for s in seeds]
    apps = []
    for i, phrase in enumerate([PASSPHRASE, "a different network"]):
        cfg = Config()
        cfg.NETWORK_PASSPHRASE = phrase
        cfg.NODE_SEED = seeds[i]
        cfg.NODE_IS_VALIDATOR = True
        cfg.RUN_STANDALONE = False
        cfg.FORCE_SCP = True
        cfg.MANUAL_CLOSE = True
        cfg.PEER_PORT = base_port + i
        cfg.KNOWN_PEERS = [f"127.0.0.1:{base_port + j}" for j in range(i)]
        cfg.QUORUM_SET = QuorumSetConfig(threshold=1,
                                         validators=list(node_ids))
        apps.append(Application.create(clock, cfg))
    try:
        for app in apps:
            app.start()
        # give the dialer several chances: authentication must NEVER
        # complete across the network split
        crank_real(clock, lambda: False, timeout_s=3)
        for app in apps:
            assert len(app.overlay_manager.get_authenticated_peers()) == 0
    finally:
        for app in apps:
            app.shutdown()


def test_banned_peer_cannot_authenticate():
    """Banning a node id drops and blocks it at the handshake
    (reference: BanManager + Peer::recvAuth ban check)."""
    clock, apps = make_tcp_apps(2, 1, 36600)
    try:
        for app in apps:
            app.start()
        assert crank_real(clock, lambda: all(
            len(a.overlay_manager.get_authenticated_peers()) == 1
            for a in apps), timeout_s=10)
        # ban node 1 on node 0 via the admin route, drop the connection
        from stellar_core_tpu.crypto.strkey import StrKey
        banned = StrKey.encode_ed25519_public(
            apps[1].config.node_id())
        r = apps[0].command_handler.handle("ban", {"node": banned})
        assert r.get("status") == "ok", r
        # the ban route drops matching authenticated peers immediately
        assert len(apps[0].overlay_manager.get_authenticated_peers()) == 0
        # the dialer retries, but authentication must not come back on
        # the banning side
        crank_real(clock, lambda: False, timeout_s=3)
        assert len(apps[0].overlay_manager.get_authenticated_peers()) == 0
        r = apps[0].command_handler.handle("bans", {})
        assert banned in r.get("bans", [])
        # unban: connection may re-establish
        r = apps[0].command_handler.handle("unban", {"node": banned})
        assert r.get("status") == "ok", r
        assert crank_real(clock, lambda: len(
            apps[0].overlay_manager.get_authenticated_peers()) == 1,
            timeout_s=12)
    finally:
        for app in apps:
            app.shutdown()


def test_max_additional_peer_connections_caps_inbound():
    """Inbound peers beyond MAX_ADDITIONAL_PEER_CONNECTIONS are dropped
    at authentication (reference: MAX_ADDITIONAL_PEER_CONNECTIONS)."""
    clock = VirtualClock(ClockMode.REAL_TIME)
    base_port = 36800
    seeds = [SecretKey.from_seed(sha256(b"cap-%d" % i)) for i in range(3)]
    node_ids = [s.public_key().raw for s in seeds]
    apps = []
    for i in range(3):
        cfg = Config()
        cfg.NETWORK_PASSPHRASE = PASSPHRASE
        cfg.NODE_SEED = seeds[i]
        cfg.NODE_IS_VALIDATOR = True
        cfg.RUN_STANDALONE = False
        cfg.FORCE_SCP = True
        cfg.MANUAL_CLOSE = True
        cfg.PEER_PORT = base_port + i
        cfg.ALLOW_LOCALHOST_FOR_TESTING = True
        # nodes 1 and 2 dial node 0; node 0 accepts only ONE inbound
        cfg.KNOWN_PEERS = [f"127.0.0.1:{base_port}"] if i else []
        if i == 0:
            cfg.MAX_ADDITIONAL_PEER_CONNECTIONS = 1
        cfg.QUORUM_SET = QuorumSetConfig(threshold=2,
                                         validators=list(node_ids))
        apps.append(Application.create(clock, cfg))
    try:
        for a in apps:
            a.start()
        crank_real(clock, lambda: len(
            apps[0].overlay_manager.get_authenticated_peers()) >= 1,
            timeout_s=10)
        crank_real(clock, lambda: False, timeout_s=2)  # let both settle
        from stellar_core_tpu.overlay.peer_auth import PeerRole
        inbound = [p for p in
                   apps[0].overlay_manager.get_authenticated_peers()
                   if p.role == PeerRole.REMOTE_CALLED_US]
        assert len(inbound) == 1, len(inbound)
    finally:
        for a in apps:
            a.shutdown()


def test_preferred_peers_only_rejects_others():
    """PREFERRED_PEERS_ONLY: inbound peers not on the preferred list
    never authenticate (reference: PREFERRED_PEERS_ONLY)."""
    clock = VirtualClock(ClockMode.REAL_TIME)
    base_port = 36900
    seeds = [SecretKey.from_seed(sha256(b"pref-%d" % i))
             for i in range(3)]
    node_ids = [s.public_key().raw for s in seeds]
    apps = []
    for i in range(3):
        cfg = Config()
        cfg.NETWORK_PASSPHRASE = PASSPHRASE
        cfg.NODE_SEED = seeds[i]
        cfg.NODE_IS_VALIDATOR = True
        cfg.RUN_STANDALONE = False
        cfg.FORCE_SCP = True
        cfg.MANUAL_CLOSE = True
        cfg.PEER_PORT = base_port + i
        cfg.ALLOW_LOCALHOST_FOR_TESTING = True
        cfg.KNOWN_PEERS = [f"127.0.0.1:{base_port}"] if i else []
        if i == 0:
            cfg.PREFERRED_PEERS_ONLY = True
            # only node 1's listening address is preferred
            cfg.PREFERRED_PEERS = [f"127.0.0.1:{base_port + 1}"]
        cfg.QUORUM_SET = QuorumSetConfig(threshold=2,
                                         validators=list(node_ids))
        apps.append(Application.create(clock, cfg))
    try:
        for a in apps:
            a.start()
        crank_real(clock, lambda: len(
            apps[0].overlay_manager.get_authenticated_peers()) >= 1,
            timeout_s=10)
        crank_real(clock, lambda: False, timeout_s=2)
        peers0 = apps[0].overlay_manager.get_authenticated_peers()
        assert all(p.peer_id == apps[1].config.node_id()
                   for p in peers0), \
            "a non-preferred peer authenticated"
        assert len(apps[2].overlay_manager.get_authenticated_peers()) \
            == 0
    finally:
        for a in apps:
            a.shutdown()
