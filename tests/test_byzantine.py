"""Adversarial chaos: Byzantine fault kinds, topology-aware
simulation, and churn with catchup-under-chaos (ISSUE 7).

Verdict semantics (docs/CHAOS.md §Byzantine): with a Byzantine
proposer in the mix the externalized values legitimately differ from a
fault-free run, so safety is HONEST-SURVIVOR AGREEMENT — byte-identical
header chains across honest nodes — not baseline equality."""

import time as _wall

import pytest

from stellar_core_tpu.simulation import topologies
from stellar_core_tpu.util import chaos
from stellar_core_tpu.util.chaos import (ChaosEngine, FaultSpec,
                                         SimulatedChurn, SimulatedCrash)

pytestmark = [pytest.mark.chaos, pytest.mark.byzantine]


@pytest.fixture(autouse=True)
def _clean_engine():
    chaos.uninstall()
    yield
    chaos.uninstall()


# ------------------------------------------------------------ fault kinds --
def test_new_fault_kinds_sentinels():
    eng = ChaosEngine(3, [
        FaultSpec("eq", "equivocate"),
        FaultSpec("fl", "bad_sig_flood", burst=5),
        FaultSpec("ch", "churn"),
        FaultSpec("de", "delay", delay_ms=250.0),
    ])
    chaos.install(eng)
    assert chaos.point("eq") is chaos.EQUIVOCATE
    out = chaos.point("fl", b"template")
    assert isinstance(out, chaos.BadSigBurst) and out.burst == 5
    with pytest.raises(SimulatedChurn) as exc:
        chaos.point("ch", node="cafe")
    assert isinstance(exc.value, SimulatedCrash)   # buries like a crash
    assert exc.value.ctx["node"] == "cafe"
    d = chaos.point("de", b"payload", _can_delay=True)
    assert isinstance(d, chaos.Delay)
    assert d.payload == b"payload" and d.seconds == 0.25
    assert eng.injected["chaos.injected.churn"] == 1
    assert eng.injected["chaos.injected.delay"] == 1
    # a seam that cannot defer (no _can_delay) passes through and the
    # hit is NOT counted — injected evidence never claims a delay that
    # had no effect
    eng2 = ChaosEngine(3, [FaultSpec("db.commit", "delay")])
    chaos.install(eng2)
    assert chaos.point("db.commit", b"x") == b"x"
    assert eng2.injected == {}


def test_malformed_xdr_is_deterministic_and_mangles():
    def run(seed):
        eng = ChaosEngine(seed, [FaultSpec("mx", "malformed_xdr",
                                           start=0, count=10)])
        chaos.install(eng)
        outs = [chaos.point("mx", bytes(range(64))) for _ in range(10)]
        chaos.uninstall()
        return outs

    a, b = run(5), run(5)
    assert a == b                       # same seed → same mangling
    assert all(o != bytes(range(64)) for o in a)
    assert run(6) != a                  # seed actually matters
    # payload-less hits consume nothing (same contract as corrupt)
    eng = ChaosEngine(5, [FaultSpec("mx", "malformed_xdr")])
    chaos.install(eng)
    assert chaos.point("mx") is None
    assert eng.injected == {}


def test_bad_sig_flood_spec_json_roundtrip():
    spec = FaultSpec("p", "bad_sig_flood", start=2, count=3, burst=17)
    back = FaultSpec.from_json(spec.to_json())
    assert back.to_json() == spec.to_json()
    assert back.burst == 17


# ----------------------------------------------------------- equivocation --
def test_equivocate_envelope_forges_signed_conflicting_twin():
    """The forged twin: same node, same slot, warped values, valid
    signature — and the nomination values re-signed by the
    equivocator's own key so proposer-signature validation passes."""
    sim = topologies.pair()
    try:
        sim.start_all_nodes()
        assert sim.crank_until(lambda: sim.have_all_externalized(2))
        app = sim.apps()[0]
        herder = app.herder
        captured = []
        orig = herder.broadcast_cb
        herder.broadcast_cb = \
            lambda env: (captured.append(env), orig(env))[1]
        assert sim.crank_until(lambda: bool(captured),
                               timeout_virtual_seconds=30)
        env = captured[0]
        twin = herder._equivocate_envelope(env)
        assert twin is not None
        st, tw = env.statement, twin.statement
        assert bytes(tw.nodeID.value) == bytes(st.nodeID.value)
        assert tw.slotIndex == st.slotIndex
        assert tw.to_bytes() != st.to_bytes()          # conflicting
        assert herder.verify_envelope(twin)            # signed right
        # warped nomination values still pass proposer validation
        from stellar_core_tpu.xdr.ledger import (StellarValue,
                                                 StellarValueType)
        for raw in tw.pledges.value.votes:
            sv = StellarValue.from_bytes(bytes(raw))
            if sv.ext.disc == StellarValueType.STELLAR_VALUE_SIGNED:
                assert herder.verify_stellar_value_signature(sv)
    finally:
        sim.stop_all_nodes()


# ------------------------------------------------- delay is virtual time --
def test_delay_schedule_consumes_virtual_time_not_wall_time():
    """Satellite regression: a 100 ms-delay schedule on a 4-node sim
    finishes in well under 1 s of WALL time — delay faults ride the
    VirtualClock, never a real sleep."""
    eng = ChaosEngine(4, [FaultSpec("overlay.send", "delay", start=0,
                                    count=10_000, delay_ms=100.0)])
    chaos.install(eng)
    sim = topologies.core(4)
    t0 = _wall.monotonic()
    try:
        sim.start_all_nodes()
        assert sim.crank_until(lambda: sim.have_all_externalized(3),
                               timeout_virtual_seconds=300)
    finally:
        chaos.uninstall()
        sim.stop_all_nodes()
    wall = _wall.monotonic() - t0
    assert eng.injected["chaos.injected.delay"] > 50
    assert wall < 1.0, f"delay faults burned {wall:.2f}s of wall time"


def test_bandwidth_capped_link_keeps_fifo_and_survives():
    """Bandwidth model: per-frame transit varies with size, but a link
    transmits SERIALLY — deliveries are FIFO-clamped, so a small frame
    never overtakes a big one and trips the MAC sequence check. The
    capped network must converge with zero auth-sequence drops."""
    from stellar_core_tpu.simulation import Simulation
    from stellar_core_tpu.simulation.topologies import _seeds
    from stellar_core_tpu.main.config import QuorumSetConfig
    sim = Simulation()
    seeds = _seeds(2, b"bwcap")
    ids = [s.public_key().raw for s in seeds]
    qset = QuorumSetConfig(threshold=2, validators=ids)
    for s in seeds:
        sim.add_node(s, qset)
    # 64 kbit/s + 20ms: handshake certs (~300B) and SCP envelopes
    # differ in size by 10x, so un-clamped scheduling WOULD reorder
    sim.add_pending_connection(ids[0], ids[1], latency_s=0.020,
                               bandwidth_bps=64_000)
    try:
        sim.start_all_nodes()
        assert sim.crank_until(lambda: sim.have_all_externalized(3),
                               timeout_virtual_seconds=120)
        for app in sim.apps():
            reasons = app.overlay_manager.drop_reasons
            assert "unexpected auth sequence" not in reasons, reasons
            assert "unexpected MAC" not in reasons, reasons
    finally:
        sim.stop_all_nodes()


def test_partial_delay_schedule_does_not_kill_links():
    """A prob<1 delay spec at overlay.send delays SOME frames; the
    FIFO clamp keeps undelayed frames behind in-flight delayed ones —
    the authenticated link must survive the whole run."""
    eng = ChaosEngine(12, [FaultSpec("overlay.send", "delay", prob=0.3,
                                     delay_ms=50.0)])
    chaos.install(eng)
    sim = topologies.core(3)
    try:
        sim.start_all_nodes()
        assert sim.crank_until(lambda: sim.have_all_externalized(3),
                               timeout_virtual_seconds=300)
        for app in sim.apps():
            reasons = app.overlay_manager.drop_reasons
            assert "unexpected auth sequence" not in reasons, reasons
    finally:
        chaos.uninstall()
        sim.stop_all_nodes()
    assert eng.injected["chaos.injected.delay"] > 0


def test_link_latency_model_is_virtual_and_converges():
    """Per-link latency: a tiered network with 2–150 ms links closes
    ledgers in virtual time that REFLECTS the latency while wall time
    stays flat."""
    sim = topologies.tiered(3, 3, latency=topologies.LinkLatency(8))
    t0 = _wall.monotonic()
    try:
        sim.start_all_nodes()
        assert sim.crank_until(lambda: sim.have_all_externalized(4),
                               timeout_virtual_seconds=120)
        assert sim.ledger_hashes_agree(3)
    finally:
        sim.stop_all_nodes()
    assert _wall.monotonic() - t0 < 30.0


# ------------------------------------------------- bad-sig flood + drops --
def test_bad_sig_flood_accounting_drops_flooder(tmp_path):
    """A flooder bursting invalid-signature transactions is charged
    per-peer and dropped through the standard path once it crosses
    PEER_BAD_SIG_DROP_THRESHOLD; the counters surface on the peers
    route and the metrics registry."""
    from stellar_core_tpu.simulation.byzantine import (
        _TargetedPayer, _install_verify_stack)

    def conf(cfg):
        cfg.PEER_BAD_SIG_DROP_THRESHOLD = 6

    sim = topologies.core(2, configure=conf)
    ids = list(sim.nodes.keys())
    flooder, honest = ids[0], ids[1]
    eng = ChaosEngine(9, [FaultSpec(
        "overlay.transaction.recv", "bad_sig_flood", start=0,
        count=1_000, burst=4, match={"peer": flooder.hex()})])
    chaos.install(eng)
    try:
        sim.start_all_nodes()
        for app in sim.apps():
            _install_verify_stack(app, sim.clock)
        assert sim.crank_until(lambda: sim.have_all_externalized(2))
        payer = _TargetedPayer(sim, sim.nodes[flooder])
        for _ in range(3):
            payer.submit_one()
            target = sim.nodes[honest].ledger_manager \
                .get_last_closed_ledger_num() + 1
            sim.crank_until(
                lambda: sim.nodes[honest].ledger_manager
                .get_last_closed_ledger_num() >= target,
                timeout_virtual_seconds=60)
        happ = sim.nodes[honest]
        assert eng.injected.get("chaos.injected.bad_sig_flood", 0) >= 2
        assert happ.metrics.new_counter(
            "overlay.peer.drop.bad_sig").count >= 6
        assert happ.overlay_manager.drop_reasons.get(
            "bad sig flood", 0) >= 1
        # per-peer counter surfaced through the peers route shape
        peers = happ.overlay_manager.peers_json()
        assert "drop_reasons" in peers
        for row in peers["inbound"] + peers["outbound"]:
            assert "bad_sig_drops" in row
    finally:
        chaos.uninstall()
        sim.stop_all_nodes()


# ----------------------------------------------------- churn + catchup ----
def test_churn_restart_catches_up(tmp_path):
    """Kill a validator with a `churn` fault mid-close, restart it from
    its persisted DB + bucket dir, and watch it catch back up over the
    overlay to the network tip with a byte-identical chain."""
    def conf(cfg):
        cfg.ARTIFICIALLY_SET_CLOSE_TIME_FOR_TESTING = 1
        cfg.ARTIFICIALLY_PESSIMIZE_MERGES_FOR_TESTING = True

    sim = topologies.tiered(3, 3, configure=conf,
                            data_dir=str(tmp_path))
    for app in sim.apps():
        app.ledger_manager.defer_completion = False
    ids = list(sim.nodes.keys())
    victim = ids[1]
    eng = ChaosEngine(8, [FaultSpec(
        "ledger.close.crash.applyTx", "churn", start=2, count=1,
        match={"node": victim.hex()})])
    chaos.install(eng)
    try:
        sim.start_all_nodes()

        def survivors_at(seq):
            return all(a.ledger_manager.get_last_closed_ledger_num()
                       >= seq for a in sim.alive_apps())

        from stellar_core_tpu.simulation.chaos import _crank_with_crashes
        churned = []
        dead = _crank_with_crashes(
            sim, lambda: survivors_at(6) and bool(churned),
            timeout=120.0, churned=churned)
        assert churned == [victim], "churn fault never fired"
        assert dead == []
        assert survivors_at(6)
        assert victim in sim.crashed

        app = sim.restart_node(victim)
        app.ledger_manager.defer_completion = False
        assert victim not in sim.crashed
        lcl0 = app.ledger_manager.get_last_closed_ledger_num()
        net = max(a.ledger_manager.get_last_closed_ledger_num()
                  for nid, a in sim.nodes.items() if nid != victim)
        assert lcl0 < net                      # it really was behind
        assert sim.crank_until(
            lambda: app.ledger_manager.get_last_closed_ledger_num()
            >= net, timeout_virtual_seconds=120)
        # the recovered chain is byte-identical to the network's
        assert sim.ledger_hashes_agree(net)
    finally:
        chaos.uninstall()
        sim.stop_all_nodes()


def test_restart_requires_data_dir():
    sim = topologies.core(2)
    try:
        sim.start_all_nodes()
        nid = list(sim.nodes.keys())[0]
        sim.crash_node(nid)
        with pytest.raises(RuntimeError, match="data_dir"):
            sim.restart_node(nid)
    finally:
        sim.stop_all_nodes()


# -------------------------------------------------------- the smoke leg --
def test_byzantine_smoke_9_nodes():
    """Acceptance (tier-1): 9-node tiered quorum, 1 equivocator + 1
    bad-sig flooder; honest nodes externalize ≥ 5 slots with
    byte-identical headers, the flooder is dropped, and both Byzantine
    fault classes actually fired."""
    from stellar_core_tpu.simulation.byzantine import run_smoke
    res = run_smoke(seed=7, target_slots=5)
    assert res["ok"], res
    assert res["safety_ok"] and res["liveness_ok"]
    assert res["injected"].get("chaos.injected.equivocate", 0) > 0
    assert res["injected"].get("chaos.injected.bad_sig_flood", 0) > 0
    assert res["flooder_dropped"]
    assert res["bad_sig_drops"] > 0
    assert res["verify_submitted"] > 0


# ------------------------------------------------------- the slow legs ---
@pytest.mark.slow
def test_byzantine_tiered_50_nodes_with_churn(tmp_path):
    """The 50+-node tiered scenario: watcher tier, per-link latency,
    equivocation + bad-sig flood + malformed XDR, and churn with
    catchup-under-chaos."""
    from stellar_core_tpu.simulation.byzantine import run_tiered_chaos
    res = run_tiered_chaos(seed=11, n_orgs=3, validators_per_org=12,
                           watchers=14, target_slots=4,
                           data_dir=str(tmp_path), churn_down_slots=1)
    assert res["ok"], res
    assert res["nodes"] >= 50
    assert res["safety_ok"] and res["liveness_ok"]
    assert res["churn"]["caught_up"]
    assert res["flooder_dropped"]
    inj = res["injected"]
    assert {"chaos.injected.equivocate", "chaos.injected.bad_sig_flood",
            "chaos.injected.malformed_xdr",
            "chaos.injected.churn"} <= set(inj)
