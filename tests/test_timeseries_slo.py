"""Telemetry time-series + SLO watchdog + perf trajectory (ISSUE 10).

Covers the tentpole contracts: the sampler ring stays bounded with
eviction accounting, the `since=` scrape cursor resyncs across
restarts/clears instead of silently gapping, SLO verdicts are
deterministic under VirtualClock (dwell timing reads sample time, not
the wall), the verifier's per-dispatch accounting lands in metrics,
and scripts/bench_trend.py both detects synthetic regressions and
runs green — structurally tier-1 — over every committed artifact."""

import json
import os
import sys

import pytest

from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.ops.slo import (BREACH, OK, WARN, SloRule,
                                      SloWatchdog, aggregate_status)
from stellar_core_tpu.util.metrics import MetricsRegistry
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.util.timeseries import (TimeSeries,
                                              aggregate_summaries,
                                              summarize_samples)

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

import bench_trend                                         # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _app(cfg=None):
    cfg = cfg or get_test_config()
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    return app


# ------------------------------------------------------------- the ring --

def test_ring_bound_and_eviction_accounting():
    ts = TimeSeries(capacity=5)
    for i in range(8):
        ts.append({"t": float(i)})
    assert len(ts) == 5
    assert ts.dropped == 3
    kept = [s["cursor"] for s in ts.samples()]
    assert kept == [4, 5, 6, 7, 8]       # oldest evicted, order kept


def test_since_cursor_incremental_and_gap_resync():
    ts = TimeSeries(capacity=4)
    for i in range(3):
        ts.append({"t": float(i)})
    full, reset = ts.since(None)
    assert reset and len(full) == 3
    token = ts.cursor_token()
    ts.append({"t": 3.0})
    newer, reset = ts.since(token)
    assert not reset and [s["cursor"] for s in newer] == [4]
    # caught-up scraper: empty increment, no reset
    newer, reset = ts.since(ts.cursor_token())
    assert newer == [] and not reset
    # push the continuation point off the ring: full buffer + reset
    for i in range(6):
        ts.append({"t": 10.0 + i})
    behind, reset = ts.since(token)
    assert reset and len(behind) == 4


def test_limit_truncates_from_the_oldest_and_cursor_continues():
    """A limited reply must serve the OLDEST pending samples and
    point its cursor at the last one served — chaining limited
    scrapes walks the whole series with no silent gap."""
    ts = TimeSeries(capacity=16)
    for i in range(7):
        ts.append({"t": float(i)})
    doc = ts.to_doc(since=None, limit=3)
    assert doc["truncated"] is True
    assert [s["cursor"] for s in doc["samples"]] == [1, 2, 3]
    doc2 = ts.to_doc(since=doc["cursor"], limit=3)
    assert doc2["reset"] is False
    assert [s["cursor"] for s in doc2["samples"]] == [4, 5, 6]
    doc3 = ts.to_doc(since=doc2["cursor"], limit=3)
    assert [s["cursor"] for s in doc3["samples"]] == [7]
    assert doc3["truncated"] is False
    # limit=0 serves nothing and does NOT advance the cursor
    doc4 = ts.to_doc(since=doc2["cursor"], limit=0)
    assert doc4["samples"] == []
    assert ts.to_doc(since=doc4["cursor"])["samples"][0]["cursor"] == 7


def test_since_cursor_across_restart_and_clear():
    """A restarted node (new TimeSeries) or a clearmetrics MUST
    invalidate outstanding cursors via the epoch, never serve a
    silent gap."""
    a = TimeSeries(capacity=8)
    a.append({"t": 0.0})
    token = a.cursor_token()
    b = TimeSeries(capacity=8)           # the restarted node's ring
    assert a.epoch != b.epoch
    b.append({"t": 1.0})
    samples, reset = b.since(token)
    assert reset and len(samples) == 1   # full resync, flagged
    # clear: same object, rotated epoch, cursor restarts at 1
    a.clear()
    assert a.since(token)[1] is True
    a.append({"t": 2.0})
    assert a.samples()[0]["cursor"] == 1


# --------------------------------------------------------- the sampler --

def test_sampler_fires_on_virtual_clock_and_stays_bounded():
    cfg = get_test_config()
    cfg.TELEMETRY_SAMPLE_PERIOD = 1.0
    cfg.TELEMETRY_RING_CAPACITY = 10
    app = _app(cfg)
    try:
        app.clock.crank_for(25.0)
        series = app.telemetry.series
        assert len(series) == 10                  # capacity, not 25
        assert series.dropped >= 10
        s = series.latest()
        # the snapshot families the SLO rules and artifacts read
        for key in ("t", "wall", "ledger", "close", "tx_e2e",
                    "slot_p99_ms", "verify", "dispatch", "breaker",
                    "breaker_open", "flood", "host"):
            assert key in s, key
        # virtual-clock sampling: timestamps step the virtual period
        ts = [x["t"] for x in series.samples()]
        assert ts == sorted(ts)
        assert all(abs((b - a) - 1.0) < 1e-6
                   for a, b in zip(ts, ts[1:]))
    finally:
        app.shutdown()


def test_sampler_determinism_under_virtual_clock():
    """Two identically-seeded apps sampled over the same virtual span
    produce identical series modulo wall-clock/host fields — the
    chaos-repro contract extended to telemetry."""
    def run():
        cfg = get_test_config(instance=7777)
        cfg.TELEMETRY_SAMPLE_PERIOD = 0.5
        app = _app(cfg)
        try:
            app.manual_close()
            app.clock.crank_for(5.0)
            out = []
            for s in app.telemetry.series.samples():
                c = {k: v for k, v in s.items()
                     if k not in ("wall", "host", "close", "tx_e2e")}
                out.append(c)
            return out
        finally:
            app.shutdown()

    assert run() == run()


def test_clearmetrics_resets_series_cursors_and_slo_state():
    cfg = get_test_config()
    app = _app(cfg)
    try:
        app.telemetry.sample_now()
        app.slo.observe({"t": 0.0, "close": {"p99_ms": 1e9,
                                             "count": 1}})
        assert app.slo.status()["rules"]["close_p99"]["verdict"] \
            == BREACH
        epoch = app.telemetry.series.epoch
        token = app.telemetry.series.cursor_token()
        app.command_handler.handle("clearmetrics", {})
        assert len(app.telemetry.series) == 0
        assert app.telemetry.series.epoch != epoch
        assert app.telemetry.series.since(token)[1] is True
        st = app.slo.status()
        assert st["overall"] == OK and st["evaluations"] == 0
        assert st["rules"]["close_p99"]["breaches"] == 0
    finally:
        app.shutdown()


def test_timeseries_and_slo_admin_routes():
    cfg = get_test_config()
    app = _app(cfg)
    try:
        app.manual_close()
        app.telemetry.sample_now()
        doc = app.command_handler.handle("timeseries", {})["timeseries"]
        assert doc["reset"] is True and len(doc["samples"]) == 1
        token = doc["cursor"]
        app.telemetry.sample_now()
        inc = app.command_handler.handle(
            "timeseries", {"since": token})["timeseries"]
        assert inc["reset"] is False and len(inc["samples"]) == 1
        # limit caps the reply, summary returns the bounded form
        app.telemetry.sample_now()
        lim = app.command_handler.handle(
            "timeseries", {"limit": "1"})["timeseries"]
        assert len(lim["samples"]) == 1
        summ = app.command_handler.handle(
            "timeseries", {"summary": "1"})["timeseries"]["summary"]
        assert summ["samples"] == 3 and "host_load" in summ
        slo = app.command_handler.handle("slo", {})["slo"]
        assert slo["overall"] in (OK, WARN, BREACH)
        assert set(slo["rules"]) == {"close_p99", "tx_e2e_p99",
                                     "breaker_open_dwell",
                                     "duplicate_ratio", "read_p99"}
    finally:
        app.shutdown()


# ------------------------------------------------------------- the SLO --

def _sample(t, **over):
    s = {"t": t, "close": {"count": 1, "p99_ms": 100.0},
         "tx_e2e": {"count": 0}, "breaker_open": 0.0,
         "flood": {"duplicate_ratio": 1.0}}
    s.update(over)
    return s


def test_slo_threshold_warn_and_breach():
    reg = MetricsRegistry()
    wd = SloWatchdog([SloRule("close_p99", ("close", "p99_ms"),
                              1000.0)], metrics=reg)
    wd.observe(_sample(0.0))
    assert wd.status()["rules"]["close_p99"]["verdict"] == OK
    wd.observe(_sample(1.0, close={"count": 1, "p99_ms": 850.0}))
    assert wd.status()["rules"]["close_p99"]["verdict"] == WARN
    wd.observe(_sample(2.0, close={"count": 1, "p99_ms": 1500.0}))
    st = wd.status()["rules"]["close_p99"]
    assert st["verdict"] == BREACH and st["breaches"] == 1
    # verdict counters rode the registry (Prometheus-exportable)
    assert reg.new_counter("slo.close_p99.breach").count == 1
    assert reg.new_counter("slo.close_p99.warn").count == 1
    assert reg.new_counter("slo.close_p99.ok").count == 1
    # recovery
    wd.observe(_sample(3.0))
    assert wd.overall() == OK


def test_slo_dwell_is_deterministic_in_sample_time():
    """Breaker-OPEN dwell: WARN while the breach window is inside the
    dwell, BREACH exactly once sample-time says the dwell elapsed —
    wall clock never consulted."""
    wd = SloWatchdog([SloRule("breaker", ("breaker_open",), 0.5,
                              warn_ratio=1.0, dwell_s=10.0)])
    wd.observe(_sample(0.0, breaker_open=1.0))
    assert wd.status()["rules"]["breaker"]["verdict"] == WARN
    wd.observe(_sample(9.0, breaker_open=1.0))
    assert wd.status()["rules"]["breaker"]["verdict"] == WARN
    wd.observe(_sample(10.0, breaker_open=1.0))
    assert wd.status()["rules"]["breaker"]["verdict"] == BREACH
    # a close resets the window: the next OPEN starts a fresh dwell
    wd.observe(_sample(11.0))
    wd.observe(_sample(12.0, breaker_open=1.0))
    assert wd.status()["rules"]["breaker"]["verdict"] == WARN


def test_slo_missing_sections_are_ok_not_breach():
    wd = SloWatchdog([SloRule("dup", ("flood", "duplicate_ratio"),
                              2.0)])
    wd.observe({"t": 0.0, "flood": None})
    wd.observe({"t": 1.0})
    assert wd.overall() == OK
    assert wd.status()["rules"]["dup"]["value"] is None


def test_slo_aggregate_status_takes_worst():
    a = {"overall": OK, "rules": {"close_p99": {
        "verdict": OK, "breaches": 0, "warns": 1, "threshold": 1.0}}}
    b = {"overall": BREACH, "rules": {"close_p99": {
        "verdict": BREACH, "breaches": 3, "warns": 0,
        "threshold": 1.0}}}
    agg = aggregate_status([a, b, None])
    assert agg["overall"] == BREACH and agg["nodes"] == 2
    assert agg["rules"]["close_p99"]["breaches"] == 3
    assert agg["rules"]["close_p99"]["warns"] == 1


# ------------------------------------------- dispatch accounting + sums --

def test_verifier_dispatch_accounting():
    """Per-dispatch device telemetry (ROADMAP item 1 groundwork):
    batch size, padding waste to the power-of-two bucket, and a
    dispatch wall-time observation per collect."""
    from stellar_core_tpu.ops.verifier import TpuBatchVerifier
    reg = MetricsRegistry()
    v = TpuBatchVerifier(device_min_batch=1, metrics=reg)
    assert all(v.verify_tuples(_sig_items(5)))
    batch = reg.new_histogram("crypto.verify.dispatch.batch")
    pad = reg.new_histogram("crypto.verify.dispatch.padding")
    wall = reg.new_timer("crypto.verify.dispatch.wall")
    assert batch.count == 1 and batch._sum == 5.0
    assert pad.count == 1 and pad._sum == 3.0       # bucket 8, n 5
    assert wall.count == 1
    # the small-batch host bypass does NOT count as a device dispatch
    v2 = TpuBatchVerifier(device_min_batch=64, metrics=reg)
    assert all(v2.verify_tuples(_sig_items(2)))
    assert batch.count == 1


def _sig_items(n):
    import hashlib

    from stellar_core_tpu.crypto import ed25519_ref as ref
    seed = bytes(range(32))
    pub = ref.secret_to_public(seed)
    out = []
    for i in range(n):
        msg = hashlib.sha256(b"ts-%d" % i).digest()
        out.append((pub, ref.sign(seed, msg), msg))
    return out


def test_summarize_and_aggregate():
    samples = [
        {"t": 0.0, "host": {"load1": 1.0},
         "close": {"count": 1, "p99_ms": 10.0},
         "tx_e2e": {"count": 0},
         "verify": {"queue_pending": 3, "queue_inflight": 0},
         "flood": {"duplicate_ratio": 1.5}, "breaker_open": 0.0},
        {"t": 4.0, "host": {"load1": 3.0},
         "close": {"count": 2, "p99_ms": 20.0},
         "tx_e2e": {"count": 0},
         "verify": {"queue_pending": 1, "queue_inflight": 2},
         "flood": {"duplicate_ratio": 2.5}, "breaker_open": 1.0},
    ]
    s = summarize_samples(samples)
    assert s["samples"] == 2 and s["span_s"] == 4.0
    assert s["host_load"] == {"min": 1.0, "mean": 2.0, "max": 3.0}
    assert s["close_p99_ms_max"] == 20.0
    assert s["queue_pending_max"] == 3
    assert s["duplicate_ratio_last"] == 2.5
    assert s["breaker_open_samples"] == 1
    agg = aggregate_summaries([s, summarize_samples([])])
    assert agg["samples"] == 2 and agg["nodes"] == 1
    assert summarize_samples([]) == {"samples": 0}


# --------------------------------------------------------- bench trend --

def test_trend_covers_every_committed_family_and_gate_green():
    """THE tier-1 trajectory gate (ISSUE 10 acceptance): every
    committed *_rNN.json family appears with its rounds, and the
    regression gate holds on the committed record — the trajectory
    can never silently go dark again."""
    trend = bench_trend.build_trend(ROOT)
    on_disk = set()
    for f in os.listdir(ROOT):
        m = bench_trend.FAMILY_RE.match(f)
        if m and m.group(1) not in bench_trend.SKIP_FAMILIES:
            on_disk.add(m.group(1))
    assert on_disk, "no artifacts committed?"
    assert set(trend["families"]) == on_disk
    assert trend["artifacts_total"] >= len(on_disk)
    for fam, doc in trend["families"].items():
        assert doc["rounds"], fam
    # artifact form satisfies the schema checker
    art = bench_trend.trend_artifact(trend)
    assert art["metric"] == "bench_trend"
    assert trend["regressions"] == [], \
        "committed artifacts regressed: %s" % trend["regressions"]


def _write_rounds(tmp_path, fam, values, host_busy=None):
    for i, v in enumerate(values, start=1):
        doc = {"metric": "m", "unit": "u", "vs_baseline": 1.0}
        if isinstance(v, str):
            doc.update({"error": v})
        else:
            doc["value"] = v
        if host_busy and i in host_busy:
            doc["host_busy"] = True
            doc["host_load"] = {"start": {"loadavg": [9.0, 1, 1],
                                          "spin_ms": 99.0}}
        (tmp_path / ("%s_r%02d.json" % (fam, i))).write_text(
            json.dumps(doc))


def test_trend_flags_synthetic_regression(tmp_path):
    _write_rounds(tmp_path, "TPSM", [200.0, 210.0, 100.0])
    trend = bench_trend.build_trend(str(tmp_path), tolerance=0.30)
    doc = trend["families"]["TPSM"]
    assert doc["regressed_vs_prev"] and doc["regressed_vs_best"]
    assert doc["regressed"]
    assert len(trend["regressions"]) == 1
    r = trend["regressions"][0]
    assert r["family"] == "TPSM" and r["round"] == 3
    assert r["delta_vs_prev"] < -0.30
    # table + strict exit code carry the flag
    assert "REGRESSED" in bench_trend.render_table(trend)
    assert bench_trend.main(["--root", str(tmp_path),
                             "--strict"]) == 1


def test_trend_tolerance_and_noise_handling(tmp_path):
    # within tolerance: not a regression
    _write_rounds(tmp_path, "TPS", [1000.0, 800.0])
    # drop vs prev only (best IS prev) — still gated, both must hold
    _write_rounds(tmp_path, "TPSS", [50.0, 300.0, 290.0])
    # a host_busy latest round never gates
    _write_rounds(tmp_path, "TPSMT", [200.0, 210.0, 90.0],
                  host_busy={3})
    # recorded-failure rounds are carried but skipped by the math
    _write_rounds(tmp_path, "CATCHUP", [100.0, "boom", 95.0])
    trend = bench_trend.build_trend(str(tmp_path), tolerance=0.30)
    assert trend["regressions"] == []
    assert trend["families"]["TPSMT"]["regressed_vs_prev"]
    assert not trend["families"]["TPSMT"]["regressed"]
    cat = trend["families"]["CATCHUP"]
    assert cat["measured_rounds"] == 2
    assert cat["rounds"]["2"]["error"] == "boom"
    assert cat["latest_value"] == 95.0
    # per-round dips recorded as data even when the gate stays green
    _write_rounds(tmp_path, "VERIFY", [100.0, 20.0, 120.0])
    trend = bench_trend.build_trend(str(tmp_path), tolerance=0.30)
    assert trend["families"]["VERIFY"]["dips"][0]["round"] == 2
    assert not trend["families"]["VERIFY"]["regressed"]


def test_trend_degraded_device_round_not_gated(tmp_path):
    """A latest round whose artifact carries the r19 device-probe
    verdict (warm device verify slower than native C — the
    accelerator is absent/sick) is annotated, never gated: the drop
    belongs to the hardware, not the code."""
    _write_rounds(tmp_path, "CATCHUP", [200.0, 210.0])
    doc = {"metric": "m", "unit": "u", "vs_baseline": 1.0,
           "value": 90.0,
           "device_probe": {"bucket": 1024,
                            "device_sigs_per_sec": 43.6,
                            "native_sigs_per_sec": 495289.7,
                            "degraded": True}}
    (tmp_path / "CATCHUP_r03.json").write_text(json.dumps(doc))
    trend = bench_trend.build_trend(str(tmp_path), tolerance=0.30)
    cat = trend["families"]["CATCHUP"]
    assert cat["regressed_vs_prev"] and cat["regressed_vs_best"]
    assert not cat["regressed"]
    assert trend["regressions"] == []
    assert cat["rounds"]["3"]["device_degraded"] is True
    assert "r03:90↓~" in bench_trend.render_table(trend)
    assert bench_trend.main(["--root", str(tmp_path),
                             "--strict"]) == 0


def test_trend_empty_root_is_loud(tmp_path):
    with pytest.raises(RuntimeError):
        bench_trend.build_trend(str(tmp_path))
