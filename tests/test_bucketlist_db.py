"""BucketListDB read path (VERDICT r02 #7).

With EXPERIMENTAL_BUCKETLIST_DB on, LedgerTxnRoot answers non-offer
entry loads from the bucket indexes (bloom-gated, newest level first)
while SQL keeps offers and remains the authoritative write store —
the reference's EXPERIMENTAL_BUCKETLIST_DB split
(/root/reference/src/bucket/readme.md:55-105).
"""

import pytest

from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.xdr.ledger_entries import LedgerKey
from stellar_core_tpu.xdr.types import PublicKey


def _mk_app(bucketlist_db: bool):
    cfg = get_test_config()
    cfg.EXPERIMENTAL_BUCKETLIST_DB = bucketlist_db
    cfg.INVARIANT_CHECKS = [".*"]
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    return app


def _run_workload(app, n_ledgers=6, per_ledger=10):
    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    # pinned traffic seed: the two apps under comparison have different
    # node ids, and the default per-node-id RNG would (by design) give
    # them different traffic shapes — this test needs IDENTICAL ones
    gen = LoadGenerator(app, seed=42)
    assert gen.generate_accounts(12) == 12
    app.manual_close()
    gen.sync_account_seqs()
    for _ in range(n_ledgers):
        assert gen.generate_payments(per_ledger) == per_ledger
        app.manual_close()
    return gen


def _account_snapshot(app, gen):
    out = {}
    with LedgerTxn(app.ledger_manager.root) as ltx:
        for acc in gen.accounts:
            le = ltx.load_without_record(LedgerKey.account(acc.account_id))
            out[acc.key.public_key().raw] = (
                le.data.value.balance, le.data.value.seqNum)
    return out


def test_bucketlist_db_reads_match_sql():
    """The same workload closes identically whether reads come from
    buckets or SQL, and the resulting account state is identical."""
    app_sql = _mk_app(False)
    app_bl = _mk_app(True)
    try:
        # same network passphrase → same genesis and tx hashes
        app_bl.config.NETWORK_PASSPHRASE = app_sql.config.NETWORK_PASSPHRASE
        gen_sql = _run_workload(app_sql)
        gen_bl = _run_workload(app_bl)
        assert list(_account_snapshot(app_sql, gen_sql).values()) == \
            list(_account_snapshot(app_bl, gen_bl).values())
    finally:
        app_sql.shutdown()
        app_bl.shutdown()


def test_bucketlist_db_serves_reads_from_buckets():
    """Loads actually hit the bucket index (bloom counters move) and a
    deleted entry's tombstone wins over any staler level."""
    app = _mk_app(True)
    try:
        gen = _run_workload(app, n_ledgers=3)
        root = app.ledger_manager.root
        assert root._bucket_list is not None
        # force a cold cache so the read path goes to the buckets
        root._cache.clear()
        before = sum(
            getattr(b._index, "bloom_lookups", 0)
            for lvl in root._bucket_list.levels
            for b in (lvl.curr, lvl.snap) if b._index is not None)
        with LedgerTxn(root) as ltx:
            le = ltx.load_without_record(
                LedgerKey.account(gen.accounts[0].account_id))
            assert le is not None
        after = sum(
            getattr(b._index, "bloom_lookups", 0)
            for lvl in root._bucket_list.levels
            for b in (lvl.curr, lvl.snap) if b._index is not None)
        assert after > before, "read did not consult any bucket index"

        # missing key → absent through the bloom/tombstone path
        root._cache.clear()
        missing = LedgerKey.account(PublicKey.ed25519(b"\xfe" * 32))
        with LedgerTxn(root) as ltx:
            assert ltx.load_without_record(missing) is None
    finally:
        app.shutdown()


def test_bucketlist_db_sees_deletions():
    """An account merged away reads as absent (DEADENTRY tombstone
    shadows the older LIVEENTRY in deeper levels)."""
    import test_standalone_app as m1
    from txtest_utils import op_account_merge

    from txtest_utils import op_create_account
    from stellar_core_tpu.crypto.keys import SecretKey

    app = _mk_app(True)
    try:
        master = m1.master_account(app)
        vkey = SecretKey.from_seed(b"\x21" * 32)
        victim = m1.AppAccount(app, vkey)
        assert m1.submit(app, master.tx([op_create_account(
            victim.account_id, 10**9)]))["status"] == "PENDING"
        app.manual_close()
        victim.sync_seq()
        key = LedgerKey.account(victim.account_id)
        root = app.ledger_manager.root
        root._cache.clear()
        with LedgerTxn(root) as ltx:
            assert ltx.load_without_record(key) is not None
        # merge the account away, close a few more ledgers so the
        # tombstone travels through at least one spill
        assert m1.submit(app, victim.tx([op_account_merge(master.muxed)]))[
            "status"] == "PENDING"
        app.manual_close()
        for _ in range(4):
            app.manual_close()
        root._cache.clear()
        with LedgerTxn(root) as ltx:
            assert ltx.load_without_record(key) is None
    finally:
        app.shutdown()


def test_prefetch_does_not_shadow_bucket_entries():
    """prefetch() must not cache an SQL miss as absent for a key the
    bucket list serves: the bigstate seed path installs entries only
    into deep bucket levels, never SQL, and a poisoned cache made
    payments to seeded accounts fail with PAYMENT_NO_DESTINATION while
    a replaying node (whose buckets were materialized into SQL by
    ApplyBucketsWork) succeeded them — a replay divergence."""
    from stellar_core_tpu.simulation.load_generator import (
        build_bigstate_buckets, bulk_account_id, install_bigstate_buckets)

    app = _mk_app(True)
    try:
        hdr = app.ledger_manager.get_last_closed_ledger_header()
        bks = build_bigstate_buckets(64, hdr.ledgerVersion, hdr.ledgerSeq)
        install_bigstate_buckets(app, bks)
        app.manual_close()
        root = app.ledger_manager.root
        key = LedgerKey.account(PublicKey.ed25519(bulk_account_id(0)))
        root._cache.clear()
        assert root.prefetch([key]) == 1
        with LedgerTxn(root) as ltx:
            assert ltx.load_without_record(key) is not None
    finally:
        app.shutdown()


def test_catchup_replay_with_bucketlist_db(tmp_path):
    """A fresh node catches up from a published archive with
    EXPERIMENTAL_BUCKETLIST_DB on and lands on the identical chain
    (the VERDICT r02 #7 'Done' condition: catchup passes with the
    flag on)."""
    import test_history_catchup as hc
    import test_standalone_app as m1
    from stellar_core_tpu.catchup.catchup_work import (
        CatchupConfiguration, CatchupWork)
    from stellar_core_tpu.work import run_work_to_completion
    from stellar_core_tpu.work.basic_work import State

    app_a, archive, root = hc.make_publishing_app(tmp_path)
    try:
        hash_a = bytes(app_a.database.query_one(
            "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=127")[0])
        cfg_b = get_test_config()
        cfg_b.NETWORK_PASSPHRASE = app_a.config.NETWORK_PASSPHRASE
        cfg_b.EXPERIMENTAL_BUCKETLIST_DB = True
        app_b = Application.create(
            VirtualClock(ClockMode.VIRTUAL_TIME), cfg_b)
        app_b.start()
        try:
            assert app_b.ledger_manager.root._bucket_list is not None
            work = CatchupWork(app_b, archive,
                               CatchupConfiguration(to_ledger=0))
            assert run_work_to_completion(
                app_b, work, timeout_virtual=3000) == State.WORK_SUCCESS
            assert app_b.ledger_manager.get_last_closed_ledger_num() == 127
            assert app_b.ledger_manager.get_last_closed_ledger_hash() == \
                hash_a
            bal_b = m1.app_account_entry(
                app_b, m1.master_account(app_b).account_id).balance
            bal_a = m1.app_account_entry(
                app_a, m1.master_account(app_a).account_id).balance
            assert bal_b == bal_a
        finally:
            app_b.shutdown()
    finally:
        app_a.shutdown()
