"""Branch-and-bound quorum intersection checker tests.

Behavior model: the reference's QuorumIntersectionChecker
(herder/QuorumIntersectionCheckerImpl.cpp MinQuorumEnumerator + SCC
scan; test shapes mirror herder/test/QuorumIntersectionTests.cpp —
balanced orgs, split networks, interruption)."""

import hashlib
import itertools
import random
import time

import pytest

from stellar_core_tpu.herder.quorum_intersection import (
    QICInterrupted, QuorumIntersectionChecker)
from stellar_core_tpu.scp import local_node as ln
from stellar_core_tpu.xdr.scp import SCPQuorumSet
from stellar_core_tpu.xdr.types import PublicKey


def node(i):
    return hashlib.sha256(b"qi-%d" % i).digest()


def qset(nodes, threshold, inner=()):
    return SCPQuorumSet(threshold=threshold,
                        validators=[PublicKey.ed25519(n) for n in nodes],
                        innerSets=list(inner))


def brute_force_enjoys_intersection(qmap):
    """Ground truth by full enumeration: every pair of quorums
    intersects (feasible for <= 10 nodes)."""
    nodes = sorted(qmap)
    quorums = []
    for r in range(1, len(nodes) + 1):
        for combo in itertools.combinations(nodes, r):
            s = set(combo)
            if all(ln.is_quorum_slice(qmap[n], s) for n in s):
                quorums.append(s)
    for a, b in itertools.combinations(quorums, 2):
        if not (a & b):
            return False
    return True


# ------------------------------------------------------------ core cases ---
def test_majority_intersects():
    ids = [node(i) for i in range(4)]
    qmap = {n: qset(ids, 3) for n in ids}
    assert QuorumIntersectionChecker(
        qmap).network_enjoys_quorum_intersection()


def test_half_threshold_splits():
    ids = [node(i) for i in range(6)]
    qmap = {n: qset(ids, 3) for n in ids}
    c = QuorumIntersectionChecker(qmap)
    assert not c.network_enjoys_quorum_intersection()
    a, b = c.potential_split
    assert a and b and not (a & b)


def test_disjoint_sccs_detected():
    """Two cliques that never reference each other are two SCCs each
    holding a quorum — the fast-path split (reference: the
    multiple-quorum-bearing-SCC check in networkEnjoysQuorumIntersection)."""
    a = [node(i) for i in range(3)]
    b = [node(i) for i in range(10, 13)]
    qmap = {n: qset(a, 2) for n in a}
    qmap.update({n: qset(b, 2) for n in b})
    c = QuorumIntersectionChecker(qmap)
    assert not c.network_enjoys_quorum_intersection()
    q1, q2 = c.potential_split
    assert not (q1 & q2)


def test_inner_sets_org_structure():
    """3 orgs of 3 validators, org-level threshold 2-of-3: enjoys
    intersection (reference: the orgs topologies in
    QuorumIntersectionTests)."""
    orgs = [[node(10 * o + v) for v in range(3)] for o in range(3)]
    inner = [qset(org, 2) for org in orgs]
    top = SCPQuorumSet(threshold=2, validators=[], innerSets=inner)
    qmap = {n: top for org in orgs for n in org}
    assert QuorumIntersectionChecker(
        qmap).network_enjoys_quorum_intersection()
    # 2-of-3 orgs with orgs at 1-of-3 does NOT intersect
    weak_inner = [qset(org, 1) for org in orgs]
    weak = SCPQuorumSet(threshold=2, validators=[], innerSets=weak_inner)
    qmap = {n: weak for org in orgs for n in org}
    assert not QuorumIntersectionChecker(
        qmap).network_enjoys_quorum_intersection()


def test_empty_and_singleton():
    assert QuorumIntersectionChecker(
        {}).network_enjoys_quorum_intersection()
    n0 = node(0)
    assert QuorumIntersectionChecker(
        {n0: qset([n0], 1)}).network_enjoys_quorum_intersection()


# ------------------------------------------------- brute-force cross-check ---
def test_matches_brute_force_on_random_networks():
    """Property: B&B verdict == full-enumeration verdict on random small
    networks (mixed thresholds, partial views)."""
    rng = random.Random(1234)
    checked_false = 0
    for trial in range(60):
        n = rng.randint(2, 7)
        ids = [node(1000 * trial + i) for i in range(n)]
        qmap = {}
        for nid in ids:
            k = rng.randint(1, n)
            members = rng.sample(ids, k)
            thr = rng.randint(max(1, k // 2), k)
            qmap[nid] = qset(members, thr)
        expected = brute_force_enjoys_intersection(qmap)
        got = QuorumIntersectionChecker(
            qmap).network_enjoys_quorum_intersection()
        assert got == expected, (trial, expected, got)
        checked_false += 0 if expected else 1
    assert checked_false > 5  # the sweep exercised real splits


def test_split_witness_is_two_disjoint_quorums():
    rng = random.Random(99)
    found = 0
    for trial in range(40):
        n = rng.randint(4, 8)
        ids = [node(2000 * trial + i) for i in range(n)]
        qmap = {}
        for nid in ids:
            k = rng.randint(1, n)
            members = rng.sample(ids, k)
            qmap[nid] = qset(members, rng.randint(1, k))
        c = QuorumIntersectionChecker(qmap)
        if not c.network_enjoys_quorum_intersection():
            found += 1
            a, b = c.potential_split
            assert not (a & b)
            assert all(ln.is_quorum_slice(qmap[x], a) for x in a)
            assert all(ln.is_quorum_slice(qmap[x], b) for x in b)
    assert found > 3


# ------------------------------------------------------ scale + interrupt ---
def _pubnet_like(n_orgs: int):
    """Tiered topology shaped like pubnet's: n_orgs orgs x 3 validators,
    every node requiring 2/3-of-orgs with 2-of-3 inside each org."""
    orgs = [[node(100 * o + v) for v in range(3)] for o in range(n_orgs)]
    inner = [qset(org, 2) for org in orgs]
    thr = (2 * n_orgs + 2) // 3
    top = SCPQuorumSet(threshold=thr, validators=[], innerSets=inner)
    return {n: top for org in orgs for n in org}


def test_seventy_node_pubnet_under_five_seconds():
    """VERDICT round-1 weak #5 acceptance: a ~70-validator transitive
    quorum analyzed < 5s."""
    qmap = _pubnet_like(24)          # 72 validators
    assert len(qmap) == 72
    t0 = time.monotonic()
    c = QuorumIntersectionChecker(qmap)
    assert c.network_enjoys_quorum_intersection()
    dt = time.monotonic() - t0
    assert dt < 5.0, f"took {dt:.1f}s"


def test_seventy_node_split_found():
    """Same scale, but org threshold dropped to half: the checker must
    FIND the split (not just time out)."""
    orgs = [[node(100 * o + v) for v in range(3)] for o in range(24)]
    inner = [qset(org, 2) for org in orgs]
    top = SCPQuorumSet(threshold=12, validators=[], innerSets=inner)
    qmap = {n: top for org in orgs for n in org}
    t0 = time.monotonic()
    c = QuorumIntersectionChecker(qmap)
    assert not c.network_enjoys_quorum_intersection()
    a, b = c.potential_split
    assert not (a & b)
    assert time.monotonic() - t0 < 5.0


def test_interruptible():
    qmap = _pubnet_like(24)
    c = QuorumIntersectionChecker(qmap, max_calls=3)
    with pytest.raises(QICInterrupted):
        c.network_enjoys_quorum_intersection()
    # external flag form
    c2 = QuorumIntersectionChecker(qmap, interrupt_flag=[True])
    with pytest.raises(QICInterrupted):
        c2.network_enjoys_quorum_intersection()


# ---------------------------------------------- topology generator feeds ---
def test_tiered_generator_output_enjoys_intersection():
    """ISSUE 7 satellite: the tiered generator's quorum maps — the
    exact configs the 50+-node byzantine scenarios run — hold quorum
    intersection at every scale we simulate."""
    from stellar_core_tpu.simulation.topologies import tiered_qmap
    for n_orgs, vper in ((3, 3), (3, 12), (5, 5)):
        qmap = tiered_qmap(n_orgs, vper)
        assert len(qmap) == n_orgs * vper
        c = QuorumIntersectionChecker(qmap)
        assert c.network_enjoys_quorum_intersection(), (n_orgs, vper)


def test_tiered_under_thresholded_config_rejected_and_splits():
    """A deliberately under-thresholded tiered config is rejected by
    the generator; forcing it through with unsafe=True hands the
    checker a map it must find the split in."""
    from stellar_core_tpu.simulation.topologies import (tiered,
                                                        tiered_qmap)
    with pytest.raises(ValueError, match="org threshold"):
        tiered_qmap(3, 4, org_threshold=2)          # half, not majority
    with pytest.raises(ValueError, match="top-level threshold"):
        tiered(4, 3, top_threshold=2)               # half the orgs
    # forced through: 1-of-3 inside each org → two disjoint quorums
    qmap = tiered_qmap(3, 3, org_threshold=1, unsafe=True)
    c = QuorumIntersectionChecker(qmap)
    assert not c.network_enjoys_quorum_intersection()
    a, b = c.potential_split
    assert a and b and not (a & b)


def test_hierarchical_generator_output_enjoys_intersection():
    """hierarchical_quorum's live quorum sets (read off the built
    simulation's SCP local nodes) also pass the checker."""
    from stellar_core_tpu.simulation.topologies import hierarchical_quorum
    sim = hierarchical_quorum(3, 2)
    try:
        qmap = {nid: app.herder.scp.local_node.qset
                for nid, app in sim.nodes.items()}
        assert QuorumIntersectionChecker(
            qmap).network_enjoys_quorum_intersection()
    finally:
        sim.stop_all_nodes()


def test_admin_route_reports_intersection():
    """quorum?transitive=true surfaces the analysis (reference:
    CommandHandler::quorum + QuorumTracker json)."""
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             get_test_config())
    app.start()
    try:
        r = app.command_handler.handle("quorum", {"transitive": "true"})
        assert "transitive" in r
        ana = r["transitive"].get("intersection")
        assert ana is not None and ana["intersection"] is True
    finally:
        app.shutdown()
