"""Operator settings-upgrade tool against a live standalone node's HTTP
API (reference: scripts/soroban-settings/SorobanSettingsUpgrade.py —
setup_upgrade + the `upgrades` endpoint round trip)."""

import base64
import json
import os
import subprocess
import sys

from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.main.command_handler import run_http_server
from stellar_core_tpu.soroban.network_config import SorobanNetworkConfig
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.xdr.contract import ConfigSettingID

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "scripts", "soroban_settings_upgrade.py")


def _run_tool(url, *argv, timeout=120):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, TOOL, "--node", url, *argv],
        capture_output=True, text=True, env=env, timeout=timeout)
    assert r.returncode == 0, (argv, r.stdout, r.stderr)
    return r.stdout


def test_settings_upgrade_tool_end_to_end(tmp_path):
    cfg = get_test_config()
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    http = run_http_server(app.command_handler, 0)
    url = f"http://127.0.0.1:{http.server.server_address[1]}"
    try:
        # get: dumps a current struct setting
        out = _run_tool(url, "get", "--id", "STATE_ARCHIVAL")
        assert json.loads(out)["maxEntriesToArchive"] == 1000

        settings = tmp_path / "upgrade.json"
        settings.write_text(json.dumps({
            "CONTRACT_MAX_SIZE_BYTES": 131072,
            "STATE_ARCHIVAL": {"maxEntriesToArchive": 77},
        }))

        # encode: deterministic upgrade-set serialization
        enc = json.loads(_run_tool(url, "encode", "--settings",
                                   str(settings)))
        assert enc["entries"] == 2

        # setup: real txs through the HTTP tx endpoint store the
        # upgrade set as the TEMPORARY entry the upgrade machinery reads
        out = _run_tool(url, "setup", "--settings", str(settings),
                        "--secret", "master", "--manual-close")
        key_b64 = json.loads(
            out[out.index("{"):])["configUpgradeSetKey"]
        assert enc["contentHash"] == json.loads(
            out[out.index("{"):])["contentHash"]

        # propose: the node now votes the CONFIG upgrade
        _run_tool(url, "propose", "--key", key_b64)
        st = json.loads(_run_tool(url, "status"))
        assert st["upgrades"]["configupgradesetkey"] == key_b64

        # the next close applies it
        app.manual_close()
        with LedgerTxn(app.ledger_manager.root) as ltx:
            nc = SorobanNetworkConfig(ltx)
            assert nc._get(
                ConfigSettingID.CONFIG_SETTING_CONTRACT_MAX_SIZE_BYTES) \
                == 131072
            assert nc.state_archival.maxEntriesToArchive == 77
    finally:
        http.server.shutdown()
        app.shutdown()
