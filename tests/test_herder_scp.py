"""Herder↔SCP integration: full Applications reaching consensus.

The pre-overlay analogue of the reference's Simulation tests: N real
Applications on one VirtualClock, SCP envelopes delivered herder-to-
herder, tx set fetches satisfied from peers' pending-envelope caches
(what ItemFetcher will do over the overlay).
"""

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.main import Application, Config, QuorumSetConfig
from stellar_core_tpu.util.timer import ClockMode, VirtualClock

import test_standalone_app as m1
from txtest_utils import op_create_account, op_payment


PASSPHRASE = "herder-scp test network"


def make_network(n_nodes: int, threshold: int):
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    seeds = [SecretKey.from_seed(sha256(b"scpnet-%d" % i))
             for i in range(n_nodes)]
    node_ids = [s.public_key().raw for s in seeds]
    apps = []
    for i in range(n_nodes):
        cfg = Config()
        cfg.NETWORK_PASSPHRASE = PASSPHRASE
        cfg.NODE_SEED = seeds[i]
        cfg.NODE_IS_VALIDATOR = True
        cfg.RUN_STANDALONE = True
        cfg.FORCE_SCP = True
        cfg.MANUAL_CLOSE = False
        cfg.EXPECTED_LEDGER_CLOSE_TIME = 1.0
        cfg.MAX_TX_SET_SIZE = 100
        cfg.INVARIANT_CHECKS = [".*"]
        cfg.QUORUM_SET = QuorumSetConfig(threshold=threshold,
                                         validators=list(node_ids))
        apps.append(Application.create(clock, cfg))

    # message bus: emitted envelopes go straight to the other herders
    def wire(app):
        def broadcast(env):
            # deliver on next crank to avoid unbounded recursion
            def deliver():
                for other in apps:
                    if other is not app:
                        other.herder.recv_scp_envelope(env)
            clock.post(deliver)
        app.herder.broadcast_cb = broadcast

        def fetch_txset(h):
            def try_fetch():
                for other in apps:
                    ts = other.herder.pending_envelopes.get_tx_set(h)
                    if ts is not None:
                        app.herder.recv_tx_set(h, ts)
                        return
            clock.post(try_fetch)
        app.herder.pending_envelopes.request_txset = fetch_txset

        def fetch_qset(h):
            def try_fetch():
                for other in apps:
                    qs = other.herder.pending_envelopes.get_qset(h)
                    if qs is not None:
                        app.herder.recv_scp_quorum_set(h, qs)
                        return
            clock.post(try_fetch)
        app.herder.pending_envelopes.request_qset = fetch_qset

    for app in apps:
        wire(app)
    return clock, apps


def crank_until(clock, pred, max_virtual_seconds=60):
    deadline = clock.now() + max_virtual_seconds
    while not pred() and clock.now() < deadline:
        if clock.crank(False) == 0:
            clock.crank(True)  # advance virtual time to next timer
    return pred()


def all_at_ledger(apps, seq):
    return all(a.ledger_manager.get_last_closed_ledger_num() >= seq
               for a in apps)


@pytest.fixture
def net3():
    clock, apps = make_network(3, 2)
    for app in apps:
        app.start()
    yield clock, apps
    for app in apps:
        app.shutdown()


def test_three_validators_close_empty_ledgers(net3):
    clock, apps = net3
    assert crank_until(clock, lambda: all_at_ledger(apps, 3))
    hashes = {a.ledger_manager.get_last_closed_ledger_num():
              a.ledger_manager.get_last_closed_ledger_hash()
              for a in apps}
    # all nodes closed the same chain
    h2 = [a.ledger_manager.get_last_closed_ledger_hash() for a in apps
          if a.ledger_manager.get_last_closed_ledger_num() ==
          apps[0].ledger_manager.get_last_closed_ledger_num()]
    assert len(set(h2)) == 1


def test_payment_reaches_all_nodes(net3):
    clock, apps = net3
    assert crank_until(clock, lambda: all_at_ledger(apps, 2))
    master = m1.master_account(apps[0])
    dest = m1.AppAccount(apps[0], SecretKey.from_seed(b"\x21" * 32))
    frame = master.tx([op_create_account(dest.account_id, 10**11)])
    r = m1.submit(apps[0], frame)
    assert r["status"] == "PENDING"

    # no overlay in this harness, so the tx sits only in the submitting
    # node's queue and lands when THAT node wins a nomination round —
    # leader election is hash-driven, so crank until it does rather
    # than assuming a fixed slot
    def applied_everywhere():
        return all(m1.app_account_entry(a, dest.account_id) is not None
                   for a in apps)
    assert crank_until(clock, applied_everywhere,
                       max_virtual_seconds=120)
    # the new account exists on EVERY node with the same balance
    for app in apps:
        acc = m1.app_account_entry(app, dest.account_id)
        assert acc is not None and acc.balance == 10**11
    # ledger hashes agree
    seqs = {a.ledger_manager.get_last_closed_ledger_num() for a in apps}
    common = min(seqs)
    hs = set()
    for app in apps:
        row = app.database.query_one(
            "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=?",
            (common,))
        hs.add(bytes(row[0]))
    assert len(hs) == 1


def test_five_nodes_threshold_four():
    clock, apps = make_network(5, 4)
    for app in apps:
        app.start()
    try:
        assert crank_until(clock, lambda: all_at_ledger(apps, 2),
                           max_virtual_seconds=120)
    finally:
        for app in apps:
            app.shutdown()


# ---------------------------------------------------------------------------
# QuorumTracker (reference: herder/QuorumTracker.{h,cpp})
# ---------------------------------------------------------------------------

def _qt_node(i: int) -> bytes:
    return sha256(b"qt-node-%d" % i)


def _qt_qset(nodes, threshold, inner=()):
    from stellar_core_tpu.xdr.scp import SCPQuorumSet
    from stellar_core_tpu.xdr.types import PublicKey
    return SCPQuorumSet(threshold=threshold,
                        validators=[PublicKey.ed25519(n) for n in nodes],
                        innerSets=list(inner))


def test_quorum_tracker_bfs_and_distance():
    from stellar_core_tpu.herder.quorum_tracker import QuorumTracker
    me, a, b, c = (_qt_node(i) for i in range(4))
    # me -> {a, b}; a -> {c}; b's qset unknown
    qt = QuorumTracker(me, _qt_qset([a, b], 2))
    assert qt.is_node_definitely_in_quorum(a)
    assert qt.is_node_definitely_in_quorum(b)
    assert not qt.is_node_definitely_in_quorum(c)
    assert qt.expand(a, _qt_qset([c], 1))
    assert qt.is_node_definitely_in_quorum(c)
    assert qt.quorum_map[c].distance == 2
    assert qt.quorum_map[c].closest_validators == {a}
    # expanding an unknown node cannot be done incrementally
    d = _qt_node(9)
    assert not qt.expand(d, _qt_qset([me], 1))
    # conflicting re-expansion of a is rejected
    assert not qt.expand(a, _qt_qset([b], 1))


def test_quorum_tracker_rebuild_lookup():
    from stellar_core_tpu.herder.quorum_tracker import QuorumTracker
    me, a, b = (_qt_node(i) for i in (0, 1, 2))
    qsets = {a: _qt_qset([b], 1)}
    qt = QuorumTracker(me, _qt_qset([a], 1))
    qt.rebuild(lambda nid: qsets.get(nid))
    assert qt.is_node_definitely_in_quorum(b)
    assert qt.quorum_map[b].closest_validators == {a}
    j = qt.transitive_json()
    assert j["node_count"] == 3


def test_herder_quorum_json_has_transitive():
    clock, apps = make_network(3, 2)
    try:
        j = apps[0].herder.quorum_json()
        assert "transitive" in j
        # all three validators are in the local node's transitive quorum
        assert j["transitive"]["node_count"] == 3
    finally:
        for app in apps:
            app.shutdown()


def test_txset_validation_uses_batch_verifier():
    """With SIGNATURE_VERIFY_BACKEND=tpu the herder's txset validation
    routes every signature through one device batch (BASELINE.md config
    #2; collection point SURVEY.md §3.2)."""
    from stellar_core_tpu.main import Application, get_test_config
    from txtest_utils import op_create_account, op_payment

    cfg = get_test_config()
    cfg.SIGNATURE_VERIFY_BACKEND = "tpu"
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    with Application.create(clock, cfg) as app:
        app.start()
        assert app.batch_verifier is not None
        assert app.herder.batch_verifier is app.batch_verifier
        master = m1.master_account(app)
        a = m1.AppAccount(app, SecretKey.from_seed(sha256(b"bv-a")))
        m1.submit(app, master.tx([
            op_create_account(a.account_id, 100_0000000)]))
        app.manual_close()

        m1.submit(app, master.tx([op_payment(a.muxed, 1234)]))
        calls = []
        orig = app.batch_verifier.verify_tuples
        app.batch_verifier.verify_tuples = \
            lambda items: (calls.append(len(items)), orig(items))[1]
        lcl = app.ledger_manager.get_last_closed_ledger_header()
        from stellar_core_tpu.herder.tx_set import (
            SurgePricingLaneConfig, make_tx_set_from_transactions)
        txs = app.herder.tx_queue.get_transactions()
        frame, applicable, _ = make_tx_set_from_transactions(
            txs, lcl, app.config.network_id(),
            SurgePricingLaneConfig([lcl.maxTxSetSize]))
        # queue admission warmed the verify cache and the prevalidator
        # only dispatches cache MISSES; a remote validator receiving
        # this set has a cold cache, which is what dispatches the batch
        from stellar_core_tpu.crypto.keys import clear_verify_cache
        clear_verify_cache()
        assert app.herder.is_tx_set_valid(frame)
        assert calls and calls[0] >= 1
