"""Test configuration.

Multi-chip sharding is tested on a virtual 8-device CPU mesh
(xla_force_host_platform_device_count), mirroring how the driver dry-runs the
multi-chip path. Must be set before jax is first imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
