"""Test configuration.

Multi-chip sharding is tested on a virtual 8-device CPU mesh
(xla_force_host_platform_device_count), mirroring how the driver dry-runs
the multi-chip path.

The environment may pre-register a real TPU backend from interpreter
startup (sitecustomize), so setting JAX_PLATFORMS before import is not
enough — force the platform back to cpu via jax.config. XLA_FLAGS is
read lazily at backend init, so setting it here (before any jax op runs)
still takes effect.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Persistent XLA compilation cache: the Ed25519 kernel (127-iteration scan
# + decompression chain) costs tens of seconds to compile per bucket size
# on CPU; cache compiled programs across test runs. Partitioned per
# platform so chip AOT artifacts never load into CPU runs (and vice
# versa) — see util/jax_cache.py.
from stellar_core_tpu.util.jax_cache import enable_compile_cache  # noqa: E402
_cache_dir = enable_compile_cache(
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 ".jax_compile_cache"))
