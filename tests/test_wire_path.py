"""Wire-path overhaul tests (ISSUE 12): serialize-once framing parity,
one-broadcast-one-encoding, recv-side MAC over the received bytes,
single-flight demand scheduling with timeout rotation, floodgate churn
indexing, and the loopback-vs-TCP duplicate-ratio contract in a 4-node
mesh.
"""

import struct

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.sha import hmac_sha256, sha256
from stellar_core_tpu.main import Application, Config, QuorumSetConfig
from stellar_core_tpu.overlay import LoopbackPeerConnection, PeerState
from stellar_core_tpu.overlay import wire
from stellar_core_tpu.overlay.floodgate import Floodgate
from stellar_core_tpu.overlay.tx_advert import TxDemandsManager
from stellar_core_tpu.util import chaos
from stellar_core_tpu.util.chaos import ChaosEngine, FaultSpec
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.xdr.overlay import (AuthenticatedMessage,
                                          FloodAdvert, MessageType,
                                          StellarMessage,
                                          _AuthenticatedMessageV0)
from stellar_core_tpu.xdr.types import HmacSha256Mac

import test_standalone_app as m1
from txtest_utils import op_create_account

PASSPHRASE = "wire path test network"


@pytest.fixture(autouse=True)
def _no_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


def make_apps(n, clock=None):
    clock = clock or VirtualClock(ClockMode.VIRTUAL_TIME)
    seeds = [SecretKey.from_seed(sha256(b"wire-%d" % i))
             for i in range(n)]
    node_ids = [s.public_key().raw for s in seeds]
    apps = []
    for i in range(n):
        cfg = Config()
        cfg.NETWORK_PASSPHRASE = PASSPHRASE
        cfg.NODE_SEED = seeds[i]
        cfg.NODE_IS_VALIDATOR = True
        cfg.RUN_STANDALONE = True
        cfg.FORCE_SCP = True
        cfg.MANUAL_CLOSE = True
        cfg.EXPECTED_LEDGER_CLOSE_TIME = 1.0
        cfg.PEER_PORT = 35200 + i
        cfg.QUORUM_SET = QuorumSetConfig(
            threshold=n // 2 + 1, validators=list(node_ids))
        app = Application.create(clock, cfg)
        app.start()
        apps.append(app)
    return clock, apps


def shutdown(apps):
    for a in apps:
        a.shutdown()


def _tx_message(app, seed=b"\x61"):
    master = m1.master_account(app)
    dest = m1.AppAccount(app, SecretKey.from_seed(seed * 32))
    frame = master.tx([op_create_account(dest.account_id, 10**11)])
    return frame, StellarMessage(MessageType.TRANSACTION, frame.envelope)


# ------------------------------------------------------------- framing --

def test_frame_parity_cached_vs_uncached():
    """`wire.assemble_frame` over the cached body must be byte-
    identical to framing through `AuthenticatedMessage.to_bytes()` —
    the MAC/seq wire contract is unchanged, only the encode count."""
    clock, apps = make_apps(1)
    try:
        _frame, msg = _tx_message(apps[0])
        key = b"\x5a" * 32
        body = wire.body_bytes(msg)
        assert body == msg.to_bytes()
        for seq in (0, 1, 7):   # three peers' worth of sequence state
            mac = hmac_sha256(key, struct.pack(">Q", seq) + body)
            legacy = AuthenticatedMessage(0, _AuthenticatedMessageV0(
                sequence=seq, message=msg,
                mac=HmacSha256Mac(mac=mac))).to_bytes()
            assert wire.assemble_frame(seq, body, mac) == legacy
        # a semantically-equal but UNCACHED message frames identically
        fresh = StellarMessage.from_bytes(body)
        fresh_body = wire.body_bytes(fresh)
        assert fresh_body == body
        mac = hmac_sha256(key, struct.pack(">Q", 3) + fresh_body)
        assert wire.assemble_frame(3, fresh_body, mac) == \
            AuthenticatedMessage(0, _AuthenticatedMessageV0(
                sequence=3, message=fresh,
                mac=HmacSha256Mac(mac=mac))).to_bytes()
    finally:
        shutdown(apps)


def test_broadcast_to_three_peers_serializes_once():
    """The acceptance-criteria assertion: one broadcast to N peers
    performs exactly ONE body serialization; every peer's frame is
    a splice around the same body bytes, differing only in the
    12-byte prefix (disc+seq) and 32-byte MAC."""
    clock, apps = make_apps(4)
    conns = []
    try:
        for j in range(1, 4):
            c = LoopbackPeerConnection(apps[0], apps[j])
            conns.append(c)
            c.crank()
        om = apps[0].overlay_manager
        assert len(om.get_authenticated_peers()) == 3
        _frame, msg = _tx_message(apps[0])
        hit0 = om.encode_counters[0].count
        miss0 = om.encode_counters[1].count

        calls = []
        orig = StellarMessage.to_bytes

        def counting(self):
            if self is msg:
                calls.append(1)
            return orig(self)

        StellarMessage.to_bytes = counting
        try:
            sent = om.broadcast_message(msg)
        finally:
            StellarMessage.to_bytes = orig
        assert sent == 3
        assert len(calls) == 1          # exactly one body serialization
        assert om.encode_counters[1].count - miss0 == 1
        assert om.encode_counters[0].count - hit0 >= 3
        # wire frames: same body region on every link, per-peer seq+MAC
        body = wire.body_bytes(msg)
        frames = [c.initiator.out_queue[-1] for c in conns]
        for raw in frames:
            assert raw[:4] == wire.FRAME_PREFIX
            assert raw[wire.BODY_OFFSET:-wire.MAC_LEN] == body
        # MAC sequence preserved per peer (all three at seq from their
        # own counters — here each link sent the same number of
        # earlier messages, so seqs match but MAC keys differ)
        assert len({raw[-wire.MAC_LEN:] for raw in frames}) == 3
    finally:
        shutdown(apps)


def test_corrupted_body_byte_fails_mac():
    """Recv-side regression (ISSUE 12 satellite): the MAC is verified
    over the received wire slice, so ANY hand-corrupted body byte that
    still parses must fail authentication and drop the peer."""
    clock, apps = make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        assert conn.initiator.state == PeerState.GOT_AUTH
        _frame, msg = _tx_message(apps[0])
        conn.initiator.send_message(msg)
        assert conn.initiator.out_queue
        raw = bytearray(conn.initiator.out_queue.pop())
        # flip a byte deep in the body (inside the envelope's signature
        # opaque: parses fine, content changed)
        raw[len(raw) - wire.MAC_LEN - 8] ^= 0xFF
        conn.initiator.out_queue.append(bytes(raw))
        conn.crank()
        assert conn.acceptor.state == PeerState.CLOSING
        assert apps[1].overlay_manager.drop_reasons.get(
            "unexpected MAC", 0) == 1
    finally:
        shutdown(apps)


def test_recv_seeds_encode_cache_from_wire_slice():
    """A received message's canonical bytes are the wire slice — the
    relay path (hash, flow control, rebroadcast) re-encodes nothing."""
    clock, apps = make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        _frame, msg = _tx_message(apps[0])
        conn.initiator.send_message(msg)
        body = wire.body_bytes(msg)

        seen = []
        orig_recv = type(apps[1].overlay_manager)._on_transaction

        def spy(self, peer, m):
            seen.append(m.__dict__.get("_wire_body"))
            return orig_recv(self, peer, m)

        type(apps[1].overlay_manager)._on_transaction = spy
        try:
            conn.crank()
        finally:
            type(apps[1].overlay_manager)._on_transaction = orig_recv
        # the class-level spy also sees node 1's pull-mode re-serve of
        # the body back to node 0 — EVERY delivery must arrive with
        # its cache pre-seeded, and the direct one with these bytes
        assert seen and seen[0] == body
        assert all(s is not None for s in seen)
    finally:
        shutdown(apps)


# ------------------------------------------------------------- demands --

def _advert(h):
    return StellarMessage(MessageType.FLOOD_ADVERT,
                          FloodAdvert(txHashes=[h]))


def _peer_to(app, other):
    other_id = other.config.node_id()
    for p in app.overlay_manager.get_authenticated_peers():
        if p.peer_id == other_id:
            return p
    raise AssertionError("no authenticated peer")


def test_demand_single_flight_second_advertiser_suppressed():
    """Two peers advertising the same hash before the body arrives
    used to mean two demands and a guaranteed duplicate body; now the
    hash is demanded from exactly one peer, the other is a backup."""
    clock, apps = make_apps(3)
    try:
        c01 = LoopbackPeerConnection(apps[0], apps[1])
        c02 = LoopbackPeerConnection(apps[0], apps[2])
        c01.crank()
        c02.crank()
        om = apps[0].overlay_manager
        p1 = _peer_to(apps[0], apps[1])
        p2 = _peer_to(apps[0], apps[2])
        h = sha256(b"some unseen tx hash")
        om._on_flood_advert(p1, _advert(h))
        om._on_flood_advert(p2, _advert(h))
        assert p1.demand_sent == 1
        assert p2.demand_sent == 0           # single flight
        assert om.demands.outstanding_from(h) == id(p1)
        assert om._demand_meters["suppressed"].count == 1
        rep = om.demand_report()
        assert rep["sent"] == 1 and rep["suppressed"] == 1
        assert rep["outstanding"] == 1
        assert rep["single_flight_efficiency"] == 0.5
    finally:
        shutdown(apps)


def test_demand_timeout_rotates_to_backup_advertiser():
    """A chaos `delay` on the demanded advertiser's link: the demand
    times out, is charged to that peer, and the retry rotates to the
    backup advertiser — the body arrives exactly once."""
    clock, apps = make_apps(3)
    try:
        c01 = LoopbackPeerConnection(apps[0], apps[1])
        c02 = LoopbackPeerConnection(apps[0], apps[2])
        c01.crank()
        c02.crank()
        # both 1 and 2 hold the body (direct submission); organic
        # adverts are suppressed so THIS test controls who advertises
        # what to node 0, and when
        apps[1].herder.tx_advert_cb = lambda *a, **k: None
        apps[2].herder.tx_advert_cb = lambda *a, **k: None
        frame, _msg = _tx_message(apps[1])
        assert m1.submit(apps[1], frame)["status"] == "PENDING"
        assert m1.submit(apps[2], frame)["status"] == "PENDING"
        c01.crank()
        c02.crank()
        om = apps[0].overlay_manager
        p1 = _peer_to(apps[0], apps[1])
        p2 = _peer_to(apps[0], apps[2])
        node1 = apps[1].config.node_id().hex()
        node0 = apps[0].config.node_id().hex()
        # every byte node 1 sends node 0 from here on is delayed 30s
        # of virtual time — the demanded body never arrives in window
        chaos.install(ChaosEngine(12, [FaultSpec(
            "overlay.send", "delay", prob=1.0, delay_ms=30000,
            match={"node": node1, "peer": node0})]))
        h = frame.full_hash()
        om._on_flood_advert(p1, _advert(h))     # demand goes to node 1
        om._on_flood_advert(p2, _advert(h))     # node 2 = backup
        assert om.demands.outstanding_from(h) == id(p1)
        for _ in range(200):
            c01.crank()
            c02.crank()
            if apps[0].herder.tx_queue.get_tx(h) is not None:
                break
            clock.crank(True)       # advance to the demand timer
        assert apps[0].herder.tx_queue.get_tx(h) is not None
        assert p1.demand_timeout >= 1
        assert p2.demand_retry == 1
        assert p2.demand_fulfilled == 1
        assert om.demands.outstanding_from(h) is None
        # the body arrived exactly once: no duplicate deliveries
        assert om.flood_kind_report()["tx"]["duplicates"] == 0
    finally:
        chaos.uninstall()
        shutdown(apps)


def test_demands_manager_rotation_unit():
    """sweep(): backoff steps per attempt, backup-first rotation,
    give-up after max_attempts."""
    dm = TxDemandsManager(max_attempts=3)
    a, b, c = object(), object(), object()
    peers = {id(p): p for p in (a, b, c)}
    h = b"\x01" * 32
    assert dm.note_advert(h, id(a), 0.0) is True
    assert dm.note_advert(h, id(b), 0.0) is False
    assert dm.note_advert(h, id(b), 0.0) is False   # no dup backups
    # not yet due
    retries, timeouts = dm.sweep(0.1, 0.2, 0.5, peers, [a, b, c])
    assert not retries and not timeouts
    # first timeout: rotate to backup b
    retries, timeouts = dm.sweep(0.3, 0.2, 0.5, peers, [a, b, c])
    assert timeouts == [id(a)]
    assert list(retries) == [id(b)]
    assert dm.outstanding_from(h) == id(b)
    # second attempt waits period + backoff
    retries, timeouts = dm.sweep(0.6, 0.2, 0.5, peers, [a, b, c])
    assert not retries and not timeouts
    retries, timeouts = dm.sweep(1.1, 0.2, 0.5, peers, [a, b, c])
    assert timeouts == [id(b)]
    assert len(retries) == 1 and id(b) not in retries
    # third expiry: attempts exhausted, record dropped
    retries, timeouts = dm.sweep(9.9, 0.2, 0.5, peers, [a, b, c])
    assert len(timeouts) == 1 and not retries
    assert len(dm) == 0


def test_demands_manager_known_hash_retired():
    dm = TxDemandsManager()
    h = b"\x02" * 32
    a = object()
    dm.note_advert(h, id(a), 0.0)
    retries, timeouts = dm.sweep(10.0, 0.2, 0.5, {id(a): a}, [a],
                                 is_known=lambda _h: True)
    assert not retries and not timeouts and len(dm) == 0


def test_old_slot_scp_envelope_not_refloded():
    """SCP relay gate: an envelope for a slot strictly below the LCL
    is ingested but NOT re-flooded (churn/boot GET_SCP_STATE echoes
    were the cluster harness's largest duplicate source); an envelope
    at or above the LCL still relays (followers externalize off it)."""
    clock, apps = make_apps(3)
    try:
        c01 = LoopbackPeerConnection(apps[0], apps[1])
        c02 = LoopbackPeerConnection(apps[0], apps[2])
        c01.crank()
        c02.crank()
        om = apps[0].overlay_manager
        p1 = _peer_to(apps[0], apps[1])
        sent = []
        om.broadcast_message, orig = (
            lambda m, msg_hash=None: sent.append(m) or 1,
            om.broadcast_message)
        try:
            lcl = apps[0].ledger_manager.get_last_closed_ledger_num()
            for slot, expect_relay in ((max(0, lcl - 1), False),
                                       (lcl, True), (lcl + 1, True)):
                seen = len(sent)

                class _Env:
                    class statement:
                        slotIndex = slot
                msg = StellarMessage(MessageType.GET_PEERS)  # any body
                msg.value = _Env()

                import stellar_core_tpu.overlay.manager as mgr_mod
                herder = apps[0].herder
                herder.recv_scp_envelope, orig_recv = (
                    lambda e: mgr_mod.RecvState.ENVELOPE_STATUS_READY,
                    herder.recv_scp_envelope)
                try:
                    om._on_scp_message(p1, msg)
                finally:
                    herder.recv_scp_envelope = orig_recv
                assert (len(sent) > seen) == expect_relay, \
                    (slot, lcl, expect_relay)
        finally:
            om.broadcast_message = orig
    finally:
        shutdown(apps)


# ------------------------------------------------------------ floodgate --

class _FakePeer:
    def __init__(self):
        self.sent = []

    def is_authenticated(self):
        return True

    def send_message(self, msg):
        self.sent.append(msg)


def test_floodgate_forget_peer_is_indexed():
    """Churn fix: forget_peer walks only the records that name the
    peer (per-peer index), and the index stays in lockstep with
    clear_below GC."""
    fg = Floodgate()
    peers = [_FakePeer() for _ in range(3)]
    hashes = [sha256(b"m%d" % i) for i in range(100)]
    for i, h in enumerate(hashes):
        fg.add_record(None, peers[i % 2], ledger_seq=i // 10, msg_hash=h)
    assert len(fg._peer_index[id(peers[0])]) == 50
    # GC half the records: the index must shrink with them
    fg.clear_below(16)   # drops ledger_seq < 6 → i < 60
    assert len(fg._records) == 40
    assert all(h in fg._records
               for told in fg._peer_index.values() for h in told)
    fg.forget_peer(peers[0])
    assert id(peers[0]) not in fg._peer_index
    assert all(id(peers[0]) not in r.peers_told
               for r in fg._records.values())
    # records for the other peer untouched
    assert any(id(peers[1]) in r.peers_told
               for r in fg._records.values())
    # churn: reconnect-style repeated forget is a no-op, not a scan
    fg.forget_peer(peers[0])
    fg.forget_peer(peers[2])


def test_floodgate_broadcast_skips_told_peers():
    fg = Floodgate()
    p1, p2 = _FakePeer(), _FakePeer()
    msg = StellarMessage(MessageType.GET_PEERS)
    h = sha256(wire.body_bytes(msg))
    fg.add_record(msg, p1, 5, msg_hash=h)      # p1 delivered it to us
    assert fg.broadcast(msg, [p1, p2], 5, msg_hash=h) == 1
    assert not p1.sent and len(p2.sent) == 1
    # second broadcast: everyone told already
    assert fg.broadcast(msg, [p1, p2], 5, msg_hash=h) == 0


# -------------------------------------------------- duplicate-ratio sim --

def _pull_mode_flood_ratio(apps, conns, clock, n_txs):
    """Submit n_txs at node 0, crank the mesh until every node has
    every body, return (aggregate duplicate_ratio, tx dup total)."""
    frames = []
    master = m1.master_account(apps[0])
    for i in range(n_txs):
        d = m1.AppAccount(apps[0], SecretKey.from_seed(
            bytes([0x70 + i]) * 32))
        frames.append(master.tx([op_create_account(d.account_id,
                                                   10**10)]))
    for f in frames:
        assert m1.submit(apps[0], f)["status"] == "PENDING"
    for _ in range(60):
        moved = sum(c.crank() for c in conns)
        n = clock.crank(False)
        if moved == 0 and n == 0:
            if all(a.herder.tx_queue.get_tx(f.full_hash()) is not None
                   for a in apps for f in frames):
                break
            clock.crank(True)
    for a in apps:
        for f in frames:
            assert a.herder.tx_queue.get_tx(f.full_hash()) is not None
    unique = dup = tx_dup = 0
    for a in apps:
        rep = a.propagation.report()
        unique += rep["unique"]
        dup += rep["duplicates"]
        tx_dup += a.overlay_manager.flood_kind_report()["tx"][
            "duplicates"]
    return dup / max(1, unique), tx_dup


def test_loopback_4node_duplicate_ratio_below_one():
    """4-node complete graph, pull-mode tx flood: single-flight
    demands keep every body single-delivery — duplicate_ratio < 1.0
    (it measured 1.43 on this exact mesh before pull-mode, and
    double-demands kept it elevated after)."""
    clock, apps = make_apps(4)
    conns = []
    try:
        for i in range(4):
            for j in range(i + 1, 4):
                c = LoopbackPeerConnection(apps[i], apps[j])
                conns.append(c)
                c.crank()
        ratio, tx_dup = _pull_mode_flood_ratio(apps, conns, clock, 8)
        assert tx_dup == 0
        assert ratio < 1.0
    finally:
        shutdown(apps)


def test_tcp_4node_duplicate_ratio_matches_loopback():
    """The same 4-node mesh over REAL localhost sockets: the wire
    path must hold the same contract — no duplicate tx bodies,
    aggregate duplicate_ratio < 1.0 (was 1.5568 across real sockets
    in CLUSTER_r09)."""
    import time as _time
    clock = VirtualClock(ClockMode.REAL_TIME)
    seeds = [SecretKey.from_seed(sha256(b"wire-tcp-%d" % i))
             for i in range(4)]
    node_ids = [s.public_key().raw for s in seeds]
    base_port = 35300
    apps = []
    for i in range(4):
        cfg = Config()
        cfg.NETWORK_PASSPHRASE = PASSPHRASE
        cfg.NODE_SEED = seeds[i]
        cfg.NODE_IS_VALIDATOR = True
        cfg.RUN_STANDALONE = False
        cfg.FORCE_SCP = True
        cfg.MANUAL_CLOSE = True           # tx flood only, no SCP noise
        cfg.ALLOW_LOCALHOST_FOR_TESTING = True
        cfg.PEER_PORT = base_port + i
        cfg.KNOWN_PEERS = [f"127.0.0.1:{base_port + j}"
                           for j in range(i)]
        cfg.QUORUM_SET = QuorumSetConfig(threshold=3,
                                         validators=list(node_ids))
        apps.append(Application.create(clock, cfg))
    try:
        for a in apps:
            a.start()
        deadline = _time.monotonic() + 15.0
        while _time.monotonic() < deadline:
            clock.crank(True)
            if all(len(a.overlay_manager.get_authenticated_peers()) == 3
                   for a in apps):
                break
        assert all(len(a.overlay_manager.get_authenticated_peers()) == 3
                   for a in apps)
        master = m1.master_account(apps[0])
        frames = []
        for i in range(8):
            d = m1.AppAccount(apps[0], SecretKey.from_seed(
                bytes([0x90 + i]) * 32))
            frames.append(master.tx([op_create_account(
                d.account_id, 10**10)]))
        for f in frames:
            assert m1.submit(apps[0], f)["status"] == "PENDING"
        deadline = _time.monotonic() + 20.0
        while _time.monotonic() < deadline:
            clock.crank(True)
            if all(a.herder.tx_queue.get_tx(f.full_hash()) is not None
                   for a in apps for f in frames):
                break
        for a in apps:
            for f in frames:
                assert a.herder.tx_queue.get_tx(
                    f.full_hash()) is not None
        unique = dup = tx_dup = 0
        for a in apps:
            rep = a.propagation.report()
            unique += rep["unique"]
            dup += rep["duplicates"]
            tx_dup += a.overlay_manager.flood_kind_report()["tx"][
                "duplicates"]
        assert tx_dup == 0
        assert dup / max(1, unique) < 1.0
        # serialize-once held over the real wire too
        enc = {}
        for a in apps:
            for k, v in a.overlay_manager.encode_report().items():
                if k != "hit_ratio":
                    enc[k] = enc.get(k, 0) + v
        assert enc["cache_hit"] > enc["cache_miss"]
    finally:
        for a in apps:
            a.shutdown()
