"""Built-in Stellar Asset Contract tests.

Reference: the native token the embedded host ships for
CONTRACT_EXECUTABLE_STELLAR_ASSET (rust/src/contract.rs:261-340 wraps it;
driven from transactions/InvokeHostFunctionOpFrame.cpp:364): the SEP-41
token interface over classic trustlines/accounts. End-to-end via real
transactions on a standalone node; function-level reads via a host over
a LedgerTxn; the wasm→SAC cross-contract leg exercises invoker auth.
"""

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.soroban import sac, scvm
from stellar_core_tpu.soroban.host import (Budget, SorobanHost,
                                           contract_id_from_preimage,
                                           instance_key)
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.xdr import contract as cx
from stellar_core_tpu.xdr.ledger_entries import (AccountFlags, Asset,
                                                 AssetType, LedgerKey,
                                                 TrustLineAsset,
                                                 TrustLineFlags)
from stellar_core_tpu.xdr.transaction import _OperationBody, OperationType
from stellar_core_tpu.xdr.types import PublicKey

import test_standalone_app as m1
from test_soroban import RESOURCE_FEE, soroban_tx, submit_and_close
from txtest_utils import (make_asset, op_change_trust, op_create_account,
                          op_payment, op_set_options)


@pytest.fixture
def app():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    cfg = get_test_config()
    with Application.create(clock, cfg) as a:
        a.start()
        yield a


def addr_of(acct) -> cx.SCAddress:
    return cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_ACCOUNT,
                        acct.account_id)


def contract_addr(cid: bytes) -> cx.SCAddress:
    return cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid)


def sac_create_op(app, asset: Asset):
    preimage = cx.ContractIDPreimage(
        cx.ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ASSET, asset)
    cid = contract_id_from_preimage(app.config.network_id(), preimage)
    body = _OperationBody(
        OperationType.INVOKE_HOST_FUNCTION,
        cx.InvokeHostFunctionOp(hostFunction=cx.HostFunction(
            cx.HostFunctionType.HOST_FUNCTION_TYPE_CREATE_CONTRACT,
            cx.CreateContractArgs(
                contractIDPreimage=preimage,
                executable=cx.ContractExecutable(
                    cx.ContractExecutableType
                    .CONTRACT_EXECUTABLE_STELLAR_ASSET))), auth=[]))
    return body, cid


def source_auth(cid: bytes, fn: str):
    """The tx-source auth entry every direct SAC call rides on
    (reference: SOROBAN_CREDENTIALS_SOURCE_ACCOUNT)."""
    return cx.SorobanAuthorizationEntry(
        credentials=cx.SorobanCredentials(
            cx.SorobanCredentialsType.SOROBAN_CREDENTIALS_SOURCE_ACCOUNT),
        rootInvocation=cx.SorobanAuthorizedInvocation(
            function=cx.SorobanAuthorizedFunction(
                cx.SorobanAuthorizedFunctionType
                .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN,
                cx.InvokeContractArgs(contractAddress=contract_addr(cid),
                                      functionName=fn.encode(), args=[])),
            subInvocations=[]))


def invoke_op(cid: bytes, fn: str, args=(), auth="source"):
    auth_entries = [source_auth(cid, fn)] if auth == "source" \
        else list(auth)
    return _OperationBody(
        OperationType.INVOKE_HOST_FUNCTION,
        cx.InvokeHostFunctionOp(hostFunction=cx.HostFunction(
            cx.HostFunctionType.HOST_FUNCTION_TYPE_INVOKE_CONTRACT,
            cx.InvokeContractArgs(contractAddress=contract_addr(cid),
                                  functionName=fn.encode(),
                                  args=list(args))), auth=auth_entries))


def tl_key(acct, asset: Asset) -> LedgerKey:
    return LedgerKey.trust_line(acct.account_id,
                                TrustLineAsset.from_asset(asset))


def tl_balance(app, acct, asset: Asset) -> int:
    with LedgerTxn(app.ledger_manager.root) as ltx:
        le = ltx.load_without_record(tl_key(acct, asset))
        return le.data.value.balance if le else 0


def make_host(app, ltx, footprint_ro=(), footprint_rw=(),
              source=None) -> SorobanHost:
    """Function-level host for read calls (name/symbol/balance...)."""
    header = ltx.get_header()
    from stellar_core_tpu.soroban.network_config import SorobanNetworkConfig
    cfg = SorobanNetworkConfig(ltx)
    return SorobanHost(
        ltx, header, cfg,
        cx.LedgerFootprint(readOnly=list(footprint_ro),
                           readWrite=list(footprint_rw)),
        Budget(100_000_000), app.config.network_id(),
        source or PublicKey.ed25519(b"\x00" * 32))


def setup_usd(app):
    """issuer + two holders with USD trustlines, 1000 USD to alice;
    returns (master, issuer, alice, bob, asset, cid)."""
    master = m1.master_account(app)
    issuer = m1.AppAccount(app, SecretKey.from_seed(b"\x51" * 32))
    alice = m1.AppAccount(app, SecretKey.from_seed(b"\x52" * 32))
    bob = m1.AppAccount(app, SecretKey.from_seed(b"\x53" * 32))
    r = m1.submit(app, master.tx(
        [op_create_account(a.account_id, 10_000_0000000)
         for a in (issuer, alice, bob)]))
    assert r["status"] == "PENDING", r
    app.manual_close()
    for a in (issuer, alice, bob):
        a.sync_seq()
    asset = make_asset(b"USD", issuer.account_id)
    m1.submit(app, alice.tx([op_change_trust(asset, 10**15)]))
    m1.submit(app, bob.tx([op_change_trust(asset, 10**15)]))
    m1.submit(app, issuer.tx([op_payment(alice.muxed, 1000_0000000,
                                         asset)]))
    app.manual_close()

    body, cid = sac_create_op(app, asset)
    res = submit_and_close(app, soroban_tx(
        app, master, body, [], [instance_key(contract_addr(cid))]))
    assert res.result.result.disc.name == "txSUCCESS", res
    return master, issuer, alice, bob, asset, cid


def test_deploy_and_metadata(app):
    _, issuer, _, _, asset, cid = setup_usd(app)
    with LedgerTxn(app.ledger_manager.root) as ltx:
        host = make_host(app, ltx,
                         footprint_ro=[instance_key(contract_addr(cid))])
        assert host.call_contract(contract_addr(cid), b"decimals",
                                  []).value == 7
        name = host.call_contract(contract_addr(cid), b"name", [])
        assert bytes(name.value).startswith(b"USD:G")
        symbol = host.call_contract(contract_addr(cid), b"symbol", [])
        assert bytes(symbol.value) == b"USD"
        admin = host.call_contract(contract_addr(cid), b"admin", [])
        assert bytes(admin.value.value.value) == \
            issuer.key.public_key().raw
        ltx.rollback()


def test_transfer_moves_classic_trustline_balance(app):
    _, issuer, alice, bob, asset, cid = setup_usd(app)
    before_a = tl_balance(app, alice, asset)
    before_b = tl_balance(app, bob, asset)
    ro = [instance_key(contract_addr(cid))]
    rw = [tl_key(alice, asset), tl_key(bob, asset)]
    res = submit_and_close(app, soroban_tx(
        app, alice, invoke_op(cid, "transfer", [
            sac._addr_scval(addr_of(alice)),
            sac._addr_scval(addr_of(bob)),
            sac.sc_i128(250_0000000)]), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res
    assert tl_balance(app, alice, asset) == before_a - 250_0000000
    assert tl_balance(app, bob, asset) == before_b + 250_0000000
    # the stored meta is V3 and carries the SEP-41 transfer event
    # (reference: TransactionMetaV3.sorobanMeta)
    from stellar_core_tpu.xdr.ledger import TransactionMeta
    row = app.database.query_one(
        "SELECT txmeta FROM txhistory WHERE txid=?",
        (bytes(res.transactionHash),))
    meta = TransactionMeta.from_bytes(bytes(row[0]))
    assert meta.disc == 3
    ev = meta.value.sorobanMeta.events
    assert len(ev) == 1
    assert bytes(ev[0].body.value.topics[0].value) == b"transfer"


def test_transfer_requires_auth(app):
    _, issuer, alice, bob, asset, cid = setup_usd(app)
    ro = [instance_key(contract_addr(cid))]
    rw = [tl_key(alice, asset), tl_key(bob, asset)]
    # bob submits a transfer FROM alice with no auth entry for alice
    res = submit_and_close(app, soroban_tx(
        app, bob, invoke_op(cid, "transfer", [
            sac._addr_scval(addr_of(alice)),
            sac._addr_scval(addr_of(bob)),
            sac.sc_i128(1)]), ro, rw))
    assert res.result.result.disc.name == "txFAILED"


def test_transfer_from_issuer_mints_and_to_issuer_burns(app):
    _, issuer, alice, bob, asset, cid = setup_usd(app)
    ro = [instance_key(contract_addr(cid)),
          LedgerKey.account(issuer.account_id)]
    rw = [tl_key(alice, asset)]
    before = tl_balance(app, alice, asset)
    # issuer -> alice mints new units
    res = submit_and_close(app, soroban_tx(
        app, issuer, invoke_op(cid, "transfer", [
            sac._addr_scval(addr_of(issuer)),
            sac._addr_scval(addr_of(alice)),
            sac.sc_i128(10_0000000)]), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res
    assert tl_balance(app, alice, asset) == before + 10_0000000
    # alice -> issuer burns them again
    res = submit_and_close(app, soroban_tx(
        app, alice, invoke_op(cid, "transfer", [
            sac._addr_scval(addr_of(alice)),
            sac._addr_scval(addr_of(issuer)),
            sac.sc_i128(10_0000000)]), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res
    assert tl_balance(app, alice, asset) == before


def test_mint_requires_admin(app):
    _, issuer, alice, bob, asset, cid = setup_usd(app)
    ro = [instance_key(contract_addr(cid))]
    rw = [tl_key(bob, asset)]
    # alice (not admin) cannot mint
    res = submit_and_close(app, soroban_tx(
        app, alice, invoke_op(cid, "mint", [
            sac._addr_scval(addr_of(bob)), sac.sc_i128(5)]), ro, rw))
    assert res.result.result.disc.name == "txFAILED"
    # the issuer (admin) can
    before = tl_balance(app, bob, asset)
    res = submit_and_close(app, soroban_tx(
        app, issuer, invoke_op(cid, "mint", [
            sac._addr_scval(addr_of(bob)), sac.sc_i128(5)]), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res
    assert tl_balance(app, bob, asset) == before + 5


def test_native_sac_transfer(app):
    master = m1.master_account(app)
    alice = m1.AppAccount(app, SecretKey.from_seed(b"\x61" * 32))
    r = m1.submit(app, master.tx(
        [op_create_account(alice.account_id, 10_000_0000000)]))
    assert r["status"] == "PENDING"
    app.manual_close()
    alice.sync_seq()
    native = Asset(AssetType.ASSET_TYPE_NATIVE)
    body, cid = sac_create_op(app, native)
    res = submit_and_close(app, soroban_tx(
        app, master, body, [], [instance_key(contract_addr(cid))]))
    assert res.result.result.disc.name == "txSUCCESS", res

    def native_balance(acct):
        return m1.app_account_entry(app, acct.account_id).balance

    before_a, before_m = native_balance(alice), native_balance(master)
    ro = [instance_key(contract_addr(cid))]
    rw = [LedgerKey.account(alice.account_id),
          LedgerKey.account(master.account_id)]
    res = submit_and_close(app, soroban_tx(
        app, alice, invoke_op(cid, "transfer", [
            sac._addr_scval(addr_of(alice)),
            sac._addr_scval(addr_of(master)),
            sac.sc_i128(100_0000000)]), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res
    assert native_balance(master) == before_m + 100_0000000
    # alice also paid the tx fee out of the same balance
    fee_paid = before_a - native_balance(alice) - 100_0000000
    assert 0 < fee_paid <= 100 + RESOURCE_FEE


def test_approve_allowance_transfer_from(app):
    _, issuer, alice, bob, asset, cid = setup_usd(app)
    lcl = app.ledger_manager.get_last_closed_ledger_num()
    allow_key = sac.allowance_key(contract_addr(cid), addr_of(alice),
                                  addr_of(bob))
    ro = [instance_key(contract_addr(cid))]
    res = submit_and_close(app, soroban_tx(
        app, alice, invoke_op(cid, "approve", [
            sac._addr_scval(addr_of(alice)),
            sac._addr_scval(addr_of(bob)),
            sac.sc_i128(100), cx.SCVal(cx.SCValType.SCV_U32, lcl + 1000)]),
        ro, [allow_key]))
    assert res.result.result.disc.name == "txSUCCESS", res
    # spender moves 60 of the 100
    rw = [tl_key(alice, asset), tl_key(bob, asset), allow_key]
    res = submit_and_close(app, soroban_tx(
        app, bob, invoke_op(cid, "transfer_from", [
            sac._addr_scval(addr_of(bob)),
            sac._addr_scval(addr_of(alice)),
            sac._addr_scval(addr_of(bob)),
            sac.sc_i128(60)]), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res
    # remaining allowance is 40; moving 60 more must fail
    res = submit_and_close(app, soroban_tx(
        app, bob, invoke_op(cid, "transfer_from", [
            sac._addr_scval(addr_of(bob)),
            sac._addr_scval(addr_of(alice)),
            sac._addr_scval(addr_of(bob)),
            sac.sc_i128(60)]), ro, rw))
    assert res.result.result.disc.name == "txFAILED"


def test_allowance_expires_at_approved_ledger(app):
    """approve()'s live_until pins the allowance TTL: past it, the
    allowance reads zero and transfer_from fails."""
    _, issuer, alice, bob, asset, cid = setup_usd(app)
    lcl = app.ledger_manager.get_last_closed_ledger_num()
    allow_key = sac.allowance_key(contract_addr(cid), addr_of(alice),
                                  addr_of(bob))
    ro = [instance_key(contract_addr(cid))]
    res = submit_and_close(app, soroban_tx(
        app, alice, invoke_op(cid, "approve", [
            sac._addr_scval(addr_of(alice)),
            sac._addr_scval(addr_of(bob)),
            sac.sc_i128(100), cx.SCVal(cx.SCValType.SCV_U32, lcl + 3)]),
        ro, [allow_key]))
    assert res.result.result.disc.name == "txSUCCESS", res
    with LedgerTxn(app.ledger_manager.root) as ltx:
        from stellar_core_tpu.soroban.host import ttl_key_for
        ttl = ltx.load_without_record(ttl_key_for(allow_key))
        assert ttl.data.value.liveUntilLedgerSeq == lcl + 3
    for _ in range(5):
        app.manual_close()
    rw = [tl_key(alice, asset), tl_key(bob, asset), allow_key]
    res = submit_and_close(app, soroban_tx(
        app, bob, invoke_op(cid, "transfer_from", [
            sac._addr_scval(addr_of(bob)),
            sac._addr_scval(addr_of(alice)),
            sac._addr_scval(addr_of(bob)),
            sac.sc_i128(1)]), ro, rw))
    assert res.result.result.disc.name == "txFAILED"


def test_burn(app):
    _, issuer, alice, bob, asset, cid = setup_usd(app)
    before = tl_balance(app, alice, asset)
    ro = [instance_key(contract_addr(cid))]
    rw = [tl_key(alice, asset)]
    res = submit_and_close(app, soroban_tx(
        app, alice, invoke_op(cid, "burn", [
            sac._addr_scval(addr_of(alice)), sac.sc_i128(7)]), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res
    assert tl_balance(app, alice, asset) == before - 7


def test_set_authorized_requires_revocable_issuer(app):
    _, issuer, alice, bob, asset, cid = setup_usd(app)
    ro = [instance_key(contract_addr(cid)),
          LedgerKey.account(issuer.account_id)]
    rw = [tl_key(alice, asset)]
    false_v = cx.SCVal(cx.SCValType.SCV_BOOL, False)
    # issuer lacks AUTH_REVOCABLE → deauthorize fails
    res = submit_and_close(app, soroban_tx(
        app, issuer, invoke_op(cid, "set_authorized", [
            sac._addr_scval(addr_of(alice)), false_v]), ro, rw))
    assert res.result.result.disc.name == "txFAILED"
    # set AUTH_REVOCABLE, then deauthorize succeeds and blocks transfer
    m1.submit(app, issuer.tx([op_set_options(
        inflationDest=None, clearFlags=None,
        setFlags=AccountFlags.AUTH_REVOCABLE_FLAG, masterWeight=None,
        lowThreshold=None, medThreshold=None, highThreshold=None,
        homeDomain=None, signer=None)]))
    app.manual_close()
    res = submit_and_close(app, soroban_tx(
        app, issuer, invoke_op(cid, "set_authorized", [
            sac._addr_scval(addr_of(alice)), false_v]), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res
    with LedgerTxn(app.ledger_manager.root) as ltx:
        le = ltx.load_without_record(tl_key(alice, asset))
        assert not (le.data.value.flags & TrustLineFlags.AUTHORIZED_FLAG)
    res = submit_and_close(app, soroban_tx(
        app, alice, invoke_op(cid, "transfer", [
            sac._addr_scval(addr_of(alice)),
            sac._addr_scval(addr_of(bob)),
            sac.sc_i128(1)]), ro, [tl_key(alice, asset),
                                   tl_key(bob, asset)]))
    assert res.result.result.disc.name == "txFAILED"


def test_contract_balance_and_clawback(app):
    master, issuer, alice, bob, asset, cid = setup_usd(app)
    # enable clawback on the issuer BEFORE the contract balance exists
    # (classic rule: AUTH_CLAWBACK_ENABLED requires AUTH_REVOCABLE)
    r = m1.submit(app, issuer.tx([op_set_options(
        inflationDest=None, clearFlags=None,
        setFlags=(AccountFlags.AUTH_REVOCABLE_FLAG |
                  AccountFlags.AUTH_CLAWBACK_ENABLED_FLAG),
        masterWeight=None, lowThreshold=None, medThreshold=None,
        highThreshold=None, homeDomain=None, signer=None)]))
    assert r["status"] == "PENDING", r
    app.manual_close()
    assert m1.app_account_entry(app, issuer.account_id).flags & \
        AccountFlags.AUTH_CLAWBACK_ENABLED_FLAG
    holder = contract_addr(sha256(b"some-holder-contract"))
    bkey = sac.balance_key(contract_addr(cid), holder)
    ro = [instance_key(contract_addr(cid)),
          LedgerKey.account(issuer.account_id)]
    res = submit_and_close(app, soroban_tx(
        app, issuer, invoke_op(cid, "mint", [
            sac._addr_scval(holder), sac.sc_i128(500)]), ro, [bkey]))
    assert res.result.result.disc.name == "txSUCCESS", res
    with LedgerTxn(app.ledger_manager.root) as ltx:
        host = make_host(app, ltx, footprint_ro=[
            instance_key(contract_addr(cid)), bkey,
            LedgerKey.account(issuer.account_id)])
        bal = host.call_contract(contract_addr(cid), b"balance",
                                 [sac._addr_scval(holder)])
        assert sac.i128_of(bal) == 500
        ltx.rollback()
    # admin claws back 200
    res = submit_and_close(app, soroban_tx(
        app, issuer, invoke_op(cid, "clawback", [
            sac._addr_scval(holder), sac.sc_i128(200)]), ro, [bkey]))
    assert res.result.result.disc.name == "txSUCCESS", res
    with LedgerTxn(app.ledger_manager.root) as ltx:
        host = make_host(app, ltx, footprint_ro=[
            instance_key(contract_addr(cid)), bkey,
            LedgerKey.account(issuer.account_id)])
        bal = host.call_contract(contract_addr(cid), b"balance",
                                 [sac._addr_scval(holder)])
        assert sac.i128_of(bal) == 300
        ltx.rollback()


def test_native_contract_holder_authorized(app):
    """authorized() on a contract address with no balance entry for the
    NATIVE SAC: native balances are always authorized (the reference
    host never consults issuer flags — there is no issuer)."""
    master = m1.master_account(app)
    native = Asset(AssetType.ASSET_TYPE_NATIVE)
    body, cid = sac_create_op(app, native)
    res = submit_and_close(app, soroban_tx(
        app, master, body, [], [instance_key(contract_addr(cid))]))
    assert res.result.result.disc.name == "txSUCCESS", res
    holder = contract_addr(sha256(b"native-holder"))
    bkey = sac.balance_key(contract_addr(cid), holder)
    with LedgerTxn(app.ledger_manager.root) as ltx:
        host = make_host(app, ltx, footprint_ro=[
            instance_key(contract_addr(cid)), bkey])
        out = host.call_contract(contract_addr(cid), b"authorized",
                                 [sac._addr_scval(holder)])
        assert out.disc == cx.SCValType.SCV_BOOL and out.value is True
        ltx.rollback()


def test_issuer_balance_is_int64_max(app):
    """The issuer's balance in its own asset reads as i64::MAX, matching
    the reference host's get_balance — not i128::MAX."""
    _, issuer, _, _, asset, cid = setup_usd(app)
    with LedgerTxn(app.ledger_manager.root) as ltx:
        host = make_host(app, ltx, footprint_ro=[
            instance_key(contract_addr(cid)),
            LedgerKey.account(issuer.account_id)])
        bal = host.call_contract(contract_addr(cid), b"balance",
                                 [sac._addr_scval(addr_of(issuer))])
        assert sac.i128_of(bal) == 2 ** 63 - 1
        ltx.rollback()


def test_clawback_from_issuer_fails(app):
    """The issuer holds no trustline in its own asset; clawback must
    error rather than silently minting-by-spending."""
    master, issuer, alice, bob, asset, cid = setup_usd(app)
    r = m1.submit(app, issuer.tx([op_set_options(
        inflationDest=None, clearFlags=None,
        setFlags=(AccountFlags.AUTH_REVOCABLE_FLAG |
                  AccountFlags.AUTH_CLAWBACK_ENABLED_FLAG),
        masterWeight=None, lowThreshold=None, medThreshold=None,
        highThreshold=None, homeDomain=None, signer=None)]))
    assert r["status"] == "PENDING", r
    app.manual_close()
    ro = [instance_key(contract_addr(cid)),
          LedgerKey.account(issuer.account_id)]
    res = submit_and_close(app, soroban_tx(
        app, issuer, invoke_op(cid, "clawback", [
            sac._addr_scval(addr_of(issuer)), sac.sc_i128(1)]), ro, []))
    assert res.result.result.disc.name == "txFAILED"


def test_wasm_contract_moves_classic_asset(app):
    """The VERDICT r3 #3 'done' condition: a (deployed, interpreted)
    contract calls the SAC and classic trustline balances move, under
    invoker auth — no explicit auth entry for the contract address."""
    master, issuer, alice, bob, asset, cid = setup_usd(app)
    sac_addr = contract_addr(cid)

    # a treasury contract whose `pay` sends its own SAC balance onward
    treasury_fns = {
        "pay": scvm.op(
            scvm.sym("call"),
            scvm.op(scvm.sym("lit"),
                    cx.SCVal(cx.SCValType.SCV_ADDRESS, sac_addr)),
            scvm.op(scvm.sym("lit"), scvm.sym("transfer")),
            scvm.op(scvm.sym("self")),
            scvm.op(scvm.sym("arg"), scvm.u64(0)),
            scvm.op(scvm.sym("arg"), scvm.u64(1))),
    }
    code = scvm.make_code(treasury_fns)
    code_key = LedgerKey.contract_code(sha256(code))
    res = submit_and_close(app, soroban_tx(
        app, master, _OperationBody(
            OperationType.INVOKE_HOST_FUNCTION,
            cx.InvokeHostFunctionOp(hostFunction=cx.HostFunction(
                cx.HostFunctionType.HOST_FUNCTION_TYPE_UPLOAD_CONTRACT_WASM,
                code), auth=[])), [], [code_key]))
    assert res.result.result.disc.name == "txSUCCESS", res
    preimage = cx.ContractIDPreimage(
        cx.ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ADDRESS,
        cx._ContractIDPreimageFromAddress(
            address=addr_of(master), salt=b"\x42" * 32))
    tcid = contract_id_from_preimage(app.config.network_id(), preimage)
    taddr = contract_addr(tcid)
    res = submit_and_close(app, soroban_tx(
        app, master, _OperationBody(
            OperationType.INVOKE_HOST_FUNCTION,
            cx.InvokeHostFunctionOp(hostFunction=cx.HostFunction(
                cx.HostFunctionType.HOST_FUNCTION_TYPE_CREATE_CONTRACT,
                cx.CreateContractArgs(
                    contractIDPreimage=preimage,
                    executable=cx.ContractExecutable(
                        cx.ContractExecutableType.CONTRACT_EXECUTABLE_WASM,
                        sha256(code)))),
                auth=[cx.SorobanAuthorizationEntry(
                    credentials=cx.SorobanCredentials(
                        cx.SorobanCredentialsType
                        .SOROBAN_CREDENTIALS_SOURCE_ACCOUNT),
                    rootInvocation=cx.SorobanAuthorizedInvocation(
                        function=cx.SorobanAuthorizedFunction(
                            cx.SorobanAuthorizedFunctionType
                            .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CREATE_CONTRACT_HOST_FN,
                            cx.CreateContractArgs(
                                contractIDPreimage=preimage,
                                executable=cx.ContractExecutable(
                                    cx.ContractExecutableType
                                    .CONTRACT_EXECUTABLE_WASM,
                                    sha256(code)))),
                        subInvocations=[]))])),
        [code_key], [instance_key(taddr)]))
    assert res.result.result.disc.name == "txSUCCESS", res

    # fund the treasury contract with 100 USD (issuer mints to it)
    bkey = sac.balance_key(sac_addr, taddr)
    res = submit_and_close(app, soroban_tx(
        app, issuer, invoke_op(cid, "mint", [
            sac._addr_scval(taddr), sac.sc_i128(100)]),
        [instance_key(sac_addr), LedgerKey.account(issuer.account_id)],
        [bkey]))
    assert res.result.result.disc.name == "txSUCCESS", res

    # anyone invokes treasury.pay(bob, 60): the treasury contract itself
    # authorizes the transfer as the direct invoker of the SAC
    before_b = tl_balance(app, bob, asset)
    res = submit_and_close(app, soroban_tx(
        app, master, invoke_op(tcid, "pay", [
            sac._addr_scval(addr_of(bob)), sac.sc_i128(60)]),
        [code_key, instance_key(taddr), instance_key(sac_addr),
         LedgerKey.account(issuer.account_id)],
        [bkey, tl_key(bob, asset)]))
    assert res.result.result.disc.name == "txSUCCESS", res
    assert tl_balance(app, bob, asset) == before_b + 60
    with LedgerTxn(app.ledger_manager.root) as ltx:
        host = make_host(app, ltx, footprint_ro=[
            instance_key(sac_addr), bkey,
            LedgerKey.account(issuer.account_id)])
        bal = host.call_contract(sac_addr, b"balance",
                                 [sac._addr_scval(taddr)])
        assert sac.i128_of(bal) == 40
        ltx.rollback()


def test_sac_events_shape(app):
    """SEP-41 event: ['transfer', from, to, sep11-asset], i128 amount."""
    _, issuer, alice, bob, asset, cid = setup_usd(app)
    with LedgerTxn(app.ledger_manager.root) as ltx:
        host = make_host(app, ltx,
                         footprint_ro=[instance_key(contract_addr(cid))],
                         footprint_rw=[tl_key(alice, asset),
                                       tl_key(bob, asset)],
                         source=alice.account_id)
        host.set_auth_entries([cx.SorobanAuthorizationEntry(
            credentials=cx.SorobanCredentials(
                cx.SorobanCredentialsType
                .SOROBAN_CREDENTIALS_SOURCE_ACCOUNT),
            rootInvocation=cx.SorobanAuthorizedInvocation(
                function=cx.SorobanAuthorizedFunction(
                    cx.SorobanAuthorizedFunctionType
                    .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN,
                    cx.InvokeContractArgs(
                        contractAddress=contract_addr(cid),
                        functionName=b"transfer", args=[])),
                subInvocations=[]))])
        host.call_contract(contract_addr(cid), b"transfer", [
            sac._addr_scval(addr_of(alice)),
            sac._addr_scval(addr_of(bob)),
            sac.sc_i128(3)])
        assert len(host.events) == 1
        ev = host.events[0]
        topics = ev.body.value.topics
        assert bytes(topics[0].value) == b"transfer"
        assert topics[1].value.to_bytes() == addr_of(alice).to_bytes()
        assert topics[2].value.to_bytes() == addr_of(bob).to_bytes()
        assert bytes(topics[3].value).startswith(b"USD:G")
        assert sac.i128_of(ev.body.value.data) == 3
        ltx.rollback()


def test_sac_create_requires_matching_preimage(app):
    """A wasm executable with an asset preimage (or SAC executable with
    an address preimage) must be rejected."""
    master = m1.master_account(app)
    preimage = cx.ContractIDPreimage(
        cx.ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ADDRESS,
        cx._ContractIDPreimageFromAddress(
            address=addr_of(master), salt=b"\x43" * 32))
    cid = contract_id_from_preimage(app.config.network_id(), preimage)
    body = _OperationBody(
        OperationType.INVOKE_HOST_FUNCTION,
        cx.InvokeHostFunctionOp(hostFunction=cx.HostFunction(
            cx.HostFunctionType.HOST_FUNCTION_TYPE_CREATE_CONTRACT,
            cx.CreateContractArgs(
                contractIDPreimage=preimage,
                executable=cx.ContractExecutable(
                    cx.ContractExecutableType
                    .CONTRACT_EXECUTABLE_STELLAR_ASSET))), auth=[]))
    res = submit_and_close(app, soroban_tx(
        app, master, body, [],
        [instance_key(contract_addr(cid))]))
    assert res.result.result.disc.name == "txFAILED"
