"""Differential tests: TPU batch verifier vs the pure-Python oracle.

Mirrors the reference's crypto test tier (crypto/test/CryptoTests.cpp)
plus the extra kernel tier mandated by SURVEY.md §4: RFC-style vectors,
random valid/corrupted batches, strict-rejection edge cases
(non-canonical S/A/R, small-order A/R), and the sharded multi-device path
on the virtual 8-device CPU mesh.
"""

import hashlib

import numpy as np
import pytest

from stellar_core_tpu.crypto import ed25519_ref as ref
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.ops import fe8
from stellar_core_tpu.ops.verifier import (TpuBatchVerifier,
                                           ShardedBatchVerifier)


def _mk(n, msg_len=32, seed=0):
    """n (pub, sig, msg) tuples, all valid."""
    items = []
    for i in range(n):
        sk = SecretKey.pseudo_random_for_testing(seed * 1000 + i)
        msg = hashlib.sha256(b"msg%d-%d" % (seed, i)).digest()[:msg_len]
        items.append((sk.public_key().raw, sk.sign(msg), msg))
    return items


def _check(verifier, items):
    got = verifier.verify_tuples(items)
    want = [ref.verify(p, s, m) for p, s, m in items]
    assert got == want, (got, want)
    return got


@pytest.fixture(scope="module")
def verifier():
    return TpuBatchVerifier()


# ---------------------------------------------------------------- field ----

def test_fe8_mul_random_vs_python_ints():
    rng = np.random.default_rng(7)
    import jax.numpy as jnp
    B = 8
    # loose limbs up to 2^10-1 (the documented input bound)
    a = rng.integers(0, 1024, size=(32, B), dtype=np.int64).astype(np.int32)
    b = rng.integers(0, 1024, size=(32, B), dtype=np.int64).astype(np.int32)
    c = np.asarray(fe8.mul(jnp.asarray(a), jnp.asarray(b)))
    assert c.max() < 512 and c.min() >= 0, "limb-bound contract violated"
    for j in range(B):
        av = sum(int(a[i, j]) << (8 * i) for i in range(32))
        bv = sum(int(b[i, j]) << (8 * i) for i in range(32))
        cv = sum(int(c[i, j]) << (8 * i) for i in range(32))
        assert cv % ref.P == (av * bv) % ref.P


def test_fe8_sub_invert_canonical():
    import jax.numpy as jnp
    rng = np.random.default_rng(8)
    B = 8
    a = rng.integers(0, 1024, size=(32, B), dtype=np.int64).astype(np.int32)
    b = rng.integers(0, 1024, size=(32, B), dtype=np.int64).astype(np.int32)
    s = np.asarray(fe8.sub(jnp.asarray(a), jnp.asarray(b)))
    inv = np.asarray(fe8.to_canonical(fe8.invert(jnp.asarray(a))))
    for j in range(B):
        av = sum(int(a[i, j]) << (8 * i) for i in range(32))
        bv = sum(int(b[i, j]) << (8 * i) for i in range(32))
        sv = sum(int(s[i, j]) << (8 * i) for i in range(32))
        iv = sum(int(inv[i, j]) << (8 * i) for i in range(32))
        assert sv % ref.P == (av - bv) % ref.P
        assert iv == pow(av % ref.P, ref.P - 2, ref.P)
        assert iv < ref.P


def test_fe8_to_canonical_edges():
    import jax.numpy as jnp
    # values straddling p: p-1, p, p+1, 2p-1, 0, and a loose encoding
    for v in (0, 1, ref.P - 1, ref.P, ref.P + 1, 2 * ref.P - 1, 19, 38):
        limbs = np.array([[(v >> (8 * i)) & 0xFF] for i in range(32)],
                         dtype=np.int32)
        got = np.asarray(fe8.to_canonical(jnp.asarray(limbs)))
        gv = sum(int(got[i, 0]) << (8 * i) for i in range(32))
        assert gv == v % ref.P, v


# --------------------------------------------------------------- verify ----

def test_valid_batch(verifier):
    assert all(_check(verifier, _mk(5)))


def test_corrupted_batches(verifier):
    items = _mk(6, seed=1)
    bad = []
    for i, (p, s, m) in enumerate(items):
        if i % 3 == 0:   # flip a sig byte
            s = bytes([s[0] ^ 1]) + s[1:]
        elif i % 3 == 1:  # flip a msg byte
            m = bytes([m[0] ^ 0x80]) + m[1:]
        else:             # wrong pubkey
            p = SecretKey.pseudo_random_for_testing(999).public_key().raw
        bad.append((p, s, m))
    assert not any(_check(verifier, bad))


def test_mixed_valid_invalid(verifier):
    items = _mk(4, seed=2)
    p, s, m = items[2]
    items[2] = (p, s[:32] + bytes(32), m)  # S = 0: fails the equation
    got = _check(verifier, items)
    assert got == [True, True, False, True]


def test_noncanonical_s_rejected(verifier):
    p, s, m = _mk(1, seed=3)[0]
    s_val = int.from_bytes(s[32:], "little")
    s_plus_l = (s_val + ref.L).to_bytes(32, "little")
    _check(verifier, [(p, s[:32] + s_plus_l, m)])  # oracle says False


def test_noncanonical_a_r_rejected(verifier):
    p, s, m = _mk(1, seed=4)[0]
    # y >= p encodings: p+1 with bit pattern; also all-FF
    bad_enc = (ref.P + 1).to_bytes(32, "little")
    _check(verifier, [(bad_enc, s, m),
                      (p, bad_enc + s[32:], m),
                      (b"\xff" * 32, s, m)])


def test_small_order_a_r_rejected(verifier):
    # build a small-order point: [L]Q for a random curve point Q kills the
    # prime-order component, leaving pure 8-torsion
    small = None
    for i in range(40):
        q = ref.pt_decompress(hashlib.sha256(b"so%d" % i).digest(),
                              strict=True)
        if q is None:
            continue
        t = ref.pt_mul(ref.L, q)
        if ref.pt_is_small_order(t):
            small = ref.pt_compress(t)
            break
    assert small is not None
    p, s, m = _mk(1, seed=5)[0]
    _check(verifier, [(small, s, m), (p, small + s[32:], m)])


def test_identity_encoding_rejected(verifier):
    p, s, m = _mk(1, seed=6)[0]
    ident = ref.pt_compress(ref.IDENTITY)
    _check(verifier, [(ident, s, m), (p, ident + s[32:], m)])


def test_variable_msg_lengths(verifier):
    items = []
    for i, ln in enumerate((0, 1, 31, 32, 33, 100, 1000)):
        sk = SecretKey.pseudo_random_for_testing(7000 + i)
        msg = bytes(range(256)) * 4
        msg = msg[:ln]
        items.append((sk.public_key().raw, sk.sign(msg), msg))
    assert all(_check(verifier, items))


def test_batch_padding_edges(verifier):
    # batch of 1 and a batch crossing a bucket boundary (9 > MIN_BUCKET=8)
    assert all(_check(verifier, _mk(1, seed=8)))
    assert all(_check(verifier, _mk(9, seed=9)))


def test_sharded_matches_single():
    sharded = ShardedBatchVerifier()
    assert sharded.ndev == 8, "conftest should expose 8 virtual devices"
    items = _mk(16, seed=10)
    p, s, m = items[5]
    items[5] = (p, s[:32] + bytes(32), m)
    got = sharded.verify_tuples(items)
    want = [ref.verify(p, s, m) for p, s, m in items]
    assert got == want


def test_pallas_ladder_interpret_matches_oracle():
    """The experimental Pallas ladder (interpret mode) agrees with the
    XLA kernel's equation check on valid + corrupted prepared inputs."""
    import numpy as np
    from stellar_core_tpu.ops import ed25519_pallas as ep
    from stellar_core_tpu.ops.verifier import host_prepare

    items = _mk(8, seed=9)
    pubs = np.frombuffer(b"".join(p for p, _, _ in items),
                         dtype=np.uint8).reshape(-1, 32).copy()
    sigs = np.frombuffer(b"".join(s for _, s, _ in items),
                         dtype=np.uint8).reshape(-1, 64).copy()
    msgs = [m for _, _, m in items]
    sigs[3, 40] ^= 0x10   # corrupt one S
    k, neg_a, ok = host_prepare(pubs, sigs, msgs)
    assert ok.all()

    def layout(a):
        return np.ascontiguousarray(
            a.astype(np.int32).T)
    s_d = layout(sigs[:, 32:])
    k_d = layout(k)
    nax_d = layout(neg_a[:, :32])
    nay_d = layout(neg_a[:, 32:])
    r_d = layout(sigs[:, :32])
    got = np.asarray(ep.verify_kernel_pallas(
        s_d, k_d, nax_d, nay_d, r_d, interpret=True, blk=8))
    want = [ref.verify(bytes(pubs[i]), bytes(sigs[i]), msgs[i])
            for i in range(8)]
    assert list(got) == want


def test_hybrid_multihost_mesh_verifier():
    """2-D (dcn, ici) hybrid mesh — 2 virtual 'hosts' x 4 'chips' on the
    8-device CPU mesh (SURVEY.md §5.8 distributed-backend analogue):
    results identical to the single-device verifier."""
    import jax
    from stellar_core_tpu.ops.multihost import (HybridShardedVerifier,
                                                make_hybrid_mesh)
    devs = jax.devices()
    assert len(devs) >= 8, "conftest provides an 8-device CPU mesh"
    mesh = make_hybrid_mesh(devices=devs[:8], n_hosts=2)
    assert mesh.axis_names == ("dcn", "ici")
    assert mesh.devices.shape == (2, 4)
    v = HybridShardedVerifier(mesh=mesh)
    items = _mk(16, seed=13)
    # corrupt a couple
    items[2] = (items[2][0], items[2][1], b"other message")
    items[9] = (items[9][0], b"\x01" * 64, items[9][2])
    got = v.verify_tuples(items)
    want = [ref.verify(p, s, m) for p, s, m in items]
    assert got == want


def test_sharded_uneven_and_tiny_batches():
    """Batch sizes that don't divide the 8-device mesh pad through the
    bucketing path and still return exact per-signature results
    (VERDICT r02 #5 remainder coverage)."""
    sharded = ShardedBatchVerifier()
    for n, seed in ((1, 20), (7, 21), (13, 22), (17, 23)):
        items = _mk(n, seed=seed)
        if n >= 3:
            p, s, m = items[2]
            items[2] = (p, s, m + b"!")      # corrupt one
        got = sharded.verify_tuples(items)
        want = [ref.verify(p, s, m) for p, s, m in items]
        assert got == want, n


def test_node_selects_sharded_verifier_and_validates_through_it():
    """A node booted with SIGNATURE_VERIFY_BACKEND=tpu on the 8-device
    mesh must auto-select the sharded verifier and route txset
    validation through it (VERDICT r02 #5 'Done' condition)."""
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.simulation.drive import \
        validate_txset_through_batch_verifier
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock

    cfg = get_test_config()
    cfg.SIGNATURE_VERIFY_BACKEND = "tpu"
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    try:
        bv = app.batch_verifier
        # PR 5: app.batch_verifier is the backend supervisor (circuit
        # breaker, docs/ROBUSTNESS.md) wrapping the selected verifier;
        # attribute access proxies through, so ndev still resolves
        assert hasattr(bv, "breaker_state")
        assert isinstance(bv._inner, ShardedBatchVerifier)
        assert bv.ndev == 8
        calls = validate_txset_through_batch_verifier(app)
        assert calls
    finally:
        app.shutdown()


def test_mesh_config_selection():
    """SIGNATURE_VERIFY_MESH picks the topology; invalid values reject."""
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.ops.multihost import HybridShardedVerifier
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock

    for mesh, expected in (("single", TpuBatchVerifier),
                           ("sharded", ShardedBatchVerifier),
                           ("hybrid", HybridShardedVerifier)):
        cfg = get_test_config()
        cfg.SIGNATURE_VERIFY_BACKEND = "tpu"
        cfg.SIGNATURE_VERIFY_MESH = mesh
        app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
        try:
            # the mesh-selected verifier sits behind the supervisor
            assert type(app.batch_verifier._inner) is expected, mesh
        finally:
            app.shutdown()

    cfg = get_test_config()
    cfg.SIGNATURE_VERIFY_BACKEND = "tpu"
    cfg.SIGNATURE_VERIFY_MESH = "bogus"
    with pytest.raises(ValueError):
        Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)


# ------------------------------------------------------- device SHA-512 ----

class TestDeviceSha:
    """ops/sha512.py: on-device SHA-512 + exact mod-L vs hashlib / ints."""

    def test_sha512_96_vs_hashlib(self):
        from stellar_core_tpu.ops import sha512 as dsha
        rng = np.random.default_rng(11)
        r = rng.integers(0, 256, (17, 32)).astype(np.uint8)
        a = rng.integers(0, 256, (17, 32)).astype(np.uint8)
        m = rng.integers(0, 256, (17, 32)).astype(np.uint8)
        got = np.asarray(dsha.sha512_96(r, a, m))          # (64, B)
        for i in range(17):
            want = hashlib.sha512(
                bytes(r[i]) + bytes(a[i]) + bytes(m[i])).digest()
            assert bytes(got[:, i].astype(np.uint8)) == want, i

    def test_mod_l_random_and_adversarial(self):
        from stellar_core_tpu.ops import sha512 as dsha
        L = dsha.L
        rng = np.random.default_rng(12)
        vals = [int.from_bytes(rng.integers(0, 256, 64).astype(
            np.uint8).tobytes(), "little") for _ in range(24)]
        # adversarial: 0, 1, L-1, L, L+1, k*L near the top, all-0xFF,
        # max value, and values engineered to stress the fold carries
        vals += [0, 1, L - 1, L, L + 1, 2**512 - 1,
                 (2**512 // L) * L, (2**512 // L) * L - 1,
                 15 * L, 16 * L - 1, 2**256 - 1, 2**256, 2**269]
        arr = np.zeros((64, len(vals)), dtype=np.int32)
        for j, v in enumerate(vals):
            for i in range(64):
                arr[i, j] = (v >> (8 * i)) & 0xFF
        got = np.asarray(dsha.mod_l(arr))
        for j, v in enumerate(vals):
            want = v % L
            gv = int.from_bytes(
                bytes(got[:, j].astype(np.uint8)), "little")
            assert gv == want, (j, hex(v))

    def test_msg32_kernel_matches_hostk_and_oracle(self):
        """The v3 (device-SHA) kernel and the v2 (host-k) kernel agree
        with each other and the oracle on valid + corrupted batches."""
        import stellar_core_tpu.ops.verifier as V
        items = _mk(12)
        # corrupt a few: bad sig byte, bad pubkey, bad msg
        p, s, m = items[3]
        items[3] = (p, s[:10] + bytes([s[10] ^ 1]) + s[11:], m)
        p, s, m = items[5]
        items[5] = (p[:0] + bytes([p[0] ^ 4]) + p[1:], s, m)
        p, s, m = items[7]
        items[7] = (p, s, bytes([m[0] ^ 0x80]) + m[1:])
        got_dev = TpuBatchVerifier(device_sha=True).verify_tuples(items)
        got_host = TpuBatchVerifier(device_sha=False).verify_tuples(items)
        want = [ref.verify(pp, ss, mm) for pp, ss, mm in items]
        assert got_dev == want
        assert got_host == want

    def test_msg32_sharded_matches(self):
        """Device-SHA path through the sharded 8-device mesh verifier."""
        items = _mk(19, seed=3)
        v = ShardedBatchVerifier()
        got = v.verify_tuples(items)
        want = [ref.verify(p, s, m) for p, s, m in items]
        assert got == want
