"""Operation/transaction frame behavior tests (modeled on reference
src/transactions/test/TxTests and per-op test files)."""

import pytest

from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
from stellar_core_tpu.xdr.ledger_entries import (AccountFlags, LedgerKey,
                                                 TrustLineFlags)
from stellar_core_tpu.xdr.results import (
    CreateAccountResultCode, PaymentResultCode, TransactionResultCode,
)
from stellar_core_tpu.xdr.types import SignerKey, SignerKeyType
from stellar_core_tpu.xdr.ledger_entries import Signer

from txtest_utils import (
    TestAccount, TestLedger, for_all_versions, for_versions, make_asset,
    native, op_account_merge, op_allow_trust, op_bump_sequence,
    op_change_trust, op_create_account, op_manage_data, op_payment,
    op_set_options, op_set_trustline_flags, sign_frame,
)

XLM = 10_000_000  # stroops


@pytest.fixture
def ledger():
    return TestLedger()


@pytest.fixture
def root(ledger):
    return ledger.root_account


def tx_code(frame):
    return frame.result.result.disc


def op_code(frame, i=0):
    return frame.result.result.value[i].value.value.disc


# ---------------------------------------------------------- create account --

class TestCreateAccount:
    def test_success(self, ledger, root):
        a = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        assert ledger.balance(a.account_id) == 100 * XLM
        acc = ledger.account(a.account_id)
        assert acc.seqNum == ledger.header().ledgerSeq << 32

    def test_already_exists(self, ledger, root):
        a = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        frame = root.tx([op_create_account(a.account_id, 100 * XLM)])
        assert not ledger.apply_tx(frame)
        assert op_code(frame) == \
            CreateAccountResultCode.CREATE_ACCOUNT_ALREADY_EXIST

    def test_low_reserve(self, ledger, root):
        a = TestAccount.fresh(ledger)
        frame = root.tx([op_create_account(a.account_id, 1)])
        assert not ledger.apply_tx(frame)
        assert op_code(frame) == \
            CreateAccountResultCode.CREATE_ACCOUNT_LOW_RESERVE

    def test_underfunded(self, ledger, root):
        a = TestAccount.fresh(ledger)
        b = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        a.sync_seq()
        frame = a.tx([op_create_account(b.account_id, 1000 * XLM)])
        assert not ledger.apply_tx(frame)
        assert op_code(frame) == \
            CreateAccountResultCode.CREATE_ACCOUNT_UNDERFUNDED

    def test_fee_charged_even_on_failure(self, ledger, root):
        a = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        a.sync_seq()
        before = ledger.balance(a.account_id)
        frame = a.tx([op_create_account(TestAccount.fresh(ledger).account_id,
                                        1000 * XLM)])
        assert not ledger.apply_tx(frame)
        assert ledger.balance(a.account_id) == before - 100


# ----------------------------------------------------------------- payment --

class TestPayment:
    def test_native(self, ledger, root):
        a = TestAccount.fresh(ledger)
        b = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        assert root.create(b, 100 * XLM)
        a.sync_seq()
        assert a.pay(b, 10 * XLM)
        assert ledger.balance(b.account_id) == 110 * XLM
        assert ledger.balance(a.account_id) == 90 * XLM - 100

    def test_no_destination(self, ledger, root):
        ghost = TestAccount.fresh(ledger)
        frame = root.tx([op_payment(ghost.muxed, XLM)])
        assert not ledger.apply_tx(frame)
        assert op_code(frame) == PaymentResultCode.PAYMENT_NO_DESTINATION

    def test_underfunded_respects_reserve(self, ledger, root):
        a = TestAccount.fresh(ledger)
        b = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        assert root.create(b, 100 * XLM)
        a.sync_seq()
        # reserve = 2 * 0.5 XLM; full balance send must fail
        frame = a.tx([op_payment(b.muxed, 100 * XLM)])
        assert not ledger.apply_tx(frame)
        assert op_code(frame) == PaymentResultCode.PAYMENT_UNDERFUNDED

    def test_credit_payment_with_trust(self, ledger, root):
        issuer = TestAccount.fresh(ledger)
        holder = TestAccount.fresh(ledger)
        assert root.create(issuer, 100 * XLM)
        assert root.create(holder, 100 * XLM)
        issuer.sync_seq()
        holder.sync_seq()
        idr = make_asset(b"IDR", issuer.account_id)
        assert holder.apply([op_change_trust(idr, 1000)])
        assert issuer.pay(holder, 500, idr)   # mint
        tl = ledger.trustline(holder.account_id, idr)
        assert tl.balance == 500
        assert holder.pay(issuer, 200, idr)   # burn
        assert ledger.trustline(holder.account_id, idr).balance == 300

    def test_credit_line_full(self, ledger, root):
        issuer = TestAccount.fresh(ledger)
        holder = TestAccount.fresh(ledger)
        assert root.create(issuer, 100 * XLM)
        assert root.create(holder, 100 * XLM)
        issuer.sync_seq(); holder.sync_seq()
        idr = make_asset(b"IDR", issuer.account_id)
        assert holder.apply([op_change_trust(idr, 400)])
        frame = issuer.tx([op_payment(holder.muxed, 500, idr)])
        assert not ledger.apply_tx(frame)
        assert op_code(frame) == PaymentResultCode.PAYMENT_LINE_FULL

    def test_no_trust(self, ledger, root):
        issuer = TestAccount.fresh(ledger)
        holder = TestAccount.fresh(ledger)
        assert root.create(issuer, 100 * XLM)
        assert root.create(holder, 100 * XLM)
        issuer.sync_seq()
        idr = make_asset(b"IDR", issuer.account_id)
        frame = issuer.tx([op_payment(holder.muxed, 500, idr)])
        assert not ledger.apply_tx(frame)
        assert op_code(frame) == PaymentResultCode.PAYMENT_NO_TRUST


# ----------------------------------------------------------- auth required --

class TestAuth:
    def test_auth_required_flow(self, ledger, root):
        issuer = TestAccount.fresh(ledger)
        holder = TestAccount.fresh(ledger)
        assert root.create(issuer, 100 * XLM)
        assert root.create(holder, 100 * XLM)
        issuer.sync_seq(); holder.sync_seq()
        # issuer requires auth
        assert issuer.apply([op_set_options(
            setFlags=AccountFlags.AUTH_REQUIRED_FLAG |
            AccountFlags.AUTH_REVOCABLE_FLAG)])
        idr = make_asset(b"IDR", issuer.account_id)
        assert holder.apply([op_change_trust(idr, 1000)])
        tl = ledger.trustline(holder.account_id, idr)
        assert not (tl.flags & TrustLineFlags.AUTHORIZED_FLAG)
        # unauthorized payment fails
        frame = issuer.tx([op_payment(holder.muxed, 10, idr)])
        assert not ledger.apply_tx(frame)
        assert op_code(frame) == PaymentResultCode.PAYMENT_NOT_AUTHORIZED
        # authorize via SetTrustLineFlags, then payment works
        assert issuer.apply([op_set_trustline_flags(
            holder.account_id, idr,
            set_flags=TrustLineFlags.AUTHORIZED_FLAG)])
        assert issuer.pay(holder, 10, idr)
        # revoke again
        assert issuer.apply([op_set_trustline_flags(
            holder.account_id, idr,
            clear_flags=TrustLineFlags.AUTHORIZED_FLAG)])
        frame = holder.tx([op_payment(issuer.muxed, 5, idr)])
        assert not ledger.apply_tx(frame)
        assert op_code(frame) == PaymentResultCode.PAYMENT_SRC_NOT_AUTHORIZED

    def test_allow_trust_legacy(self, ledger, root):
        issuer = TestAccount.fresh(ledger)
        holder = TestAccount.fresh(ledger)
        assert root.create(issuer, 100 * XLM)
        assert root.create(holder, 100 * XLM)
        issuer.sync_seq(); holder.sync_seq()
        assert issuer.apply([op_set_options(
            setFlags=AccountFlags.AUTH_REQUIRED_FLAG |
            AccountFlags.AUTH_REVOCABLE_FLAG)])
        idr = make_asset(b"IDR", issuer.account_id)
        assert holder.apply([op_change_trust(idr, 1000)])
        assert issuer.apply([op_allow_trust(
            holder.account_id, b"IDR", TrustLineFlags.AUTHORIZED_FLAG)])
        assert issuer.pay(holder, 10, idr)


# -------------------------------------------------------------- multisig ---

class TestMultisig:
    def test_add_signer_and_threshold(self, ledger, root):
        a = TestAccount.fresh(ledger)
        b = TestAccount.fresh(ledger)
        other = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        assert root.create(b, 100 * XLM)
        a.sync_seq()
        sk2 = SignerKey(SignerKeyType.SIGNER_KEY_TYPE_ED25519,
                        other.key.public_key().raw)
        assert a.apply([op_set_options(
            signer=Signer(key=sk2, weight=1),
            masterWeight=1, medThreshold=2)])
        # single-signed payment now fails with txBAD_AUTH
        frame = a.tx([op_payment(b.muxed, XLM)])
        assert not ledger.apply_tx(frame)
        assert frame.result.result.value[0].disc == -1  # opBAD_AUTH
        # dual-signed succeeds
        frame = a.tx([op_payment(b.muxed, XLM)],
                     extra_signers=[other.key])
        assert ledger.apply_tx(frame)

    def test_bad_auth_extra(self, ledger, root):
        a = TestAccount.fresh(ledger)
        b = TestAccount.fresh(ledger)
        other = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        assert root.create(b, 100 * XLM)
        a.sync_seq()
        frame = a.tx([op_payment(b.muxed, XLM)],
                     extra_signers=[other.key])
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txBAD_AUTH_EXTRA


# ----------------------------------------------------------------- others ---

class TestMiscOps:
    def test_bump_sequence(self, ledger, root):
        a = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        a.sync_seq()
        target = a.seq + 100
        assert a.apply([op_bump_sequence(target)])
        assert ledger.account(a.account_id).seqNum == target
        a.seq = target

    def test_manage_data_lifecycle(self, ledger, root):
        a = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        a.sync_seq()
        assert a.apply([op_manage_data(b"k1", b"v1")])
        acc = ledger.account(a.account_id)
        assert acc.numSubEntries == 1
        assert a.apply([op_manage_data(b"k1", b"v2")])
        assert a.apply([op_manage_data(b"k1", None)])
        assert ledger.account(a.account_id).numSubEntries == 0
        frame = a.tx([op_manage_data(b"k1", None)])
        assert not ledger.apply_tx(frame)

    def test_account_merge(self, ledger, root):
        a = TestAccount.fresh(ledger)
        b = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        assert root.create(b, 100 * XLM)
        a.sync_seq()
        # accounts created in ledger N cannot merge until N+1 (reference:
        # MergeOpFrame SEQNUM_TOO_FAR, maxSeq = ledgerSeq << 32)
        frame_same_ledger = a.tx([op_account_merge(b.muxed)])
        assert not ledger.apply_tx(frame_same_ledger)
        ledger.advance_ledger()
        a.sync_seq()
        bal_a = ledger.balance(a.account_id)
        frame = a.tx([op_account_merge(b.muxed)])
        assert ledger.apply_tx(frame)
        assert ledger.account(a.account_id) is None
        # merged balance = a's balance minus the fee it paid
        assert ledger.balance(b.account_id) == 100 * XLM + bal_a - 100

    def test_merge_with_subentries_fails(self, ledger, root):
        issuer = TestAccount.fresh(ledger)
        a = TestAccount.fresh(ledger)
        assert root.create(issuer, 100 * XLM)
        assert root.create(a, 100 * XLM)
        a.sync_seq()
        idr = make_asset(b"IDR", issuer.account_id)
        assert a.apply([op_change_trust(idr, 1000)])
        frame = a.tx([op_account_merge(root.muxed)])
        assert not ledger.apply_tx(frame)


# ------------------------------------------------------------ tx validity ---

class TestTxValidity:
    def test_bad_seq(self, ledger, root):
        a = TestAccount.fresh(ledger)
        b = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        assert root.create(b, 100 * XLM)
        a.sync_seq()
        frame = a.tx([op_payment(b.muxed, XLM)], seq=a.seq + 5)
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txBAD_SEQ

    def test_insufficient_fee(self, ledger, root):
        a = TestAccount.fresh(ledger)
        b = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        assert root.create(b, 100 * XLM)
        a.sync_seq()
        frame = a.tx([op_payment(b.muxed, XLM)], fee=50)
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txINSUFFICIENT_FEE

    def test_no_account(self, ledger, root):
        ghost = TestAccount.fresh(ledger)
        other = TestAccount.fresh(ledger)
        frame = ghost.tx([op_payment(other.muxed, XLM)], seq=1)
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txNO_ACCOUNT

    def test_bad_auth_wrong_key(self, ledger, root):
        a = TestAccount.fresh(ledger)
        b = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        assert root.create(b, 100 * XLM)
        a.sync_seq()
        imposter = TestAccount(ledger, b.key)
        imposter.key = b.key
        frame = a.tx([op_payment(b.muxed, XLM)])
        # strip real signature, sign with the wrong key
        frame.signatures.clear()
        sign_frame(frame, b.key)
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txBAD_AUTH

    def test_seqnum_consumed_on_failed_tx(self, ledger, root):
        a = TestAccount.fresh(ledger)
        assert root.create(a, 100 * XLM)
        a.sync_seq()
        frame = a.tx([op_create_account(
            TestAccount.fresh(ledger).account_id, 1000 * XLM)])
        assert not ledger.apply_tx(frame)
        assert ledger.account(a.account_id).seqNum == a.seq

    def test_missing_operation(self, ledger, root):
        frame = root.tx([])
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txMISSING_OPERATION


# ---------------------------------------------------------------------------
# Protocol-version sweeps (reference: for_versions_* test/test.h:41-60)
# ---------------------------------------------------------------------------

def test_set_options_flags_gate_sweeps_versions():
    """AUTH_CLAWBACK_ENABLED is only a known flag from protocol 17
    (account_ops ALL_ACCOUNT_FLAGS gate); the sweep pins the behavior on
    both sides of the boundary."""
    from stellar_core_tpu.xdr.ledger_entries import AccountFlags

    def body(ledger, v):
        acct = TestAccount.fresh(ledger)
        assert ledger.root_account.create(acct, 100 * XLM)
        acct.sync_seq()
        ok = acct.apply([op_set_options(
            setFlags=int(AccountFlags.AUTH_CLAWBACK_ENABLED_FLAG
                         | AccountFlags.AUTH_REVOCABLE_FLAG))])
        assert ok == (v >= 17), f"protocol {v}"

    for_all_versions(body)


def test_signer_weight_clamp_sweeps_versions():
    """Signer weight > 255 is rejected from protocol 10 (reference:
    SetOptionsOpFrame doCheckValid signer-weight rule)."""
    from stellar_core_tpu.xdr.ledger_entries import Signer
    from stellar_core_tpu.xdr.types import SignerKey, SignerKeyType

    def body(ledger, v):
        acct = TestAccount.fresh(ledger)
        assert ledger.root_account.create(acct, 100 * XLM)
        acct.sync_seq()
        other = TestAccount.fresh(ledger)
        signer = Signer(key=SignerKey(
            SignerKeyType.SIGNER_KEY_TYPE_ED25519,
            other.key.public_key().raw), weight=1000)
        ok = acct.apply([op_set_options(signer=signer)])
        assert not ok, f"protocol {v}"  # >=13 always post-v10 rule

    for_versions(13, 15, body)


def test_zero_balance_create_sweeps_versions():
    """startingBalance == 0 is CREATE_ACCOUNT_MALFORMED before protocol
    14 (sponsored creation era); allowed — but LOW_RESERVE unsponsored —
    from 14 (reference: CreateAccountOpFrame doCheckValid)."""
    from stellar_core_tpu.xdr.results import CreateAccountResultCode as CC

    def body(ledger, v):
        a = TestAccount.fresh(ledger)
        frame = ledger.root_account.tx([op_create_account(a.account_id, 0)])
        assert not ledger.apply_tx(frame)
        code = op_code(frame)
        if v < 14:
            assert code == CC.CREATE_ACCOUNT_MALFORMED, f"protocol {v}"
        else:
            assert code == CC.CREATE_ACCOUNT_LOW_RESERVE, f"protocol {v}"

    for_versions(13, 15, body)


def test_pool_share_trustline_sweeps_versions():
    """Pool-share trustlines are malformed before protocol 18
    (reference: ChangeTrustOpFrame + liquidity pools protocol gate)."""
    from stellar_core_tpu.xdr.transaction import (ChangeTrustAsset,
                                                  ChangeTrustOp)
    from stellar_core_tpu.xdr.transaction import OperationType as OT
    from stellar_core_tpu.xdr.ledger_entries import AssetType

    def body(ledger, v):
        issuer = TestAccount.fresh(ledger)
        holder = TestAccount.fresh(ledger)
        assert ledger.root_account.create(issuer, 100 * XLM)
        assert ledger.root_account.create(holder, 100 * XLM)
        holder.sync_seq()
        from stellar_core_tpu.xdr.transaction import _LPParams
        from stellar_core_tpu.xdr.ledger_entries import (
            LiquidityPoolConstantProductParameters,
            LiquidityPoolType)
        params = _LPParams(
            LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT,
            LiquidityPoolConstantProductParameters(
                assetA=native(),
                assetB=make_asset(b"USD", issuer.account_id),
                fee=30))
        # pool-share lines require trust on the constituent assets
        assert holder.apply([op_change_trust(
            make_asset(b"USD", issuer.account_id), 2**60)])
        line = ChangeTrustAsset(AssetType.ASSET_TYPE_POOL_SHARE, params)
        from txtest_utils import _op
        frame = holder.tx([_op(OT.CHANGE_TRUST,
                               ChangeTrustOp(line=line, limit=2**60))])
        ok = ledger.apply_tx(frame)
        assert ok == (v >= 18), f"protocol {v}"
        if v < 18:
            from stellar_core_tpu.xdr.results import ChangeTrustResultCode
            assert op_code(frame) == \
                ChangeTrustResultCode.CHANGE_TRUST_MALFORMED

    for_versions(17, 19, body)


def test_inflation_retired_sweeps_versions():
    """Inflation is only a supported operation before protocol 12
    (reference: InflationOpFrame::isOpSupported)."""
    from stellar_core_tpu.xdr.results import OperationResultCode
    from stellar_core_tpu.xdr.transaction import _OperationBody, Operation
    from stellar_core_tpu.xdr.transaction import OperationType as OT

    def body(ledger, v):
        op = Operation(sourceAccount=None,
                       body=_OperationBody(OT.INFLATION))
        frame = ledger.root_account.tx([op])
        ok = ledger.apply_tx(frame)
        assert not ok  # v>=13 only in sweeps: always retired
        res = frame.result.result.value[0]
        assert res.disc == OperationResultCode.opNOT_SUPPORTED, \
            f"protocol {v}"

    for_versions(13, 14, body)
