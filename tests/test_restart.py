"""Node restart: LCL + bucket list restore from disk (reference:
LedgerManagerImpl::loadLastKnownLedger + BucketManager::assumeState,
SURVEY.md §3.4/§5.4 — the DB + bucket dir + storestate ARE the
checkpoint)."""

import pytest

from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.util.timer import ClockMode, VirtualClock

import test_standalone_app as m1
from txtest_utils import op_create_account, op_payment


def make_cfg(tmp_path):
    cfg = get_test_config()
    cfg.DATABASE = f"sqlite3://{tmp_path}/node.db"
    cfg.BUCKET_DIR_PATH = str(tmp_path / "buckets")
    return cfg


def test_restart_restores_lcl_and_bucket_list(tmp_path):
    cfg = make_cfg(tmp_path)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    master = m1.master_account(app)
    from stellar_core_tpu.crypto.keys import SecretKey
    dest = m1.AppAccount(app, SecretKey.from_seed(b"\x07" * 32))
    m1.submit(app, master.tx([op_create_account(dest.account_id, 10**10)]))
    app.manual_close()
    dest.sync_seq()
    for _ in range(5):
        m1.submit(app, dest.tx([op_payment(master.muxed, 1000)]))
        app.manual_close()
    lcl = app.ledger_manager.get_last_closed_ledger_num()
    lcl_hash = app.ledger_manager.get_last_closed_ledger_hash()
    bl_hash = app.bucket_manager.bucket_list.get_hash()
    dest_balance = m1.app_account_entry(app, dest.account_id).balance
    app.shutdown()

    # a new process: same DB + bucket dir
    cfg2 = make_cfg(tmp_path)
    app2 = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg2)
    app2.start()
    try:
        assert app2.ledger_manager.get_last_closed_ledger_num() == lcl
        assert app2.ledger_manager.get_last_closed_ledger_hash() == lcl_hash
        assert app2.bucket_manager.bucket_list.get_hash() == bl_hash
        assert m1.app_account_entry(
            app2, dest.account_id).balance == dest_balance
        # the node keeps closing ledgers with a consistent bucket list
        master2 = m1.master_account(app2)
        master2.sync_seq()
        m1.submit(app2, master2.tx([op_payment(dest.muxed, 555)]))
        app2.manual_close()
        assert app2.ledger_manager.get_last_closed_ledger_num() == lcl + 1
        assert m1.app_account_entry(
            app2, dest.account_id).balance == dest_balance + 555
    finally:
        app2.shutdown()


def test_restart_with_missing_bucket_dir_fails_loudly(tmp_path):
    cfg = make_cfg(tmp_path)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    for _ in range(3):
        app.manual_close()
    app.shutdown()

    import shutil
    shutil.rmtree(tmp_path / "buckets")
    cfg2 = make_cfg(tmp_path)
    with pytest.raises(RuntimeError, match="missing bucket|mismatch"):
        app2 = Application.create(
            VirtualClock(ClockMode.VIRTUAL_TIME), cfg2)
        app2.start()
        app2.shutdown()


def test_restart_right_after_genesis(tmp_path):
    """Shutdown before any close must still restore cleanly (the
    genesis HAS is persisted too)."""
    cfg = make_cfg(tmp_path)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    bl_hash = app.bucket_manager.bucket_list.get_hash()
    app.shutdown()

    app2 = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                              make_cfg(tmp_path))
    app2.start()
    try:
        assert app2.ledger_manager.get_last_closed_ledger_num() == 1
        assert app2.bucket_manager.bucket_list.get_hash() == bl_hash
        app2.manual_close()
        assert app2.ledger_manager.get_last_closed_ledger_num() == 2
    finally:
        app2.shutdown()


def test_restart_without_persisted_has_fails_loudly(tmp_path):
    """A DB whose header commits to bucket state but has no persisted
    HAS must refuse to continue (silent divergence would fork)."""
    cfg = make_cfg(tmp_path)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    for _ in range(2):
        app.manual_close()
    # simulate a pre-HAS database
    app.database.execute(
        "DELETE FROM storestate WHERE statename = 'historyarchivestate'")
    app.shutdown()

    with pytest.raises(RuntimeError, match="no local HAS"):
        app2 = Application.create(
            VirtualClock(ClockMode.VIRTUAL_TIME), make_cfg(tmp_path))
        app2.start()
        app2.shutdown()
