"""MATRIX artifact family (ISSUE 20): the committed scenario-matrix
cell list, the typed per-cell verdict contract (even for wrecked
cells), the check_artifacts schema that gates it, and the cluster-side
fault-schedule builders the cells install over the chaos route."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

import bench_matrix                                        # noqa: E402
import check_artifacts                                     # noqa: E402

from stellar_core_tpu.simulation import topologies         # noqa: E402
from stellar_core_tpu.simulation.cluster import Cluster    # noqa: E402
from stellar_core_tpu.util import chaos                    # noqa: E402


# ------------------------------------------------------ the cell list --

def test_default_cells_cover_the_acceptance_matrix():
    cells = bench_matrix.default_cells()
    names = [c["name"] for c in cells]
    assert len(cells) >= 6
    assert len(names) == len(set(names))
    # one cell per fault family + the control + skewed-load + surge
    assert {"smoke_uniform", "zipf_surge", "smoke_partition",
            "smoke_flap", "smoke_slowlink", "sick_device"} <= set(names)
    by = {c["name"]: c for c in cells}
    assert by["zipf_surge"]["load"] == "zipf"
    assert by["zipf_surge"]["surge"] > 0
    assert by["smoke_partition"]["partition"]["window_s"] > 0
    assert by["smoke_flap"]["flap"]["period_s"] > 0
    assert by["smoke_slowlink"]["slow_link"]["bps"] > 0
    # the scaled cell: >= 24 real processes on the tiered topology
    big = by["full_tiered_24"]
    assert big["n_orgs"] * big["validators_per_org"] >= 24
    # --smoke drops exactly the scaled cell
    smoke_names = [c["name"]
                   for c in bench_matrix.default_cells("smoke")]
    assert smoke_names == [n for n in names if n != "full_tiered_24"]


def test_failed_cell_doc_is_typed():
    """A cell whose harness died still ships every typed verdict key —
    the MATRIX artifact's schema holds even for wrecked cells."""
    doc = bench_matrix._failed_cell(
        {"name": "x", "n_orgs": 6, "validators_per_org": 4}, "boom")
    for key in bench_matrix.CELL_VERDICT_KEYS:
        assert key in doc, key
    assert doc["nodes"] == 24
    assert doc["ok"] is False and doc["survival_ok"] is False
    assert doc["crashes"] == 0 and doc["error"] == "boom"


def test_matrix_artifact_folds_cell_verdicts():
    ok_cell = {"name": "a", "nodes": 3, "survival_ok": True,
               "rejoin_ok": True, "safety_ok": True, "slo_ok": True,
               "crashes": 0, "ok": True, "duplicate_ratio": 0.5}
    bad_cell = bench_matrix._failed_cell({"name": "b", "n_orgs": 6,
                                          "validators_per_org": 4},
                                         "dead")
    bad_cell["crashes"] = 2
    art = bench_matrix.matrix_artifact([ok_cell, bad_cell])
    assert art["metric"] == "matrix_cells_pass_fraction"
    assert art["value"] == 0.5 and art["unit"] == "fraction_cells_ok"
    assert art["cells_total"] == 2 and art["cells_ok"] == 1
    assert art["cells_failed"] == 1
    assert art["max_nodes"] == 24
    assert art["crashes_total"] == 2
    # duplicate evidence vs the CLUSTER_r12 floor
    assert art["duplicate_ratio_best"] == 0.5
    assert art["duplicate_baseline_r12"] == \
        bench_matrix.DUPLICATE_BASELINE_R12
    assert art["duplicate_vs_r12"] == pytest.approx(
        0.5 / bench_matrix.DUPLICATE_BASELINE_R12, abs=1e-3)
    assert art["cells"] == [ok_cell, bad_cell]
    # no cell reported a ratio: the comparison stays null, not fake
    art2 = bench_matrix.matrix_artifact([bad_cell])
    assert art2["duplicate_ratio_best"] is None
    assert art2["duplicate_vs_r12"] is None


# --------------------------------------------------- artifact schema --

def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def _valid_matrix_doc():
    cells = [{"name": "a", "nodes": 3, "survival_ok": True,
              "rejoin_ok": True, "safety_ok": True, "slo_ok": True,
              "crashes": 0, "ok": True}]
    art = bench_matrix.matrix_artifact(cells)
    art["host_load"] = {"start": {}, "end": {}}
    return art


def test_checker_matrix_family(tmp_path):
    good = _write(tmp_path, "MATRIX_r20.json", _valid_matrix_doc())
    assert check_artifacts.check_artifact(good) == []
    # every top-level evidence key is required
    for missing in ("cells", "cells_total", "cells_ok", "cells_failed",
                    "max_nodes", "crashes_total", "host_load"):
        doc = {k: v for k, v in _valid_matrix_doc().items()
               if k != missing}
        p = _write(tmp_path, "MATRIX_r21.json", doc)
        assert any(missing in x
                   for x in check_artifacts.check_artifact(p)), missing
    # an empty cell list gates nothing -> rejected
    p = _write(tmp_path, "MATRIX_r22.json",
               dict(_valid_matrix_doc(), cells=[]))
    assert any("non-empty" in x
               for x in check_artifacts.check_artifact(p))
    # a cell missing a verdict key is rejected, naming the cell
    doc = _valid_matrix_doc()
    del doc["cells"][0]["rejoin_ok"]
    p = _write(tmp_path, "MATRIX_r23.json", doc)
    assert any("'a'" in x and "rejoin_ok" in x
               for x in check_artifacts.check_artifact(p))
    # verdicts are type-checked: a bool smuggled in as a crash count
    # (and a string as a verdict) both fail
    doc = _valid_matrix_doc()
    doc["cells"][0]["crashes"] = True
    p = _write(tmp_path, "MATRIX_r24.json", doc)
    assert any("crashes" in x
               for x in check_artifacts.check_artifact(p))
    doc = _valid_matrix_doc()
    doc["cells"][0]["survival_ok"] = "yes"
    p = _write(tmp_path, "MATRIX_r25.json", doc)
    assert any("survival_ok" in x
               for x in check_artifacts.check_artifact(p))
    # a recorded harness failure stays legal
    err = _write(tmp_path, "MATRIX_r26.json", {
        "metric": "matrix_cells_pass_fraction",
        "error": "ClusterError('boot stalled')"})
    assert check_artifacts.check_artifact(err) == []


# -------------------------------------------- cluster fault builders --

def test_cluster_fault_schedule_builders(tmp_path):
    """The schedule builders emit chaos specs that (a) land on BOTH
    endpoints of each cut edge, (b) name the remote node id in the
    match, and (c) round-trip through chaos.schedule_from_json — the
    exact path the `chaos?mode=install` route takes."""
    c = Cluster(3, 1, str(tmp_path))
    minority = [c.nodes[0]]
    edges = c.cut_edges(minority)
    assert edges
    for na, nb in edges:
        assert (na is c.nodes[0]) != (nb is c.nodes[0])

    per = c.partition_schedules(minority, 10.0)
    # node0 carries one spec per cut edge, each naming the far end
    specs0 = per[c.nodes[0].name]
    assert len(specs0) == len(edges)
    assert {s["match"]["peer"] for s in specs0} == \
        {n.node_id.hex() for n in c.nodes[1:]
         if any(n in e for e in edges)}
    for name, specs in per.items():
        for s in specs:
            assert s["point"] == "overlay.link"
            assert s["kind"] == "partition"
            assert s["window_s"] == 10.0
    # and the far endpoints carry the mirror spec back at node0
    for na, nb in edges:
        far = nb if na is c.nodes[0] else na
        assert any(s["match"]["peer"] == c.nodes[0].node_id.hex()
                   for s in per[far.name])

    flap = c.flap_schedules(edges, 9.0, period_s=3.0, duty=0.4)
    for specs in flap.values():
        for s in specs:
            assert s["kind"] == "flap"
            assert s["period_s"] == 3.0 and s["duty"] == 0.4
            assert s["window_s"] == 9.0

    # shape_schedules: LinkLatency speaks bits/s, the chaos Shape
    # wants bytes/s — the builder must divide by 8
    lat = topologies.LinkLatency(seed=7, cross_org_ms=(30.0, 30.0),
                                 bandwidth_bps=8_000_000.0)
    shapes = c.shape_schedules(lat, window_s=12.0)
    assert shapes
    for specs in shapes.values():
        for s in specs:
            assert s["point"] == "overlay.send"
            assert s["kind"] == "slow_link"
            assert s["bps"] == pytest.approx(1_000_000.0)
            assert s["window_s"] == 12.0
            assert s["delay_ms"] > 0

    # merge keeps every family in ONE per-node schedule (install
    # REPLACES the engine) and the wire shape parses back into specs
    merged = Cluster.merge_schedules(per, flap, shapes)
    n0 = c.nodes[0].name
    assert len(merged[n0]) == (len(per[n0]) + len(flap.get(n0, []))
                               + len(shapes.get(n0, [])))
    for specs in merged.values():
        parsed = chaos.schedule_from_json(json.loads(json.dumps(specs)))
        assert len(parsed) == len(specs)
