"""Mesh observatory tests (ISSUE 8): hash-keyed propagation tracking,
SCP slot timelines, multi-node trace merge with flow stitching, the
clusterstatus route, and the observability satellites (stamp-map
bounds, clearmetrics clean-slate, trace_report cluster modes, flood
report in bench artifacts)."""

import json
import os
import sys

import pytest

from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.overlay.propagation import PropagationTracker
from stellar_core_tpu.simulation import LoadGenerator, topologies
from stellar_core_tpu.util import tracing
from stellar_core_tpu.util.metrics import MetricsRegistry
from stellar_core_tpu.util.timer import ClockMode, VirtualClock

import test_overlay as ovl

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

import trace_report                                        # noqa: E402


@pytest.fixture(autouse=True)
def _no_leftover_tracing():
    yield
    with tracing._state_lock:
        tracing._active_count = 0
        tracing.ENABLED = False


# ------------------------------------------------ merged cluster trace --

@pytest.fixture(scope="module")
def merged_trace_doc():
    """One traced 4-node run shared by the merge/flow/slot/report
    tests: accounts + payments over real SCP, every node recording,
    merged through Simulation.merged_trace."""
    sim = topologies.core(4)
    try:
        sim.start_tracing()
        sim.start_all_nodes()
        assert sim.crank_until(lambda: sim.have_all_externalized(2))
        app = sim.apps()[0]
        lg = LoadGenerator(app)
        assert lg.generate_accounts(4) == 4
        target = app.ledger_manager.get_last_closed_ledger_num() + 2
        assert sim.crank_until(lambda: sim.have_all_externalized(target))
        lg.sync_account_seqs()
        assert lg.generate_payments(4) == 4
        target = app.ledger_manager.get_last_closed_ledger_num() + 2
        assert sim.crank_until(lambda: sim.have_all_externalized(target))
        assert lg.failed == 0
        doc = sim.merged_trace()
        flood = app.command_handler.handle(
            "peers")["authenticated_peers"]["flood"]
        cluster = [a.command_handler.handle("clusterstatus")
                   for a in sim.apps()]
        timelines = dict(app.herder.slot_timelines)
    finally:
        sim.stop_all_nodes()
    return {"doc": doc, "flood": flood, "cluster": cluster,
            "timelines": timelines}


def test_merged_trace_has_one_process_lane_per_node(merged_trace_doc):
    doc = merged_trace_doc["doc"]
    events = json.loads(json.dumps(doc))["traceEvents"]   # serializable
    pids = {e["pid"] for e in events if e.get("ph") != "M"}
    assert len(pids) == 4
    # every lane carries process_name metadata with the node label
    named = {e["pid"]: e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert pids <= set(named)
    assert len(set(named.values())) == 4          # distinct labels
    # per-node async ids are label-scoped so tracks never merge
    for e in events:
        if e.get("ph") in ("b", "e"):
            assert ":" in e["id"], e


def test_flow_events_follow_tx_across_three_lanes(merged_trace_doc):
    """Acceptance: a single tx hash can be followed send→recv across
    ≥3 node lanes via flow events."""
    events = merged_trace_doc["doc"]["traceEvents"]
    # pick a tx hash that is ALSO on the submit node's e2e track
    e2e_ids = {e["id"].split(":", 1)[1] for e in events
               if e.get("ph") in ("b", "e") and e["name"] == "tx.e2e"}
    assert e2e_ids
    by_hash = {}
    for e in events:
        if e.get("ph") == "i" and e.get("name") in ("flood.send",
                                                    "flood.recv"):
            args = e.get("args") or {}
            if args.get("type") == "TRANSACTION":
                by_hash.setdefault(args["hash"], []).append(e)
    followed = [h for h, evs in by_hash.items()
                if h in e2e_ids and len({e["pid"] for e in evs}) >= 3]
    assert followed, "no tx hash observable on >=3 node lanes"
    h = followed[0]
    flows = sorted((e for e in events if e.get("ph") in ("s", "t", "f")
                    and e.get("id") == h), key=lambda e: e["ts"])
    assert flows, "no flow chain for the followed tx"
    assert flows[0]["ph"] == "s" and flows[-1]["ph"] == "f"
    assert all(e["ph"] == "t" for e in flows[1:-1])
    assert len({e["pid"] for e in flows}) >= 3
    # the chain strictly advances in time
    ts = [e["ts"] for e in flows]
    assert all(b > a for a, b in zip(ts, ts[1:]))
    # and connects a send to a recv: the first endpoint is the origin's
    # send, a later one is a different node's recv
    send_pids = {e["pid"] for e in by_hash[h]
                 if e["name"] == "flood.send"}
    recv_pids = {e["pid"] for e in by_hash[h]
                 if e["name"] == "flood.recv"}
    assert flows[0]["pid"] in send_pids
    assert recv_pids - send_pids


def test_slot_phase_spans_strictly_ordered_per_node(merged_trace_doc):
    events = merged_trace_doc["doc"]["traceEvents"]
    begins = {}
    for e in events:
        if e.get("ph") == "b" and e["name"].startswith("scp.slot."):
            phase = e["name"].rsplit(".", 1)[1]
            slot = e["args"]["slot"]
            begins.setdefault((e["pid"], slot), {})[phase] = e["ts"]
    assert begins, "no slot phase spans recorded"
    complete = 0
    for (pid, slot), phases in begins.items():
        if {"nominate", "prepare", "confirm"} <= set(phases):
            complete += 1
            assert phases["nominate"] <= phases["prepare"] \
                <= phases["confirm"], (pid, slot, phases)
    assert complete >= 4, "no node recorded a full phase progression"
    # herder-side timeline bounded and phase-ordered too
    for slot, tl in merged_trace_doc["timelines"].items():
        keys = [k for k in ("nominate", "prepare", "confirm",
                            "externalize") if k in tl]
        vals = [tl[k] for k in keys]
        assert vals == sorted(vals), (slot, tl)


def test_trace_report_slots_and_flood_modes(merged_trace_doc, tmp_path,
                                            capsys):
    """Acceptance: --slots and --flood each render a non-empty report
    from a merged multinode trace."""
    path = str(tmp_path / "merged.json")
    with open(path, "w") as f:
        json.dump(merged_trace_doc["doc"], f)
    rows = trace_report.report_slots(path)
    out = capsys.readouterr().out
    assert rows and "slot timelines" in out
    assert any(r["slowest"] for r in rows)
    summary = trace_report.report_flood(path)
    out = capsys.readouterr().out
    assert summary["messages"] > 0 and "hop-count" in out
    assert summary["recvs"] > summary["messages"]     # flood redundancy
    assert summary["duplicates"] > 0
    assert summary["links"], "no per-link latency measured"
    assert max(int(k) for k in summary["hop_histogram"]) >= 3


def test_duplicate_accounting_and_peers_route(merged_trace_doc):
    flood = merged_trace_doc["flood"]
    # a 4-node complete graph re-floods everything: duplicates certain
    assert flood["unique"] > 0 and flood["duplicates"] > 0
    assert flood["duplicate_ratio"] > 0
    assert flood["redundancy"] > 1.0


def test_clusterstatus_valid_for_every_node(merged_trace_doc):
    cluster = merged_trace_doc["cluster"]
    assert len(cluster) == 4
    for doc in cluster:
        json.dumps(doc)                              # valid JSON
        cs = doc["clusterstatus"]
        assert cs["node"] and cs["label"]
        assert cs["ledger"]["num"] >= 2 and cs["ledger"]["hash"]
        assert cs["close"]["count"] >= 2
        assert cs["flood"]["unique"] > 0
        assert cs["peers"]["authenticated"] == 3
        assert isinstance(cs["healthy"], bool)
        assert cs["slot_phases"]["nominate"]["count"] > 0
        assert cs["herder_state"]


# -------------------------------------------------- propagation bounds --

def test_stamp_map_bounded_and_dropped_counted():
    """Satellite: a never-externalized tx cannot grow the stamp map —
    TTL prune past the threshold, evictions counted in
    tracing.stamps.dropped (the ledger.transaction.e2e policy)."""
    m = MetricsRegistry()
    tr = PropagationTracker(metrics=m)
    tr.PRUNE_THRESHOLD = 100
    # a flood of never-externalized hashes at t=0
    for i in range(150):
        tr.on_recv(b"%032d" % i, now=0.0)
    assert len(tr) == 150          # inside the TTL nothing is dropped
    # one more arrival past the TTL prunes the stale backlog
    tr.on_recv(b"fresh" + b"\x00" * 27,
               now=tr.STAMP_TTL_SECONDS + 1.0)
    assert len(tr) <= tr.PRUNE_THRESHOLD
    dropped = m.to_json()["tracing.stamps.dropped"]["count"]
    assert dropped >= 150 - tr.PRUNE_THRESHOLD
    # externalize stamps are update-only: unseen hashes add nothing
    before = len(tr)
    tr.on_externalized(b"never-seen" + b"\x00" * 22)
    assert len(tr) == before


def test_propagation_duplicate_detection():
    tr = PropagationTracker()
    h = b"\x01" * 32
    assert tr.on_recv(h, now=1.0) is False      # first delivery
    assert tr.on_recv(h, now=2.0) is True       # redundant
    assert tr.on_recv(h, duplicate=False, now=3.0) is False  # override
    # a locally-admitted tx makes a later delivery a duplicate
    h2 = b"\x02" * 32
    tr.on_admitted(h2, now=1.0)
    assert tr.on_recv(h2, now=2.0) is True
    rep = tr.report()
    assert rep["unique"] == 2 and rep["duplicates"] == 2
    assert rep["redundancy"] == 2.0
    tr.clear()
    assert len(tr) == 0 and tr.report()["unique"] == 0


# ---------------------------------------------------- clearmetrics reset --

def test_clearmetrics_resets_peer_counters_and_stamp_dicts():
    from stellar_core_tpu.overlay import LoopbackPeerConnection
    clock, apps = ovl.make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        app = apps[0]
        peer = (app.overlay_manager.get_authenticated_peers())[0]
        peer.duplicate_messages = 7
        assert peer.messages_read > 0 and peer.bytes_written > 0
        app.propagation.on_recv(b"\x03" * 32)
        app.herder._tx_submit_times[b"\x04" * 32] = 1.0
        app.herder.slot_timelines[5] = {"nominate": 1.0}
        assert app.command_handler.handle(
            "clearmetrics")["status"] == "ok"
        assert peer.messages_read == 0 and peer.messages_written == 0
        assert peer.bytes_read == 0 and peer.bytes_written == 0
        assert peer.duplicate_messages == 0
        assert len(app.propagation) == 0
        assert app.herder._tx_submit_times == {}
        assert app.herder.slot_timelines == {}
        # flood counters reset via the registry clear
        assert app.metrics.to_json()[
            "overlay.flood.unique"]["count"] == 0
    finally:
        ovl.shutdown(apps)


def test_clusterstatus_on_bare_node():
    """The route answers on a standalone node too (no overlay peers,
    no SCP slots yet) — the multi-process harness must be able to poll
    it from boot."""
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             get_test_config())
    app.start()
    try:
        doc = app.command_handler.handle("clusterstatus")
        json.dumps(doc)
        cs = doc["clusterstatus"]
        assert cs["ledger"]["num"] >= 1
        assert cs["close"] == {"count": 0} or cs["close"]["count"] >= 0
        assert cs["peers"]["authenticated"] == 0
        assert cs["healthy"] is True
        app.manual_close()
        cs = app.command_handler.handle("clusterstatus")[
            "clusterstatus"]
        assert cs["close"]["count"] >= 1
        assert cs["close"]["p99_ms"] >= cs["close"]["median_ms"] >= 0
    finally:
        app.shutdown()


# ----------------------------------------------------- bench flood report --

def test_bench_flood_report_shape():
    """Acceptance: the TPSM/TPSMT artifact field carries the flood
    duplicate ratio and per-peer byte totals."""
    import bench
    from stellar_core_tpu.overlay import LoopbackPeerConnection
    clock, apps = ovl.make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        apps[0].propagation.on_recv(b"\x05" * 32)
        apps[0].propagation.on_recv(b"\x05" * 32)
        rep = bench._flood_report(apps)
        assert set(rep) == {"unique", "duplicates", "duplicate_ratio",
                            "bytes_sent_total", "bytes_received_total",
                            "per_peer_bytes",
                            # ISSUE 12 wire-path evidence sections
                            "demand", "encode", "by_kind"}
        # the artifact-schema contract: demand + encode always dicts
        assert isinstance(rep["demand"], dict)
        assert isinstance(rep["encode"], dict)
        assert rep["encode"]["cache_hit"] + \
            rep["encode"]["cache_miss"] > 0
        assert rep["unique"] == 1 and rep["duplicates"] == 1
        assert rep["duplicate_ratio"] == 1.0
        assert rep["bytes_sent_total"] > 0
        assert rep["per_peer_bytes"]
        row = rep["per_peer_bytes"][0]
        assert {"node", "peer", "bytes_sent", "bytes_received",
                "messages_sent", "messages_received",
                "duplicates"} <= set(row)
    finally:
        ovl.shutdown(apps)
