"""Protocol-next tree slice: the hot-archive bucket list (VERDICT r02 #6).

Three guarantees:
  1. curr's wire language is untouched — pinned curr encodings stay
     byte-identical with next_types imported, and the curr namespace
     contains no hot-archive types;
  2. the next namespace's hashes differ and its new types round-trip;
  3. the bucket subsystem's core behaviors (sorted buckets, newest
     wins, spill cadence, deterministic hashes, HAS round-trip,
     assume-state reconstruction) hold under BOTH namespaces — the
     live list (curr) and the hot-archive list (next) run the same
     sweep.

Reference mechanism: src/protocol-curr and src/protocol-next built and
CI'd side by side (Makefile.am:46-51).
"""

import pytest

from stellar_core_tpu.bucket.bucket import Bucket, merge_buckets
from stellar_core_tpu.bucket.bucket_list import BucketList
from stellar_core_tpu.bucket.hot_archive import (HotArchiveBucket,
                                                 HotArchiveBucketList,
                                                 merge_hot_archive)
from stellar_core_tpu.history.archive import HistoryArchiveState
from stellar_core_tpu.xdr import next_types, schema
from stellar_core_tpu.xdr.ledger import BucketEntry, BucketEntryType
from stellar_core_tpu.xdr.ledger import BucketMetadata as CurrBucketMeta
from stellar_core_tpu.xdr.ledger_entries import (LedgerEntry, LedgerKey,
                                                 ledger_entry_key)
from stellar_core_tpu.xdr.next_types import (HotArchiveBucketEntry,
                                             HotArchiveBucketEntryType)

from stellar_core_tpu.tx.tx_utils import make_account_ledger_entry
from stellar_core_tpu.xdr.types import PublicKey


def _acct(i: int, balance: int = 1000) -> LedgerEntry:
    return make_account_ledger_entry(
        PublicKey.ed25519(bytes([i]) * 32), balance, seq_num=i)


def _key(i: int) -> LedgerKey:
    return ledger_entry_key(_acct(i))


# ------------------------------------------------------------- guarantee 1
def test_curr_wire_bytes_untouched():
    """A pinned curr-protocol encoding stays byte-identical with the
    next tree loaded, and curr knows nothing of hot-archive types."""
    curr = schema.curr_namespace()
    assert "HotArchiveBucketEntry" not in curr
    assert "HotArchiveBucketEntryType" not in curr
    # pinned: curr BucketEntry METAENTRY(protocol 20) wire bytes
    be = BucketEntry(BucketEntryType.METAENTRY,
                     CurrBucketMeta(ledgerVersion=20))
    assert be.to_bytes().hex() == (
        "ffffffff" + "00000014" + "00000000")
    # curr BucketMetadata has no bucketListType arm to encode
    assert "_BucketMetadataExt" not in curr or not hasattr(
        curr.get("_BucketMetadataExt", object), "HOT_ARCHIVE")


def test_next_namespace_extends_and_differs():
    ident = schema.identity()
    assert ident["curr"] != ident["next"]
    nxt = schema.next_namespace()
    assert nxt["HotArchiveBucketEntry"] is HotArchiveBucketEntry
    # next BucketMetadata can carry the list discriminator; curr can't
    meta = next_types.BucketMetadata(
        ledgerVersion=23,
        ext=next_types._BucketMetadataExt(
            1, next_types.BucketListType.HOT_ARCHIVE))
    raw = meta.to_bytes()
    assert next_types.BucketMetadata.from_bytes(raw) == meta
    with pytest.raises(Exception):
        CurrBucketMeta.from_bytes(raw)


# ------------------------------------------------------------- guarantee 2
def test_hot_archive_entry_roundtrips():
    T = HotArchiveBucketEntryType
    cases = [
        HotArchiveBucketEntry(T.HOT_ARCHIVE_ARCHIVED, _acct(1)),
        HotArchiveBucketEntry(T.HOT_ARCHIVE_LIVE, _key(2)),
        HotArchiveBucketEntry(T.HOT_ARCHIVE_DELETED, _key(3)),
        HotArchiveBucketEntry(
            T.HOT_ARCHIVE_METAENTRY,
            next_types.BucketMetadata(
                ledgerVersion=23,
                ext=next_types._BucketMetadataExt(
                    1, next_types.BucketListType.HOT_ARCHIVE))),
    ]
    for be in cases:
        assert HotArchiveBucketEntry.from_bytes(be.to_bytes()) == be


# --------------------------------------------- guarantee 3: both namespaces
def _curr_bucket_ops():
    """(make_bucket, merge, key_of, lookup_disc) for the live list."""
    def mk(ids, dead_ids=()):
        return Bucket.fresh(20, [], [_acct(i) for i in ids],
                            [_key(i) for i in dead_ids])

    def merge(a, b, bottom):
        return merge_buckets(a, b, keep_dead=not bottom, protocol=20)

    return mk, merge


def _next_bucket_ops():
    def mk(ids, dead_ids=()):
        entries = [HotArchiveBucketEntry(
            HotArchiveBucketEntryType.HOT_ARCHIVE_ARCHIVED, _acct(i))
            for i in ids]
        entries += [HotArchiveBucketEntry(
            HotArchiveBucketEntryType.HOT_ARCHIVE_LIVE, _key(i))
            for i in dead_ids]
        return HotArchiveBucket.from_entries(entries, 23)

    def merge(a, b, bottom):
        return merge_hot_archive(a, b, 23, bottom_level=bottom)

    return mk, merge


@pytest.mark.parametrize("namespace", ["curr", "next"])
def test_bucket_sweep_both_namespaces(namespace):
    """Sorted entries, newest wins, tombstone elision at the bottom —
    the same sweep over the curr live bucket and the next hot-archive
    bucket."""
    mk, merge = (_curr_bucket_ops() if namespace == "curr"
                 else _next_bucket_ops())
    old = mk([1, 2, 3])
    new = mk([2], dead_ids=[3])
    merged = merge(old, new, False)
    body = [e for e in merged.entries()
            if getattr(e.disc, "name", "") not in
            ("METAENTRY", "HOT_ARCHIVE_METAENTRY")]
    # sorted by key bytes
    from stellar_core_tpu.bucket.hot_archive import _entry_key_bytes
    if namespace == "next":
        keys = [_entry_key_bytes(e) for e in body]
    else:
        from stellar_core_tpu.bucket.bucket_index import entry_index_key
        keys = [entry_index_key(e) for e in body]
    assert keys == sorted(keys)
    # newest wins: key 3 carries the tombstone/restored marker
    discs = {k: e.disc.name for k, e in zip(keys, body)}
    assert len(body) == 3
    # bottom-level merge drops the tombstone kind
    bottom = merge(old, new, True)
    bot_names = {e.disc.name for e in bottom.entries()}
    assert "DEADENTRY" not in bot_names
    assert "HOT_ARCHIVE_LIVE" not in bot_names
    # hashes deterministic
    again = merge(old, new, False)
    assert again.hash == merged.hash


def test_hot_archive_list_lifecycle():
    """archive → restore → lookup across spills; hash determinism."""
    T = HotArchiveBucketEntryType
    hal = HotArchiveBucketList()
    for seq in range(1, 40):
        archived = [_acct(seq % 7 + 1, balance=seq)] if seq % 3 else []
        restored = [_key(seq % 5 + 1)] if seq % 11 == 0 else []
        hal.add_batch(seq, 23, archived, restored, [])
    # newest archived version of account 1 wins
    be = hal.get_entry(_key(1))
    assert be is not None
    if be.disc == T.HOT_ARCHIVE_ARCHIVED:
        assert be.value.data.value.balance >= 1
    # deterministic rebuild
    hal2 = HotArchiveBucketList()
    for seq in range(1, 40):
        archived = [_acct(seq % 7 + 1, balance=seq)] if seq % 3 else []
        restored = [_key(seq % 5 + 1)] if seq % 11 == 0 else []
        hal2.add_batch(seq, 23, archived, restored, [])
    assert hal.get_hash() == hal2.get_hash()
    # restored entries read as LIVE markers until merged to bottom
    hal.add_batch(40, 23, [], [_key(2)], [])
    assert hal.get_entry(_key(2)).disc == T.HOT_ARCHIVE_LIVE


def test_has_carries_hot_archive_and_curr_json_unchanged():
    """HAS: next-protocol manifests add hotArchiveBuckets; curr JSON is
    byte-identical to a HAS built without the field; assume-state
    reconstructs the list from the manifest (the catchup leg)."""
    bl = BucketList()
    bl.add_batch(1, 20, [], [_acct(1)], [])
    has_curr = HistoryArchiveState.from_bucket_list(1, bl, "test net")
    base_json = has_curr.to_json()
    assert "hotArchiveBuckets" not in base_json
    # round-trip preserves absence
    again = HistoryArchiveState.from_json(base_json)
    assert again.hot_archive_buckets is None
    assert again.to_json() == base_json

    hal = HotArchiveBucketList()
    for seq in range(1, 12):
        hal.add_batch(seq, 23, [_acct(seq % 4 + 1)], [], [])
    has_next = HistoryArchiveState.from_bucket_list(1, bl, "test net")
    has_next.hot_archive_buckets = hal.level_states()
    nxt_json = has_next.to_json()
    assert "hotArchiveBuckets" in nxt_json
    parsed = HistoryArchiveState.from_json(nxt_json)
    assert parsed.hot_archive_buckets == hal.level_states()
    # referenced hot buckets join the download set
    hot_hashes = {h for lvl in hal.level_states()
                  for h in (lvl["curr"], lvl["snap"])
                  if set(h) != {"0"}}
    assert hot_hashes <= set(parsed.bucket_hashes())

    # assume-state: reconstruct from the manifest + bucket store
    store = {}
    for lvl in hal.levels:
        for b in (lvl.curr, lvl.snap):
            if not b.is_empty():
                store[b.hash.hex()] = b.raw_bytes()
    rebuilt = HotArchiveBucketList.from_level_states(
        parsed.hot_archive_buckets, store.__getitem__)
    assert rebuilt.get_hash() == hal.get_hash()
