"""LedgerTxn + Database tests.

Mirrors the behavioral coverage of the reference's LedgerTxnTests.cpp
(create/load/erase through nesting, commit/rollback folding, delta
classification) and LedgerTxnRoot SQL round-trips — the 'port their test
suites' behavior' mandate of SURVEY.md §7 hard-parts.
"""

import pytest

from stellar_core_tpu.db import Database
from stellar_core_tpu.ledger import (LedgerTxn, InMemoryLedgerTxnRoot,
                                     LedgerTxnRoot)
from stellar_core_tpu.util.checks import AssertionFailed
from stellar_core_tpu.xdr.ledger_entries import (
    AccountEntry, Asset, LedgerEntry, LedgerEntryType, LedgerKey,
    OfferEntry, Price, _LedgerEntryData)
from stellar_core_tpu.xdr.types import PublicKey, PublicKeyType, Uint256


def _acc_id(n: int):
    return PublicKey(PublicKeyType.PUBLIC_KEY_TYPE_ED25519,
                     bytes([n]) * 32)


def _account_entry(n: int, balance: int = 1000) -> LedgerEntry:
    ae = AccountEntry(accountID=_acc_id(n), balance=balance,
                      thresholds=b"\x01\x00\x00\x00")
    return LedgerEntry(
        lastModifiedLedgerSeq=1,
        data=_LedgerEntryData(LedgerEntryType.ACCOUNT, ae))


def _offer_entry(seller: int, offer_id: int, n: int, d: int,
                 amount: int = 100) -> LedgerEntry:
    of = OfferEntry(sellerID=_acc_id(seller), offerID=offer_id,
                    selling=Asset.native(), buying=Asset.native(),
                    amount=amount, price=Price(n=n, d=d))
    return LedgerEntry(lastModifiedLedgerSeq=1,
                       data=_LedgerEntryData(LedgerEntryType.OFFER, of))


@pytest.fixture(params=["memory", "sql"])
def root(request):
    if request.param == "memory":
        return InMemoryLedgerTxnRoot()
    db = Database(":memory:")
    db.initialize()
    return LedgerTxnRoot(db)


def test_create_load_erase(root):
    ltx = LedgerTxn(root)
    e = _account_entry(1)
    ltx.create(e)
    key = LedgerKey.account(_acc_id(1))
    assert ltx.load(key).data.value.balance == 1000
    ltx.erase(key)
    assert ltx.load(key) is None
    ltx.commit()
    ltx2 = LedgerTxn(root)
    assert ltx2.load(key) is None
    ltx2.rollback()


def test_commit_persists_to_root(root):
    with LedgerTxn(root) as ltx:
        ltx.create(_account_entry(1))
        ltx.commit()
    key = LedgerKey.account(_acc_id(1))
    with LedgerTxn(root) as ltx:
        assert ltx.load(key).data.value.balance == 1000


def test_rollback_discards(root):
    with LedgerTxn(root) as ltx:
        ltx.create(_account_entry(1))
        ltx.rollback()
    with LedgerTxn(root) as ltx:
        assert ltx.load(LedgerKey.account(_acc_id(1))) is None


def test_nested_commit_and_rollback(root):
    key1 = LedgerKey.account(_acc_id(1))
    key2 = LedgerKey.account(_acc_id(2))
    ltx = LedgerTxn(root)
    ltx.create(_account_entry(1))
    child = LedgerTxn(ltx)
    child.create(_account_entry(2))
    assert child.load(key1).data.value.balance == 1000
    child.commit()
    assert ltx.load(key2) is not None
    child2 = LedgerTxn(ltx)
    child2.erase(key2)
    child2.rollback()
    assert ltx.load(key2) is not None
    ltx.commit()
    with LedgerTxn(root) as chk:
        assert chk.load(key1) is not None and chk.load(key2) is not None


def test_parent_sealed_while_child_open(root):
    ltx = LedgerTxn(root)
    child = LedgerTxn(ltx)
    with pytest.raises(AssertionFailed):
        ltx.create(_account_entry(1))
    child.rollback()
    ltx.create(_account_entry(1))
    ltx.rollback()


def test_mutation_via_load_is_recorded(root):
    with LedgerTxn(root) as ltx:
        ltx.create(_account_entry(1, balance=500))
        ltx.commit()
    key = LedgerKey.account(_acc_id(1))
    with LedgerTxn(root) as ltx:
        e = ltx.load(key)
        e.data.value.balance = 750
        ltx.commit()
    with LedgerTxn(root) as ltx:
        assert ltx.load(key).data.value.balance == 750


def test_load_copies_do_not_alias_root(root):
    with LedgerTxn(root) as ltx:
        ltx.create(_account_entry(1, balance=500))
        ltx.commit()
    key = LedgerKey.account(_acc_id(1))
    with LedgerTxn(root) as ltx:
        e = ltx.load(key)
        e.data.value.balance = 999
        ltx.rollback()
    with LedgerTxn(root) as ltx:
        assert ltx.load(key).data.value.balance == 500


def test_delta_classification(root):
    with LedgerTxn(root) as ltx:
        ltx.create(_account_entry(1))
        ltx.create(_account_entry(2))
        ltx.commit()
    with LedgerTxn(root) as ltx:
        ltx.create(_account_entry(3))                      # init
        e = ltx.load(LedgerKey.account(_acc_id(1)))        # live
        e.data.value.balance = 1
        ltx.erase(LedgerKey.account(_acc_id(2)))           # dead
        d = ltx.get_delta()
        assert len(d.init) == 1 and len(d.live) == 1 and len(d.dead) == 1
        assert d.init[0].data.value.accountID == _acc_id(3)
        assert d.dead[0].value.accountID == _acc_id(2)
        ltx.commit()


def test_create_erase_within_txn_leaves_no_trace(root):
    with LedgerTxn(root) as ltx:
        ltx.create(_account_entry(7))
        ltx.erase(LedgerKey.account(_acc_id(7)))
        d = ltx.get_delta()
        assert not d.init and not d.live and not d.dead
        ltx.commit()


def test_best_offer_ordering(root):
    with LedgerTxn(root) as ltx:
        ltx.create(_offer_entry(1, 10, 3, 2))   # price 1.5
        ltx.create(_offer_entry(1, 11, 1, 1))   # price 1.0  <- best
        ltx.create(_offer_entry(2, 12, 1, 1))   # price 1.0, higher id
        ltx.commit()
    with LedgerTxn(root) as ltx:
        best = ltx.load_best_offer(Asset.native(), Asset.native())
        assert best.data.value.offerID == 11
        # erase it in a child; next best should surface
        ltx.erase(LedgerKey.offer(_acc_id(1), 11))
        best2 = ltx.load_best_offer(Asset.native(), Asset.native())
        assert best2.data.value.offerID == 12
        ltx.rollback()


def test_header_propagation(root):
    with LedgerTxn(root) as ltx:
        h = ltx.load_header()
        h.ledgerSeq = 42
        ltx.commit()
    assert root.get_header().ledgerSeq == 42


def test_sql_persistence_across_roots():
    db = Database(":memory:")
    db.initialize()
    root = LedgerTxnRoot(db)
    with LedgerTxn(root) as ltx:
        ltx.create(_account_entry(1, balance=123))
        ltx.commit()
    # new root over the same DB sees the entry (cache cold)
    root2 = LedgerTxnRoot(db)
    with LedgerTxn(root2) as ltx:
        assert ltx.load(
            LedgerKey.account(_acc_id(1))).data.value.balance == 123


def test_db_transaction_rollback():
    db = Database(":memory:")
    db.initialize()
    with pytest.raises(RuntimeError):
        with db.transaction():
            db.execute("INSERT INTO storestate VALUES ('a', 'b')")
            raise RuntimeError("boom")
    assert db.query_one(
        "SELECT state FROM storestate WHERE statename='a'") is None


def test_db_nested_savepoints():
    db = Database(":memory:")
    db.initialize()
    with db.transaction():
        db.execute("INSERT INTO storestate VALUES ('outer', '1')")
        try:
            with db.transaction():
                db.execute("INSERT INTO storestate VALUES ('inner', '2')")
                raise ValueError()
        except ValueError:
            pass
    assert db.query_one(
        "SELECT state FROM storestate WHERE statename='outer'") is not None
    assert db.query_one(
        "SELECT state FROM storestate WHERE statename='inner'") is None


def test_root_prefetch_batches_and_caches():
    """prefetch() warms the root cache in one query per table and serves
    subsequent loads without touching SQL (reference: LedgerTxnRoot
    prefetch / prefetchTxSourceIds)."""
    db = Database(":memory:")
    db.initialize()
    root = LedgerTxnRoot(db)
    with LedgerTxn(root) as ltx:
        for i in range(20):
            ltx.create(_account_entry(i, balance=1000 + i))
        ltx.commit()

    root2 = LedgerTxnRoot(db)
    keys = [LedgerKey.account(_acc_id(i)) for i in range(25)]  # 5 misses
    n = root2.prefetch(keys)
    assert n == 25
    calls = []
    orig = db.query_one
    db.query_one = lambda *a, **k: (calls.append(a), orig(*a, **k))[1]
    try:
        with LedgerTxn(root2) as ltx:
            for i in range(20):
                le = ltx.load_without_record(
                    LedgerKey.account(_acc_id(i)))
                assert le is not None and \
                    le.data.value.balance == 1000 + i
            for i in range(20, 25):
                assert ltx.load_without_record(
                    LedgerKey.account(_acc_id(i))) is None
    finally:
        db.query_one = orig
    assert not any("SELECT entry FROM accounts" in c[0] for c in calls), \
        "prefetched keys must not hit SQL again"
