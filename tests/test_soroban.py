"""Soroban host layer tests: upload → create → invoke through real
transactions against a standalone node; storage, TTL, auth, events,
budget, fees (reference behavior: InvokeHostFunctionOpFrame +
soroban-env-host e2e_invoke surface)."""

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.soroban import scvm
from stellar_core_tpu.soroban.host import (contract_id_from_preimage,
                                           instance_key,
                                           soroban_auth_payload,
                                           ttl_key_for)
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.xdr import contract as cx
from stellar_core_tpu.xdr.ledger_entries import LedgerKey
from stellar_core_tpu.xdr.transaction import (Memo, MemoType, MuxedAccount,
                                              Operation, _OperationBody,
                                              OperationType, Preconditions,
                                              PreconditionType, Transaction,
                                              TransactionEnvelope,
                                              TransactionV1Envelope, _TxExt,
                                              DecoratedSignature)
from stellar_core_tpu.xdr.types import EnvelopeType, PublicKey

import test_standalone_app as m1

RESOURCE_FEE = 10_000_000


@pytest.fixture(params=["scvm", "wasm"])
def app(request):
    """Each test runs twice: once against the builtin scvm build of the
    counter contract, once against the real-wasm build of the same
    logic (soroban/scvm_wasm.py compiler → soroban/wasm interpreter)."""
    global COUNTER_CODE
    COUNTER_CODE = CODE_BUILDS[request.param]
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    cfg = get_test_config()
    with Application.create(clock, cfg) as a:
        a.start()
        yield a


def soroban_tx(app, source, op_body, footprint_ro, footprint_rw,
               instructions=2_000_000, read=10000, write=10000,
               resource_fee=RESOURCE_FEE):
    sd = cx.SorobanTransactionData(
        resources=cx.SorobanResources(
            footprint=cx.LedgerFootprint(readOnly=footprint_ro,
                                         readWrite=footprint_rw),
            instructions=instructions, readBytes=read, writeBytes=write),
        resourceFee=resource_fee)
    source.seq += 1
    tx = Transaction(
        sourceAccount=source.muxed, fee=100 + resource_fee,
        seqNum=source.seq,
        cond=Preconditions(PreconditionType.PRECOND_NONE),
        memo=Memo(MemoType.MEMO_NONE),
        operations=[Operation(sourceAccount=None, body=op_body)],
        ext=_TxExt(1, sd))
    env = TransactionEnvelope(
        EnvelopeType.ENVELOPE_TYPE_TX,
        TransactionV1Envelope(tx=tx, signatures=[]))
    from stellar_core_tpu.tx.frame import make_frame
    frame = make_frame(env, app.config.network_id())
    sig = source.key.sign(frame.contents_hash())
    frame.signatures.append(DecoratedSignature(
        hint=source.key.public_key().hint(), signature=sig))
    env.value.signatures = frame.signatures
    return frame


def submit_and_close(app, frame):
    r = m1.submit(app, frame)
    assert r["status"] == "PENDING", r
    app.manual_close()
    row = app.database.query_one(
        "SELECT txresult FROM txhistory WHERE txid=?", (frame.full_hash(),))
    assert row is not None, "tx not applied"
    from stellar_core_tpu.xdr.results import TransactionResultPair
    return TransactionResultPair.from_bytes(bytes(row[0]))


COUNTER_FUNCTIONS = {
    "increment": scvm.op(
        scvm.sym("seq"),
        scvm.op(scvm.sym("put"), scvm.op(scvm.sym("lit"), scvm.sym("count")),
                scvm.op(scvm.sym("add"),
                        scvm.op(scvm.sym("if"),
                                scvm.op(scvm.sym("eq"),
                                        scvm.op(scvm.sym("get"),
                                                scvm.op(scvm.sym("lit"),
                                                        scvm.sym("count"))),
                                        cx.SCVal(cx.SCValType.SCV_VOID)),
                                scvm.u64(0),
                                scvm.op(scvm.sym("get"),
                                        scvm.op(scvm.sym("lit"),
                                                scvm.sym("count")))),
                        scvm.u64(1))),
        scvm.op(scvm.sym("get"), scvm.op(scvm.sym("lit"),
                                         scvm.sym("count")))),
    "get_count": scvm.op(scvm.sym("get"),
                         scvm.op(scvm.sym("lit"), scvm.sym("count"))),
    "auth_bump": scvm.op(
        scvm.sym("seq"),
        scvm.op(scvm.sym("require_auth"), scvm.op(scvm.sym("arg"),
                                                  scvm.u64(0))),
        scvm.op(scvm.sym("event"),
                scvm.op(scvm.sym("lit"), scvm.sym("bumped")),
                scvm.u64(1))),
    "boom": scvm.op(scvm.sym("fail")),
}

# scvm-only extension (the scvm_wasm compiler has no `log` mapping):
# used by the diagnostic-events test via an scvm build
NOISY_FUNCTIONS = dict(COUNTER_FUNCTIONS)
NOISY_FUNCTIONS["noisy"] = scvm.op(
    scvm.sym("seq"),
    scvm.op(scvm.sym("log"), scvm.op(scvm.sym("lit"),
                                     scvm.sym("hello-diag"))),
    scvm.u64(1))
NOISY_FUNCTIONS["noisy_boom"] = scvm.op(
    scvm.sym("seq"),
    scvm.op(scvm.sym("log"), scvm.op(scvm.sym("lit"),
                                     scvm.sym("hello-diag"))),
    scvm.op(scvm.sym("fail")))

from stellar_core_tpu.soroban.scvm_wasm import make_wasm_code  # noqa: E402

CODE_BUILDS = {"scvm": scvm.make_code(COUNTER_FUNCTIONS),
               "wasm": make_wasm_code(COUNTER_FUNCTIONS)}
COUNTER_CODE = CODE_BUILDS["scvm"]


def wasm_hash():
    return sha256(COUNTER_CODE)


def upload_op():
    return _OperationBody(
        OperationType.INVOKE_HOST_FUNCTION,
        cx.InvokeHostFunctionOp(hostFunction=cx.HostFunction(
            cx.HostFunctionType.HOST_FUNCTION_TYPE_UPLOAD_CONTRACT_WASM,
            COUNTER_CODE), auth=[]))


def create_op(app, master):
    preimage = cx.ContractIDPreimage(
        cx.ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ADDRESS,
        cx._ContractIDPreimageFromAddress(
            address=cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_ACCOUNT,
                                 master.account_id),
            salt=b"\x01" * 32))
    cid = contract_id_from_preimage(app.config.network_id(), preimage)
    body = _OperationBody(
        OperationType.INVOKE_HOST_FUNCTION,
        cx.InvokeHostFunctionOp(hostFunction=cx.HostFunction(
            cx.HostFunctionType.HOST_FUNCTION_TYPE_CREATE_CONTRACT,
            cx.CreateContractArgs(
                contractIDPreimage=preimage,
                executable=cx.ContractExecutable(
                    cx.ContractExecutableType.CONTRACT_EXECUTABLE_WASM,
                    wasm_hash()))), auth=[
                        cx.SorobanAuthorizationEntry(
                            credentials=cx.SorobanCredentials(
                                cx.SorobanCredentialsType
                                .SOROBAN_CREDENTIALS_SOURCE_ACCOUNT),
                            rootInvocation=cx.SorobanAuthorizedInvocation(
                                function=cx.SorobanAuthorizedFunction(
                                    cx.SorobanAuthorizedFunctionType
                                    .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CREATE_CONTRACT_HOST_FN,
                                    cx.CreateContractArgs(
                                        contractIDPreimage=preimage,
                                        executable=cx.ContractExecutable(
                                            cx.ContractExecutableType
                                            .CONTRACT_EXECUTABLE_WASM,
                                            wasm_hash()))),
                                subInvocations=[]))]))
    return body, cid


def invoke_op(cid, fn, args=()):
    addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid)
    return _OperationBody(
        OperationType.INVOKE_HOST_FUNCTION,
        cx.InvokeHostFunctionOp(hostFunction=cx.HostFunction(
            cx.HostFunctionType.HOST_FUNCTION_TYPE_INVOKE_CONTRACT,
            cx.InvokeContractArgs(
                contractAddress=addr,
                functionName=fn.encode(),
                args=list(args))), auth=[]))


def counter_key(cid):
    addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid)
    return LedgerKey.contract_data(
        addr, cx.SCVal(cx.SCValType.SCV_SYMBOL, b"count"),
        cx.ContractDataDurability.PERSISTENT)


def deploy(app):
    """upload + create; returns (master, contract id)."""
    master = m1.master_account(app)
    code_key = LedgerKey.contract_code(wasm_hash())
    res = submit_and_close(app, soroban_tx(
        app, master, upload_op(), [], [code_key]))
    assert res.result.result.disc.name == "txSUCCESS", res
    body, cid = create_op(app, master)
    addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid)
    res = submit_and_close(app, soroban_tx(
        app, master, body, [code_key], [instance_key(addr)]))
    assert res.result.result.disc.name == "txSUCCESS", res
    return master, cid


def invoke_footprints(cid):
    addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid)
    ro = [LedgerKey.contract_code(wasm_hash()), instance_key(addr)]
    rw = [counter_key(cid)]
    return ro, rw


def test_upload_create_invoke_counter(app):
    master, cid = deploy(app)
    ro, rw = invoke_footprints(cid)
    for expected in (1, 2, 3):
        res = submit_and_close(app, soroban_tx(
            app, master, invoke_op(cid, "increment"), ro, rw))
        assert res.result.result.disc.name == "txSUCCESS", res
    # read back through the ledger
    with LedgerTxn(app.ledger_manager.root) as ltx:
        le = ltx.load_without_record(counter_key(cid))
        assert le is not None
        assert le.data.value.val.value == 3
        # TTL entry exists and is live
        ttl = ltx.load_without_record(ttl_key_for(counter_key(cid)))
        assert ttl is not None
        assert ttl.data.value.liveUntilLedgerSeq > \
            app.ledger_manager.get_last_closed_ledger_num()


def test_contract_trap_fails_tx(app):
    master, cid = deploy(app)
    ro, rw = invoke_footprints(cid)
    res = submit_and_close(app, soroban_tx(
        app, master, invoke_op(cid, "boom"), ro, rw))
    assert res.result.result.disc.name == "txFAILED"


def test_write_outside_footprint_fails(app):
    master, cid = deploy(app)
    ro, _ = invoke_footprints(cid)
    # no read-write footprint for the counter key → storage error
    res = submit_and_close(app, soroban_tx(
        app, master, invoke_op(cid, "increment"), ro, []))
    assert res.result.result.disc.name == "txFAILED"


def test_budget_exhaustion(app):
    master, cid = deploy(app)
    ro, rw = invoke_footprints(cid)
    res = submit_and_close(app, soroban_tx(
        app, master, invoke_op(cid, "increment"), ro, rw,
        instructions=200))  # far below the storage-op costs
    assert res.result.result.disc.name == "txFAILED"


def test_source_account_auth_and_event(app):
    master, cid = deploy(app)
    ro, rw = invoke_footprints(cid)
    addr_val = cx.SCVal(
        cx.SCValType.SCV_ADDRESS,
        cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_ACCOUNT,
                     master.account_id))
    body = invoke_op(cid, "auth_bump", [addr_val])
    # add source-account credentials
    body.value.auth = [cx.SorobanAuthorizationEntry(
        credentials=cx.SorobanCredentials(
            cx.SorobanCredentialsType.SOROBAN_CREDENTIALS_SOURCE_ACCOUNT),
        rootInvocation=cx.SorobanAuthorizedInvocation(
            function=cx.SorobanAuthorizedFunction(
                cx.SorobanAuthorizedFunctionType
                .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN,
                cx.InvokeContractArgs(
                    contractAddress=cx.SCAddress(
                        cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid),
                    functionName=b"auth_bump", args=[addr_val])),
            subInvocations=[]))]
    res = submit_and_close(app, soroban_tx(app, master, body, ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res


def test_missing_auth_fails(app):
    master, cid = deploy(app)
    ro, rw = invoke_footprints(cid)
    other = SecretKey.from_seed(b"\x55" * 32)
    addr_val = cx.SCVal(
        cx.SCValType.SCV_ADDRESS,
        cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_ACCOUNT,
                     PublicKey.ed25519(other.public_key().raw)))
    res = submit_and_close(app, soroban_tx(
        app, master, invoke_op(cid, "auth_bump", [addr_val]), ro, rw))
    assert res.result.result.disc.name == "txFAILED"


def test_soroban_tx_structural_validation(app):
    """Multi-op soroban txs and missing sorobanData are rejected at
    admission (reference: txMALFORMED)."""
    master = m1.master_account(app)
    body = upload_op()
    master.seq += 1
    tx = Transaction(
        sourceAccount=master.muxed, fee=100 + RESOURCE_FEE,
        seqNum=master.seq,
        cond=Preconditions(PreconditionType.PRECOND_NONE),
        memo=Memo(MemoType.MEMO_NONE),
        operations=[Operation(sourceAccount=None, body=body)],
        ext=_TxExt(0))  # missing sorobanData
    env = TransactionEnvelope(
        EnvelopeType.ENVELOPE_TYPE_TX,
        TransactionV1Envelope(tx=tx, signatures=[]))
    from stellar_core_tpu.tx.frame import make_frame
    frame = make_frame(env, app.config.network_id())
    sig = master.key.sign(frame.contents_hash())
    frame.signatures.append(DecoratedSignature(
        hint=master.key.public_key().hint(), signature=sig))
    env.value.signatures = frame.signatures
    r = m1.submit(app, frame)
    assert r["status"] == "ERROR"


def test_extend_and_restore_ttl(app):
    master, cid = deploy(app)
    ro, rw = invoke_footprints(cid)
    submit_and_close(app, soroban_tx(
        app, master, invoke_op(cid, "increment"), ro, rw))
    key = counter_key(cid)
    with LedgerTxn(app.ledger_manager.root) as ltx:
        before = ltx.load_without_record(
            ttl_key_for(key)).data.value.liveUntilLedgerSeq

    # extend the TTL via the op
    body = _OperationBody(
        OperationType.EXTEND_FOOTPRINT_TTL,
        cx.ExtendFootprintTTLOp(extendTo=50_000))
    res = submit_and_close(app, soroban_tx(
        app, master, body, [key], []))
    assert res.result.result.disc.name == "txSUCCESS", res
    with LedgerTxn(app.ledger_manager.root) as ltx:
        after = ltx.load_without_record(
            ttl_key_for(key)).data.value.liveUntilLedgerSeq
    assert after > before

    # simulate archival, then restore
    with LedgerTxn(app.ledger_manager.root) as ltx:
        ttl_le = ltx.load(ttl_key_for(key))
        ttl_le.data.value.liveUntilLedgerSeq = 1
        ltx.commit()
    body = _OperationBody(
        OperationType.RESTORE_FOOTPRINT,
        cx.RestoreFootprintOp())
    res = submit_and_close(app, soroban_tx(app, master, body, [], [key]))
    assert res.result.result.disc.name == "txSUCCESS", res
    with LedgerTxn(app.ledger_manager.root) as ltx:
        restored = ltx.load_without_record(
            ttl_key_for(key)).data.value.liveUntilLedgerSeq
    assert restored > app.ledger_manager.get_last_closed_ledger_num()


def test_fee_model_sanity():
    from stellar_core_tpu.soroban.fees import (
        compute_transaction_resource_fee, compute_write_fee_per_1kb)
    from stellar_core_tpu.soroban.network_config import initial_settings

    class _Cfg:
        pass
    from stellar_core_tpu.xdr.contract import ConfigSettingID
    settings = {s.disc: s.value for s in initial_settings()}
    cfg = _Cfg()
    cfg.fee_rate_per_instructions_increment = settings[
        ConfigSettingID.CONFIG_SETTING_CONTRACT_COMPUTE_V0]\
        .feeRatePerInstructionsIncrement
    cfg.ledger_cost = settings[
        ConfigSettingID.CONFIG_SETTING_CONTRACT_LEDGER_COST_V0]
    cfg.bandwidth = settings[
        ConfigSettingID.CONFIG_SETTING_CONTRACT_BANDWIDTH_V0]
    cfg.historical = settings[
        ConfigSettingID.CONFIG_SETTING_CONTRACT_HISTORICAL_DATA_V0]
    cfg.events_cfg = settings[
        ConfigSettingID.CONFIG_SETTING_CONTRACT_EVENTS_V0]

    res = cx.SorobanResources(
        footprint=cx.LedgerFootprint(readOnly=[], readWrite=[]),
        instructions=1_000_000, readBytes=5000, writeBytes=2000)
    non_ref, ref = compute_transaction_resource_fee(res, 500, 1000, cfg)
    assert non_ref > 0 and ref > 0
    # more instructions → more fee
    res2 = cx.SorobanResources(
        footprint=cx.LedgerFootprint(readOnly=[], readWrite=[]),
        instructions=10_000_000, readBytes=5000, writeBytes=2000)
    non_ref2, _ = compute_transaction_resource_fee(res2, 500, 1000, cfg)
    assert non_ref2 > non_ref
    # write fee grows with bucket list size
    low = compute_write_fee_per_1kb(0, cfg.ledger_cost)
    high = compute_write_fee_per_1kb(10 * 1024**3, cfg.ledger_cost)
    assert high > low


def test_soroban_config_upgrades(tmp_path):
    """LEDGER_UPGRADE_MAX_SOROBAN_TX_SET_SIZE and LEDGER_UPGRADE_CONFIG
    applied through a close (reference: Upgrades.cpp:301-362 +
    ConfigUpgradeSetFrame:1273-1400)."""
    import base64
    from stellar_core_tpu.crypto.sha import sha256
    from stellar_core_tpu.herder.upgrades import ConfigUpgradeSetFrame
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.soroban.host import ttl_key_for
    from stellar_core_tpu.soroban.network_config import SorobanNetworkConfig
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock
    from stellar_core_tpu.xdr.contract import (
        ConfigSettingEntry, ConfigSettingID, ConfigUpgradeSet,
        ConfigUpgradeSetKey, ContractDataDurability, ContractDataEntry,
        SCAddress, SCAddressType, SCVal, SCValType, TTLEntry)
    from stellar_core_tpu.xdr.ledger_entries import (LedgerEntry,
                                                     LedgerEntryType,
                                                     _LedgerEntryData,
                                                     _LedgerEntryExt)
    from stellar_core_tpu.xdr.types import ExtensionPoint

    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             get_test_config())
    app.start()
    try:
        # 1. max-soroban-tx-set-size via the admin API
        r = app.command_handler.handle("upgrades", {
            "mode": "set", "upgradetime": "0",
            "maxsorobantxsetsize": "55"})
        assert r["status"] == "ok"
        app.manual_close()
        with LedgerTxn(app.ledger_manager.root) as ltx:
            cfg = SorobanNetworkConfig(ltx)
            lanes = cfg._get(
                ConfigSettingID.CONFIG_SETTING_CONTRACT_EXECUTION_LANES)
            assert lanes.ledgerMaxTxCount == 55

        # 2. CONFIG upgrade: publish an upgrade set as TEMPORARY
        # contract data, then vote its key
        new_entry = ConfigSettingEntry(
            ConfigSettingID.CONFIG_SETTING_CONTRACT_MAX_SIZE_BYTES,
            131072)
        upgrade_set = ConfigUpgradeSet(updatedEntry=[new_entry])
        content_hash = sha256(upgrade_set.to_bytes())
        key = ConfigUpgradeSetKey(contractID=b"\x42" * 32,
                                  contentHash=content_hash)
        lk = ConfigUpgradeSetFrame.ledger_key(key)
        with LedgerTxn(app.ledger_manager.root) as ltx:
            cd = ContractDataEntry(
                ext=ExtensionPoint(0),
                contract=SCAddress(
                    SCAddressType.SC_ADDRESS_TYPE_CONTRACT, b"\x42" * 32),
                key=SCVal(SCValType.SCV_BYTES, bytes(content_hash)),
                durability=ContractDataDurability.TEMPORARY,
                val=SCVal(SCValType.SCV_BYTES, upgrade_set.to_bytes()))
            ltx.create(LedgerEntry(
                lastModifiedLedgerSeq=0,
                data=_LedgerEntryData(LedgerEntryType.CONTRACT_DATA, cd),
                ext=_LedgerEntryExt(0)))
            ttl = TTLEntry(keyHash=sha256(lk.to_bytes()),
                           liveUntilLedgerSeq=10_000)
            ltx.create(LedgerEntry(
                lastModifiedLedgerSeq=0,
                data=_LedgerEntryData(LedgerEntryType.TTL, ttl),
                ext=_LedgerEntryExt(0)))
            ltx.commit()

        r = app.command_handler.handle("upgrades", {
            "mode": "set", "upgradetime": "0",
            "configupgradesetkey":
                base64.b64encode(key.to_bytes()).decode()})
        assert r["status"] == "ok"
        app.manual_close()
        with LedgerTxn(app.ledger_manager.root) as ltx:
            cfg = SorobanNetworkConfig(ltx)
            max_size = cfg._get(
                ConfigSettingID.CONFIG_SETTING_CONTRACT_MAX_SIZE_BYTES)
            assert max_size == 131072

        # 3. a key pointing at missing data produces no vote (no crash)
        bogus = ConfigUpgradeSetKey(contractID=b"\x43" * 32,
                                    contentHash=b"\x44" * 32)
        r = app.command_handler.handle("upgrades", {
            "mode": "set", "upgradetime": "0",
            "configupgradesetkey":
                base64.b64encode(bogus.to_bytes()).decode()})
        assert r["status"] == "ok"
        lcl = app.ledger_manager.get_last_closed_ledger_num()
        app.manual_close()
        assert app.ledger_manager.get_last_closed_ledger_num() == lcl + 1
    finally:
        app.shutdown()


def test_config_upgrade_validation_rejects_bad_sets():
    """Non-upgradeable ids and zero limits are rejected at load;
    unloadable keys are rejected at ballot validation with an ltx
    (reference: ConfigUpgradeSetFrame::isValid + isValidForApply)."""
    from stellar_core_tpu.herder.upgrades import (ConfigUpgradeSetFrame,
                                                  Upgrades,
                                                  _is_valid_config_entry)
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock
    from stellar_core_tpu.xdr.contract import (
        ConfigSettingContractExecutionLanesV0, ConfigSettingEntry,
        ConfigSettingID, ConfigUpgradeSetKey)
    from stellar_core_tpu.xdr.ledger import LedgerUpgrade, LedgerUpgradeType

    # internal bookkeeping setting: not upgradeable
    from stellar_core_tpu.xdr.contract import StateArchivalSettings
    bad = ConfigSettingEntry(
        ConfigSettingID.CONFIG_SETTING_CONTRACT_EXECUTION_LANES,
        ConfigSettingContractExecutionLanesV0(ledgerMaxTxCount=0))
    assert not _is_valid_config_entry(bad)
    ok = ConfigSettingEntry(
        ConfigSettingID.CONFIG_SETTING_CONTRACT_EXECUTION_LANES,
        ConfigSettingContractExecutionLanesV0(ledgerMaxTxCount=10))
    assert _is_valid_config_entry(ok)

    # ballot-stage: a CONFIG upgrade whose key loads nothing is invalid
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             get_test_config())
    app.start()
    try:
        up = LedgerUpgrade(
            LedgerUpgradeType.LEDGER_UPGRADE_CONFIG,
            ConfigUpgradeSetKey(contractID=b"\x01" * 32,
                                contentHash=b"\x02" * 32))
        lcl = app.ledger_manager.get_last_closed_ledger_header()
        with LedgerTxn(app.ledger_manager.root) as ltx:
            assert not app.herder.upgrades.is_valid(
                up, lcl, nomination=False, ltx=ltx)
        # without an ltx (structural check only) it still passes, as in
        # the reference's isValid(..., nomination=false)
        assert app.herder.upgrades.is_valid(up, lcl, nomination=False)
    finally:
        app.shutdown()


def test_auth_tuples_collected_for_batch(app):
    """Address-credential auth signatures are collected as batch-verify
    tuples with the exact payload the host checks (BASELINE.md config
    #4: auth-entry batches)."""
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.soroban.host import soroban_auth_payload
    from stellar_core_tpu.tx.signature_checker import (
        PrevalidatedVerifier, collect_signature_tuples)

    master, cid = deploy(app)
    signer = SecretKey.from_seed(sha256(b"auth-signer"))
    addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_ACCOUNT,
                        PublicKey.ed25519(signer.public_key().raw))
    addr_val = cx.SCVal(cx.SCValType.SCV_ADDRESS, addr)
    root_inv = cx.SorobanAuthorizedInvocation(
        function=cx.SorobanAuthorizedFunction(
            cx.SorobanAuthorizedFunctionType
            .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN,
            cx.InvokeContractArgs(
                contractAddress=cx.SCAddress(
                    cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid),
                functionName=b"auth_bump", args=[addr_val])),
        subInvocations=[])
    nonce, expiration = 7, 10_000
    payload = soroban_auth_payload(app.config.network_id(), nonce,
                                   expiration, root_inv)
    sig = signer.sign(payload)
    sig_val = cx.SCVal(cx.SCValType.SCV_VEC, [cx.SCVal(
        cx.SCValType.SCV_MAP, [
            cx.SCMapEntry(key=cx.SCVal(cx.SCValType.SCV_SYMBOL,
                                       b"public_key"),
                          val=cx.SCVal(cx.SCValType.SCV_BYTES,
                                       signer.public_key().raw)),
            cx.SCMapEntry(key=cx.SCVal(cx.SCValType.SCV_SYMBOL,
                                       b"signature"),
                          val=cx.SCVal(cx.SCValType.SCV_BYTES, sig)),
        ])])
    body = invoke_op(cid, "auth_bump", [addr_val])
    body.value.auth = [cx.SorobanAuthorizationEntry(
        credentials=cx.SorobanCredentials(
            cx.SorobanCredentialsType.SOROBAN_CREDENTIALS_ADDRESS,
            cx.SorobanAddressCredentials(
                address=addr, nonce=nonce,
                signatureExpirationLedger=expiration,
                signature=sig_val)),
        rootInvocation=root_inv)]
    frame = soroban_tx(app, master, body, [], [])

    tuples = collect_signature_tuples([frame], app.config.network_id())
    # envelope signature + the auth-entry signature
    auth_tuples = [t for t in tuples if t[2] == payload]
    assert len(auth_tuples) == 1
    pub, s, m = auth_tuples[0]
    assert pub == signer.public_key().raw and s == sig
    # the batch result is exactly what the host's verify call consumes
    from stellar_core_tpu.crypto import ed25519_ref as ref
    pv = PrevalidatedVerifier()
    pv.add_results(tuples, [ref.verify(p, sg, ms) for p, sg, ms in tuples])
    assert pv(pub, s, m) is True
    assert pv.misses == 0


def test_malformed_auth_signature_never_crashes(app):
    """A void-typed signature map (valid XDR, hostile content) must not
    crash collection or the host — it yields no tuples and the host
    raises a clean auth error (remote-DoS guard)."""
    from stellar_core_tpu.tx.signature_checker import (
        collect_signature_tuples)

    master, cid = deploy(app)
    addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_ACCOUNT,
                        master.account_id)
    addr_val = cx.SCVal(cx.SCValType.SCV_ADDRESS, addr)
    bad_sig = cx.SCVal(cx.SCValType.SCV_VEC, [cx.SCVal(
        cx.SCValType.SCV_MAP, [
            cx.SCMapEntry(key=cx.SCVal(cx.SCValType.SCV_SYMBOL,
                                       b"public_key"),
                          val=cx.SCVal(cx.SCValType.SCV_VOID)),
            cx.SCMapEntry(key=cx.SCVal(cx.SCValType.SCV_SYMBOL,
                                       b"signature"),
                          val=cx.SCVal(cx.SCValType.SCV_VOID)),
        ])])
    body = invoke_op(cid, "auth_bump", [addr_val])
    body.value.auth = [cx.SorobanAuthorizationEntry(
        credentials=cx.SorobanCredentials(
            cx.SorobanCredentialsType.SOROBAN_CREDENTIALS_ADDRESS,
            cx.SorobanAddressCredentials(
                address=addr, nonce=1, signatureExpirationLedger=10_000,
                signature=bad_sig)),
        rootInvocation=cx.SorobanAuthorizedInvocation(
            function=cx.SorobanAuthorizedFunction(
                cx.SorobanAuthorizedFunctionType
                .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN,
                cx.InvokeContractArgs(
                    contractAddress=cx.SCAddress(
                        cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid),
                    functionName=b"auth_bump", args=[addr_val])),
            subInvocations=[]))]
    frame = soroban_tx(app, master, body, [], [])
    # collection is total: no tuples, no crash
    tuples = collect_signature_tuples([frame], app.config.network_id())
    assert all(len(t[0]) == 32 for t in tuples)
    # the apply path fails with a clean auth error, not a TypeError
    r = m1.submit(app, frame)
    assert r["status"] == "PENDING", r
    app.manual_close()
    from stellar_core_tpu.xdr.results import TransactionResultPair
    row = app.database.query_one(
        "SELECT txresult FROM txhistory WHERE txid=?", (frame.full_hash(),))
    pair = TransactionResultPair.from_bytes(bytes(row[0]))
    assert pair.result.result.disc.name == "txFAILED"


def test_diagnostic_events_in_v3_meta():
    """ENABLE_SOROBAN_DIAGNOSTIC_EVENTS surfaces the host's log sink as
    DIAGNOSTIC events in sorobanMeta (reference: Config.h:571; off by
    default — off-consensus, never hashed)."""
    global COUNTER_CODE
    saved_code = COUNTER_CODE
    COUNTER_CODE = scvm.make_code(NOISY_FUNCTIONS)
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock
    from stellar_core_tpu.xdr.ledger import TransactionMeta
    cfg = get_test_config()
    cfg.ENABLE_SOROBAN_DIAGNOSTIC_EVENTS = True
    try:
        _run_diagnostic_scenario(cfg)
    finally:
        COUNTER_CODE = saved_code


def _run_diagnostic_scenario(cfg):
    from stellar_core_tpu.main import Application
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock
    from stellar_core_tpu.xdr.ledger import TransactionMeta
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg) as a:
        a.start()
        master, cid = deploy(a)
        ro, rw = invoke_footprints(cid)
        res = submit_and_close(a, soroban_tx(
            a, master, invoke_op(cid, "increment"), ro, rw))
        assert res.result.result.disc.name == "txSUCCESS", res
        # a contract that logs: the diagnostic lands in sorobanMeta
        res = submit_and_close(a, soroban_tx(
            a, master, invoke_op(cid, "noisy"), ro, rw))
        assert res.result.result.disc.name == "txSUCCESS", res
        row = a.database.query_one(
            "SELECT txmeta FROM txhistory WHERE txid=?",
            (bytes(res.transactionHash),))
        meta = TransactionMeta.from_bytes(bytes(row[0]))
        assert meta.disc == 3
        des = meta.value.sorobanMeta.diagnosticEvents
        assert len(des) == 1
        assert des[0].inSuccessfulContractCall
        body = des[0].event.body.value
        assert bytes(body.topics[0].value) == b"log"
        assert bytes(body.topics[1].value) == b"hello-diag"
        # a FAILED invocation still surfaces its diagnostics, marked
        # inSuccessfulContractCall=false (the reference's primary use)
        res = submit_and_close(a, soroban_tx(
            a, master, invoke_op(cid, "noisy_boom"), ro, rw))
        assert res.result.result.disc.name == "txFAILED"
        row = a.database.query_one(
            "SELECT txmeta FROM txhistory WHERE txid=?",
            (bytes(res.transactionHash),))
        meta = TransactionMeta.from_bytes(bytes(row[0]))
        assert meta.disc == 3
        des = meta.value.sorobanMeta.diagnosticEvents
        assert len(des) == 1
        assert not des[0].inSuccessfulContractCall
        assert meta.value.sorobanMeta.events == []
