"""Milestone M1: standalone manual-close node, end to end.

Reference behavior: RUN_STANDALONE + MANUAL_CLOSE node driven over the
admin command API — submit payments via `tx`, close via `manualclose`,
observe state via `info` (main/CommandHandler.cpp routes :87-125).
"""

import base64

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.herder.tx_queue import AddResult
from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.tx import tx_utils
from stellar_core_tpu.tx.frame import make_frame
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.xdr.ledger_entries import LedgerKey
from stellar_core_tpu.xdr.transaction import (Memo, MemoType, MuxedAccount,
                                              Preconditions,
                                              PreconditionType, Transaction,
                                              TransactionEnvelope,
                                              TransactionV1Envelope, _TxExt)
from stellar_core_tpu.xdr.types import EnvelopeType, PublicKey

from txtest_utils import (op_create_account, op_payment, sign_frame)


class AppAccount:
    """Envelope builder bound to an Application's network id."""

    def __init__(self, app, key: SecretKey, seq: int = 0):
        self.app = app
        self.key = key
        self.seq = seq

    @property
    def account_id(self) -> PublicKey:
        return PublicKey.ed25519(self.key.public_key().raw)

    @property
    def muxed(self) -> MuxedAccount:
        return MuxedAccount.from_ed25519(self.key.public_key().raw)

    def sync_seq(self) -> None:
        acc = app_account_entry(self.app, self.account_id)
        assert acc is not None
        self.seq = acc.seqNum

    def tx(self, ops, fee=None, seq=None):
        if seq is None:
            self.seq += 1
            seq = self.seq
        if fee is None:
            fee = 100 * max(1, len(ops))
        t = Transaction(
            sourceAccount=self.muxed, fee=fee, seqNum=seq,
            cond=Preconditions(PreconditionType.PRECOND_NONE),
            memo=Memo(MemoType.MEMO_NONE), operations=list(ops),
            ext=_TxExt(0))
        env = TransactionEnvelope(
            EnvelopeType.ENVELOPE_TYPE_TX,
            TransactionV1Envelope(tx=t, signatures=[]))
        frame = make_frame(env, self.app.config.network_id())
        sign_frame(frame, self.key)
        return frame


def app_account_entry(app, account_id: PublicKey):
    with LedgerTxn(app.ledger_manager.root) as ltx:
        le = ltx.load_without_record(LedgerKey.account(account_id))
        return le.data.value if le else None


def master_account(app) -> AppAccount:
    key = SecretKey.from_seed(app.config.network_id())
    acct = AppAccount(app, key)
    acct.sync_seq()
    return acct


@pytest.fixture
def app():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    cfg = get_test_config()
    with Application.create(clock, cfg) as a:
        a.start()
        yield a


def submit(app, frame) -> dict:
    blob = base64.b64encode(frame.envelope.to_bytes()).decode()
    return app.command_handler.handle("tx", {"blob": blob})


def test_genesis_info(app):
    info = app.info()
    assert info["ledger"]["num"] == 1
    assert info["state"] == "Synced!"
    assert info["ledger"]["version"] == app.config.LEDGER_PROTOCOL_VERSION
    # genesis master holds all lumens
    master = master_account(app)
    acc = app_account_entry(app, master.account_id)
    assert acc.balance == 10**18


def test_submit_and_manual_close(app):
    master = master_account(app)
    dest = AppAccount(app, SecretKey.from_seed(b"\x07" * 32))

    r = submit(app, master.tx(
        [op_create_account(dest.account_id, 10**11)]))
    assert r["status"] == "PENDING"
    assert app.herder.tx_queue.size_txs() == 1

    app.command_handler.handle("manualclose")
    assert app.ledger_manager.get_last_closed_ledger_num() == 2
    assert app.herder.tx_queue.size_txs() == 0
    acc = app_account_entry(app, dest.account_id)
    assert acc is not None and acc.balance == 10**11

    # follow-up payment in the next ledger
    dest.sync_seq()
    r = submit(app, dest.tx([op_payment(master.muxed, 10**7)]))
    assert r["status"] == "PENDING"
    app.manual_close()
    assert app.ledger_manager.get_last_closed_ledger_num() == 3
    acc = app_account_entry(app, dest.account_id)
    assert acc.balance == 10**11 - 10**7 - 100  # amount + fee


def test_duplicate_and_bad_submissions(app):
    master = master_account(app)
    dest = AppAccount(app, SecretKey.from_seed(b"\x08" * 32))
    frame = master.tx([op_create_account(dest.account_id, 10**11)])
    assert submit(app, frame)["status"] == "PENDING"
    assert submit(app, frame)["status"] == "DUPLICATE"
    # bad seqnum (too far ahead)
    bad = master.tx([op_payment(master.muxed, 1)], seq=master.seq + 100)
    assert submit(app, bad)["status"] == "ERROR"
    # unparsable blob
    r = app.command_handler.handle("tx", {"blob": "!!!notb64!!!"})
    assert "exception" in r
    # wrong-network signature: sign against a different passphrase
    other = master.tx([op_payment(master.muxed, 1)])
    other.signatures[0].signature = b"\x00" * 64
    other.envelope.value.signatures = other.signatures
    assert submit(app, other)["status"] == "ERROR"


def test_chained_txs_one_ledger(app):
    """Several txs from one account in a single ledger apply in seqnum
    order (reference: getTxsInApplyOrder per-account ordering)."""
    master = master_account(app)
    dests = [AppAccount(app, SecretKey.from_seed(bytes([i]) * 32))
             for i in range(1, 6)]
    for d in dests:
        assert submit(app, master.tx(
            [op_create_account(d.account_id, 10**10)]))["status"] == "PENDING"
    app.manual_close()
    for d in dests:
        acc = app_account_entry(app, d.account_id)
        assert acc is not None and acc.balance == 10**10


def test_upgrades_via_admin_api(app):
    r = app.command_handler.handle(
        "upgrades", {"mode": "set", "upgradetime": "0", "basefee": "250",
                     "maxtxsetsize": "500"})
    assert r["status"] == "ok"
    app.manual_close()
    hdr = app.ledger_manager.get_last_closed_ledger_header()
    assert hdr.baseFee == 250
    assert hdr.maxTxSetSize == 500
    # upgrades only vote once the parameters say so; clearing stops them
    r = app.command_handler.handle("upgrades", {"mode": "clear"})
    app.manual_close()
    hdr = app.ledger_manager.get_last_closed_ledger_header()
    assert hdr.baseFee == 250  # sticky after upgrade


def test_metrics_and_ll_routes(app):
    out = app.command_handler.handle("metrics")
    assert "metrics" in out
    out = app.command_handler.handle("ll", {"level": "error"})
    assert out["status"] == "ok"
    out = app.command_handler.handle("nope")
    assert "exception" in out


def test_restart_from_db(tmp_path):
    """LCL + accounts survive restart via loadLastKnownLedger
    (reference: §3.4)."""
    dbpath = str(tmp_path / "node.db")
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    cfg = get_test_config()
    cfg.DATABASE = f"sqlite3://{dbpath}"
    cfg.BUCKET_DIR_PATH = str(tmp_path / "buckets")
    dest_key = SecretKey.from_seed(b"\x11" * 32)
    with Application.create(clock, cfg) as app1:
        app1.start()
        master = master_account(app1)
        dest = AppAccount(app1, dest_key)
        assert submit(app1, master.tx(
            [op_create_account(dest.account_id, 10**11)]))["status"] == \
            "PENDING"
        app1.manual_close()
        lcl_hash = app1.ledger_manager.get_last_closed_ledger_hash()
        assert app1.ledger_manager.get_last_closed_ledger_num() == 2

    cfg2 = get_test_config()
    cfg2.NETWORK_PASSPHRASE = cfg.NETWORK_PASSPHRASE
    cfg2.DATABASE = f"sqlite3://{dbpath}"
    cfg2.BUCKET_DIR_PATH = str(tmp_path / "buckets")
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg2,
                            new_db=False) as app2:
        app2.start()
        assert app2.ledger_manager.get_last_closed_ledger_num() == 2
        assert app2.ledger_manager.get_last_closed_ledger_hash() == lcl_hash
        acc = app_account_entry(
            app2, PublicKey.ed25519(dest_key.public_key().raw))
        assert acc is not None and acc.balance == 10**11
