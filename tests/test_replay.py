"""Whole-node deterministic record/replay (ISSUE 18): round-trip of
the tier-1 4-node seeded chaos scenario (byte-identical honest header
chains, controller decision logs, and zero-diff flight-recorder traces
across two replays), crash-tolerant log format (torn tail detected and
skipped loudly), divergence injection (one flipped recorded frame byte
produces a first-divergence finding with its evidence chain), and the
config-gated record* admin routes."""

import copy
import os
import sys

import pytest

from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.replay import log as rlog
from stellar_core_tpu.replay.recorder import (config_from_snapshot,
                                              config_snapshot)
from stellar_core_tpu.replay.replayer import (first_divergence,
                                              replay_log)
from stellar_core_tpu.replay.scenario import run_recorded_scenario
from stellar_core_tpu.util.timer import ClockMode, VirtualClock

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

import replay_report                                       # noqa: E402


@pytest.fixture(scope="module")
def scenario():
    """One live recorded run shared by the round-trip tests."""
    return run_recorded_scenario(seed=7, target=8)


# ------------------------------------------------------------ round trip --

def test_round_trip_matches_live_run(scenario):
    """Every honest survivor's replay reproduces the live run
    byte-for-byte: header chain, controller decision log, final LCL."""
    res = scenario
    survivors = [h for h in res.logs if h not in res.crashed]
    assert len(survivors) == 3 and len(res.crashed) == 1
    for hx in survivors:
        r = replay_log(res.logs[hx])
        assert not r.crashed
        assert r.end_matches is True
        assert (r.lcl_seq, r.lcl_hash) == res.lcl[hx]
        assert r.header_chain == res.chains[hx]
        assert r.decisions == res.decisions[hx]
        assert r.frames_fed > 0


def test_replay_twice_zero_trace_diff(scenario):
    """Two replays of the same log are indistinguishable: identical
    chains and a zero-diff normalized flight-recorder trace."""
    res = scenario
    hx = [h for h in res.logs if h not in res.crashed][0]
    r1 = replay_log(res.logs[hx], trace=True)
    r2 = replay_log(res.logs[hx], trace=True)
    assert r1.header_chain == r2.header_chain
    assert r1.decisions_json() == r2.decisions_json()
    assert len(r1.trace) > 100
    assert first_divergence(r1.trace, r2.trace) is None
    # the replay trace even matches the LIVE node's trace — the replay
    # re-creates the crank phase machine, not an approximation of it
    assert first_divergence(res.traces[hx], r1.trace) is None


def test_crashed_node_log_replays_to_same_crash(scenario):
    """The killed node's log has no END marker; its replay runs up to
    the recorded stream's end and dies at the same chaos point."""
    res = scenario
    hx = res.crashed[0]
    ilog = res.logs[hx]
    assert ilog.end_record() is None
    r = replay_log(ilog)
    assert r.crashed
    assert r.crash_point == "ledger.close.crash.applyTx"
    assert r.end_matches is None
    assert r.lcl_seq >= 2


# ------------------------------------------------------------ divergence --

def test_single_byte_frame_mutation_is_caught(scenario):
    """Flip one byte of one recorded wire frame: the divergence diff
    pinpoints the first trace event where the runs fork and carries
    the evidence chain leading up to it."""
    res = scenario
    hx = [h for h in res.logs if h not in res.crashed][0]
    clean = replay_log(res.logs[hx], trace=True)
    mutated_log = copy.deepcopy(res.logs[hx])
    frames = [r for r in mutated_log.records
              if r.rtype == rlog.RT_FRAME and len(r.data) > 200]
    victim = frames[len(frames) // 2]
    raw = bytearray(victim.data)
    # the frame tail is <signature(64)><hmac(32)>; the hmac bytes are
    # deliberately ignored on replay (verdicts ride MACFAIL records),
    # so flip inside the envelope signature: still parses, no longer
    # verifies — the node now discards an envelope it accepted live
    raw[-40] ^= 0x01
    victim.data = bytes(raw)
    mutated = replay_log(mutated_log, trace=True)
    div = first_divergence(clean.trace, mutated.trace)
    assert div is not None
    assert div["chain"], "finding must carry its evidence chain"
    finding = replay_report.divergence_finding(div, "clean", "mutated")
    assert finding["pass"] == "replay-divergence"
    assert finding["chain"]
    for key in ("key", "path", "line", "message", "hint"):
        assert key in finding


def test_replay_report_cli(tmp_path, scenario):
    """scripts/replay_report.py aligns two trace dumps and emits the
    finding in the analyzer's findings format (or reports zero-diff)."""
    res = scenario
    hx = [h for h in res.logs if h not in res.crashed][0]
    r1 = replay_log(res.logs[hx], trace=True)
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(replay_report.dump_trace(r1.trace))
    b.write_text(replay_report.dump_trace(r1.trace))
    out = replay_report.run([str(a), str(b)])
    assert out["divergence"] is None and out["findings"] == []
    t2 = list(r1.trace)
    t2[5] = (t2[5][0], t2[5][1], t2[5][2] + "x", t2[5][3])
    b.write_text(replay_report.dump_trace(t2))
    out = replay_report.run([str(a), str(b)])
    assert out["divergence"]["index"] == 5
    assert out["findings"][0]["pass"] == "replay-divergence"


# ------------------------------------------------------------ log format --

def _tiny_log() -> tuple:
    """(bytes, record start offsets) for a 4-record in-memory log."""
    w = rlog.LogWriter()
    offsets = []
    w.write_json(rlog.RT_HEADER, {"version": 1, "node": "ab",
                                  "config": {}, "extras": {}})
    import json
    end = json.dumps({"ts": 1.0, "reason": "ok", "lcl_seq": 1,
                      "lcl_hash": ""}, sort_keys=True).encode()
    for rtype, payload in (
            (rlog.RT_TICK, rlog.encode_tick_payload(0.0,
                                                    rlog.TICK_START)),
            (rlog.RT_FRAME, rlog.encode_frame_payload(0.0, 0, b"x" * 40)),
            (rlog.RT_FRAME, rlog.encode_frame_payload(1.0, 0, b"y" * 40)),
            (rlog.RT_END, end)):
        offsets.append(w.bytes)
        w.write(rtype, payload)
    return w.to_bytes(), offsets


def test_torn_tail_detected_and_skipped():
    """A kill -9 mid-record leaves a torn tail: every truncation point
    inside the final record parses to the preceding records plus a
    loud tear count — never an exception, never silent loss."""
    data, offsets = _tiny_log()
    full = rlog.InputLog.from_bytes(data)
    assert full.torn_tail == 0 and len(full.records) == 4
    last_start = offsets[-1]
    # truncate at every byte inside the END record
    for cut in range(last_start + 1, len(data)):
        ilog = rlog.InputLog.from_bytes(data[:cut])
        assert ilog.torn_tail == 1
        assert ilog.torn_bytes == cut - last_start
        assert len(ilog.records) == 3
        assert ilog.end_record() is None


def test_mid_file_corruption_stops_loudly():
    data, offsets = _tiny_log()
    data = bytearray(data)
    # flip a payload byte of the FIRST frame record: CRC mismatch —
    # nothing after that point is trustworthy
    data[offsets[1] + 9 + 15] ^= 0xFF
    ilog = rlog.InputLog.from_bytes(bytes(data))
    assert ilog.torn_tail == 1
    assert all(r.rtype != rlog.RT_END for r in ilog.records)
    assert len(ilog.records) == 1          # header consumed, TICK kept


def test_bad_magic_rejected():
    with pytest.raises(ValueError, match="magic"):
        rlog.InputLog.from_bytes(b"NOTALOG!" + b"\x00" * 16)


def test_config_snapshot_round_trip():
    cfg = get_test_config()
    cfg.ALLOW_INPUT_RECORDING = True
    doc = config_snapshot(cfg)
    back = config_from_snapshot(doc)
    assert back.ALLOW_INPUT_RECORDING is True
    assert back.QUORUM_SET.threshold == cfg.QUORUM_SET.threshold
    assert back.QUORUM_SET.validators == cfg.QUORUM_SET.validators


# ----------------------------------------------------------- admin routes --

def _single_node():
    cfg = get_test_config()
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    return app


def test_record_routes_gated_behind_config():
    app = _single_node()
    try:
        app.config.ALLOW_INPUT_RECORDING = False
        for cmd in ("recordstart", "recordstop", "recorddump"):
            out = app.command_handler.handle(cmd)
            assert "exception" in out, cmd
            assert "ALLOW_INPUT_RECORDING" in out["exception"]
    finally:
        app.shutdown()


def test_record_routes_lifecycle(tmp_path):
    app = _single_node()
    try:
        h = app.command_handler
        out = h.handle("recordstart")
        assert out.get("status") == "recording"
        # double-start refused
        assert "exception" in h.handle("recordstart")
        app.crank(False)
        app.crank(True)
        stats = h.handle("recordstop")
        assert stats["records"] > 0 and stats["ticks"] > 0
        assert "exception" in h.handle("recordstop")   # already stopped
        path = str(tmp_path / "node.rlog")
        out = h.handle("recorddump", {"path": path})
        assert out["bytes"] > 0
        ilog = rlog.InputLog.load(path)
        assert ilog.node == app.config.node_id().hex()
        assert ilog.end_record() is not None
        # create-only: a second dump to the same path must refuse
        assert "exception" in h.handle("recorddump", {"path": path})
    finally:
        app.shutdown()
