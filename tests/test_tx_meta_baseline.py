"""Golden tx-meta baseline testing.

Reference: the `--check-test-tx-meta` CI mechanism (test/test.h:23-28,
baselines checked in under test-tx-meta-baseline-current/): the XDR
TransactionMeta produced by applying a fixed scenario is hashed and
compared against a checked-in baseline, so any unintended change to apply
semantics (fees, entry changes, meta encoding) is caught as a diff.

Regenerate after an *intended* semantic change with:
    UPDATE_TX_META_BASELINE=1 python -m pytest tests/test_tx_meta_baseline.py
"""

import json
import os

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.util.timer import ClockMode, VirtualClock

import test_standalone_app as m1
from txtest_utils import (make_asset, native, op_change_trust,
                          op_create_account, op_manage_data, op_payment,
                          op_set_options)

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "testdata",
                             "tx_meta_baselines.json")
UPDATE = os.environ.get("UPDATE_TX_META_BASELINE") == "1"


def _collect_app():
    """App whose meta stream is captured in-memory."""
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    cfg = get_test_config()
    app = Application.create(clock, cfg)
    metas = []
    app.ledger_manager.meta_stream = metas.append
    app.start()
    return app, metas


def _meta_hashes(metas):
    """Per-tx sha256 of the XDR TransactionMeta, in apply order."""
    out = []
    for meta in metas:
        v = meta.value
        for trm in v.txProcessing:
            out.append(sha256(trm.txApplyProcessing.to_bytes()).hex())
    return out


def _submit_ok(app, frame):
    r = m1.submit(app, frame)
    assert r.get("status") == "PENDING", r
    return r


def _check(name: str, hashes):
    assert hashes, "scenario produced no tx meta"
    baselines = {}
    if os.path.exists(BASELINE_PATH):
        with open(BASELINE_PATH) as f:
            baselines = json.load(f)
    if UPDATE:
        baselines[name] = hashes
        os.makedirs(os.path.dirname(BASELINE_PATH), exist_ok=True)
        with open(BASELINE_PATH, "w") as f:
            json.dump(baselines, f, indent=1, sort_keys=True)
        pytest.skip("baseline regenerated")
    assert name in baselines, (
        f"no baseline for {name}; run with UPDATE_TX_META_BASELINE=1")
    assert hashes == baselines[name], (
        f"tx meta for {name} diverged from the checked-in baseline; if the "
        "change is intended, regenerate with UPDATE_TX_META_BASELINE=1")


def test_classic_scenario_meta_is_stable():
    app, metas = _collect_app()
    try:
        master = m1.master_account(app)
        a = m1.AppAccount(app, SecretKey.from_seed(sha256(b"meta-a")))
        b = m1.AppAccount(app, SecretKey.from_seed(sha256(b"meta-b")))
        _submit_ok(app, master.tx([
            op_create_account(a.account_id, 500_0000000),
            op_create_account(b.account_id, 500_0000000)]))
        app.manual_close()
        a.sync_seq(); b.sync_seq()
        usd = make_asset(b"USD", master.account_id)
        _submit_ok(app, a.tx([op_change_trust(usd, 2**62),
                              op_manage_data(b"k1", b"v1"),
                              op_set_options(homeDomain=b"example.com")]))
        _submit_ok(app, b.tx([op_payment(a.muxed, 1234567)]))
        app.manual_close()
        _submit_ok(app, master.tx([op_payment(a.muxed, 42, asset=usd)]))
        app.manual_close()
        _check("classic-v1", _meta_hashes(metas))
    finally:
        app.shutdown()


@pytest.mark.parametrize("build,golden", [
    ("scvm", "soroban-upload-v1"),
    ("wasm", "soroban-upload-wasm-v1"),
])
def test_soroban_scenario_meta_is_stable(build, golden):
    import test_soroban as sb
    # pin the contract build: sb.COUNTER_CODE is swapped by test_soroban's
    # parametrized fixture, so it must be set explicitly here
    sb.COUNTER_CODE = sb.CODE_BUILDS[build]
    app, metas = _collect_app()
    try:
        master = m1.master_account(app)
        from stellar_core_tpu.xdr.ledger_entries import LedgerKey
        code_key = LedgerKey.contract_code(sb.wasm_hash())
        frame = sb.soroban_tx(app, master, sb.upload_op(), [], [code_key])
        r = m1.submit(app, frame)
        assert r["status"] == "PENDING", r
        app.manual_close()
        _check(golden, _meta_hashes(metas))
    finally:
        app.shutdown()


def test_dex_scenario_meta_is_stable():
    """Crossing offers + a fee-bump exercise OfferExchange rounding and
    the fee-bump meta shape; pins their XDR meta bytes."""
    from txtest_utils import (op_manage_sell_offer, op_manage_buy_offer)
    from stellar_core_tpu.xdr.ledger_entries import Price
    app, metas = _collect_app()
    try:
        master = m1.master_account(app)
        a = m1.AppAccount(app, SecretKey.from_seed(sha256(b"dex-a")))
        b = m1.AppAccount(app, SecretKey.from_seed(sha256(b"dex-b")))
        _submit_ok(app, master.tx([
            op_create_account(a.account_id, 500_0000000),
            op_create_account(b.account_id, 500_0000000)]))
        app.manual_close()
        a.sync_seq(); b.sync_seq()
        usd = make_asset(b"USD", master.account_id)
        _submit_ok(app, a.tx([op_change_trust(usd, 2**62)]))
        _submit_ok(app, b.tx([op_change_trust(usd, 2**62)]))
        app.manual_close()
        _submit_ok(app, master.tx([op_payment(b.muxed, 1_000_0000, usd)]))
        app.manual_close()
        # a sells native for USD; b's buy crosses it
        _submit_ok(app, a.tx([op_manage_sell_offer(
            native(), usd, 100_0000, Price(n=1, d=2), 0)]))
        app.manual_close()
        _submit_ok(app, b.tx([op_manage_buy_offer(
            usd, native(), 50_0000, Price(n=2, d=1), 0)]))
        app.manual_close()
        # the crossing really happened
        row = app.database.query_one("SELECT COUNT(*) FROM offers", ())
        assert row[0] <= 1
        _check("dex-v1", _meta_hashes(metas))
    finally:
        app.shutdown()
