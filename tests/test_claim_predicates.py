"""Claimable-balance claim predicates (reference: ClaimableBalanceTests
predicate cases + ClaimClaimableBalanceOpFrame evaluatePredicate /
CreateClaimableBalanceOpFrame's relative→absolute rebase and the
4-deep validation limit)."""

import pytest

from stellar_core_tpu.tx.operations.claimable_balance_ops import (
    MAX_PREDICATE_DEPTH, rebase_predicate, test_predicate as eval_pred,
    validate_predicate)
from stellar_core_tpu.xdr.ledger_entries import (ClaimPredicate,
                                                 ClaimPredicateType,
                                                 Claimant, ClaimantType,
                                                 ClaimantV0)
from stellar_core_tpu.xdr.transaction import (ClaimClaimableBalanceOp,
                                              CreateClaimableBalanceOp,
                                              OperationType)

from txtest_utils import TestAccount, TestLedger, _op, native

XLM = 10_000_000
PT = ClaimPredicateType


@pytest.fixture
def ledger():
    return TestLedger()


@pytest.fixture
def root(ledger):
    return ledger.root_account


def uncond():
    return ClaimPredicate(PT.CLAIM_PREDICATE_UNCONDITIONAL)


def before_abs(t):
    return ClaimPredicate(PT.CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME, t)


def before_rel(t):
    return ClaimPredicate(PT.CLAIM_PREDICATE_BEFORE_RELATIVE_TIME, t)


def p_not(p):
    return ClaimPredicate(PT.CLAIM_PREDICATE_NOT, p)


def p_and(a, b):
    return ClaimPredicate(PT.CLAIM_PREDICATE_AND, [a, b])


def p_or(a, b):
    return ClaimPredicate(PT.CLAIM_PREDICATE_OR, [a, b])


class TestPredicateMachinery:
    def test_evaluation_matrix(self):
        t = 1000
        assert eval_pred(uncond(), t)
        assert eval_pred(before_abs(1001), t)
        assert not eval_pred(before_abs(1000), t)       # strict <
        assert eval_pred(p_not(before_abs(1000)), t)
        assert eval_pred(p_and(uncond(), before_abs(2000)), t)
        assert not eval_pred(p_and(uncond(), before_abs(500)), t)
        assert eval_pred(p_or(before_abs(500), before_abs(2000)), t)
        assert not eval_pred(p_or(before_abs(500), before_abs(600)), t)

    def test_relative_rebased_to_absolute_at_create(self):
        close = 5_000
        rb = rebase_predicate(before_rel(100), close)
        assert rb.disc == PT.CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME
        assert rb.value == 5_100
        # nested rebase keeps structure
        rb2 = rebase_predicate(p_and(before_rel(10), uncond()), close)
        assert rb2.value[0].value == 5_010
        assert rb2.value[1].disc == PT.CLAIM_PREDICATE_UNCONDITIONAL

    def test_depth_limit(self):
        p = uncond()
        for _ in range(MAX_PREDICATE_DEPTH - 1):
            p = p_not(p)
        assert validate_predicate(p)           # exactly at the limit
        assert not validate_predicate(p_not(p))


def _create(ledger, alice, bob, predicate):
    op = _op(OperationType.CREATE_CLAIMABLE_BALANCE,
             CreateClaimableBalanceOp(
                 asset=native(), amount=5 * XLM,
                 claimants=[Claimant(
                     ClaimantType.CLAIMANT_TYPE_V0,
                     ClaimantV0(destination=bob.account_id,
                                predicate=predicate))]))
    frame = alice.tx([op])
    ok = ledger.apply_tx(frame)
    bid = frame.result.result.value[0].value.value.value if ok else None
    return ok, bid, frame


def _claim(ledger, who, bid):
    return who.apply([_op(OperationType.CLAIM_CLAIMABLE_BALANCE,
                          ClaimClaimableBalanceOp(balanceID=bid))])


class TestPredicatesOnLedger:
    def _accounts(self, ledger, root):
        alice = TestAccount.fresh(ledger)
        bob = TestAccount.fresh(ledger)
        root.create(alice, 1_000 * XLM)
        root.create(bob, 1_000 * XLM)
        alice.sync_seq()
        bob.sync_seq()
        return alice, bob

    def test_expired_deadline_cannot_claim(self, ledger, root):
        alice, bob = self._accounts(ledger, root)
        now = ledger.header().scpValue.closeTime
        ok, bid, _ = _create(ledger, alice, bob, before_abs(now + 100))
        assert ok
        # deadline passes
        ledger.root._header.scpValue.closeTime = now + 200
        assert not _claim(ledger, bob, bid)
        # a NOT-before predicate becomes claimable only after the time
        ok, bid2, _ = _create(ledger, alice, bob,
                              p_not(before_abs(now + 300)))
        assert ok
        assert not _claim(ledger, bob, bid2)   # now+200 < now+300
        ledger.root._header.scpValue.closeTime = now + 400
        assert _claim(ledger, bob, bid2)

    def test_relative_predicate_claim_window(self, ledger, root):
        """BEFORE_RELATIVE_TIME is rebased against the CREATE ledger's
        close time; the stored entry carries the absolute deadline."""
        alice, bob = self._accounts(ledger, root)
        now = ledger.header().scpValue.closeTime
        ok, bid, _ = _create(ledger, alice, bob, before_rel(50))
        assert ok
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
        from stellar_core_tpu.xdr.ledger_entries import LedgerKey
        with LedgerTxn(ledger.root) as ltx:
            le = ltx.load_without_record(LedgerKey.claimable_balance(bid))
            stored = le.data.value.claimants[0].value.predicate
            assert stored.disc == PT.CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME
            assert stored.value == now + 50
        ledger.root._header.scpValue.closeTime = now + 49
        assert _claim(ledger, bob, bid)

    def test_too_deep_predicate_rejected_at_create(self, ledger, root):
        alice, bob = self._accounts(ledger, root)
        p = uncond()
        for _ in range(MAX_PREDICATE_DEPTH):
            p = p_not(p)                       # depth limit + 1
        ok, _, frame = _create(ledger, alice, bob, p)
        assert not ok
