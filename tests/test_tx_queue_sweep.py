"""TransactionQueue behavior sweep.

Each test names the reference behavior it mirrors from
src/herder/test/TransactionQueueTests.cpp (ageing, ban generations,
replace-by-fee, evictions, applied-removal) — VERDICT round-1 weak #6's
highest-risk suite."""

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.herder import AddResult, TransactionQueue
from stellar_core_tpu.herder.tx_queue import FEE_MULTIPLIER

from test_ledger_close import (close_with, make_manager, make_tx,
                               master_key, master_seq,
                               op_manage_data_stub, op_create_account,
                               xpk)


@pytest.fixture
def lm():
    return make_manager(invariants=False)


def fund(lm, n=1, balance=10**10):
    """n fresh funded accounts."""
    mk = master_key()
    seq = master_seq(lm)
    sks = [SecretKey.pseudo_random_for_testing(5000 + i) for i in range(n)]
    txs = [make_tx(lm, mk, seq + 1,
                   [op_create_account(xpk(sk), balance) for sk in sks])]
    close_with(lm, txs)
    created = lm.get_last_closed_ledger_num()
    return [(sk, created << 32) for sk in sks]


# ------------------------------------------------------------------ ageing --
def test_age_increments_per_shift_and_bans_at_pending_depth(lm):
    """TransactionQueueTests 'TransactionQueue base' ageing sweep."""
    mk = master_key()
    q = TransactionQueue(pending_depth=3)
    t = make_tx(lm, mk, master_seq(lm) + 1, [op_manage_data_stub(0)])
    assert q.try_add(t, lm.root, 100) == AddResult.ADD_STATUS_PENDING
    q.shift()
    q.shift()
    assert q.size_txs() == 1              # age 2 < 3: still queued
    q.shift()
    assert q.size_txs() == 0              # age 3 == depth: banned out
    assert q.is_banned(t.full_hash())


def test_ban_lasts_exactly_ban_depth_shifts(lm):
    """Ban-generation rotation boundary (TransactionQueueTests 'ban')."""
    mk = master_key()
    q = TransactionQueue(pending_depth=1, ban_depth=4)
    t = make_tx(lm, mk, master_seq(lm) + 1, [op_manage_data_stub(0)])
    q.try_add(t, lm.root, 100)
    q.shift()                              # ages out + bans (gen 0)
    assert q.is_banned(t.full_hash())
    for _ in range(3):
        q.shift()
        assert q.is_banned(t.full_hash())  # gens 1..3 still hold it
    q.shift()
    assert not q.is_banned(t.full_hash())  # rotated out after depth


def test_banned_resubmission_try_again_later_then_accepted(lm):
    mk = master_key()
    q = TransactionQueue(pending_depth=1, ban_depth=2)
    t = make_tx(lm, mk, master_seq(lm) + 1, [op_manage_data_stub(0)])
    q.try_add(t, lm.root, 100)
    q.shift()
    assert q.try_add(t, lm.root, 100) == \
        AddResult.ADD_STATUS_TRY_AGAIN_LATER
    q.shift()
    q.shift()
    assert q.try_add(t, lm.root, 100) == AddResult.ADD_STATUS_PENDING


def test_explicit_ban_drops_and_bans(lm):
    mk = master_key()
    q = TransactionQueue()
    t = make_tx(lm, mk, master_seq(lm) + 1, [op_manage_data_stub(0)])
    q.try_add(t, lm.root, 100)
    q.ban([t])
    assert q.size_txs() == 0
    assert q.is_banned(t.full_hash())
    assert q.try_add(t, lm.root, 100) == \
        AddResult.ADD_STATUS_TRY_AGAIN_LATER


# --------------------------------------------------------- remove_applied --
def test_remove_applied_drops_without_ban(lm):
    """TransactionQueueTests 'TransactionQueue removeApplied'."""
    mk = master_key()
    q = TransactionQueue()
    t = make_tx(lm, mk, master_seq(lm) + 1, [op_manage_data_stub(0)])
    q.try_add(t, lm.root, 100)
    q.remove_applied([t])
    assert q.size_txs() == 0
    assert not q.is_banned(t.full_hash())


def test_remove_applied_drops_stale_lower_seqnums(lm):
    """An applied tx invalidates queued txs at <= its seqnum for the
    same account (removeApplied's seqnum sweep)."""
    mk = master_key()
    seq = master_seq(lm)
    q = TransactionQueue()
    t1 = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)])
    t2 = make_tx(lm, mk, seq + 2, [op_manage_data_stub(1)])
    t3 = make_tx(lm, mk, seq + 3, [op_manage_data_stub(2)])
    for t in (t1, t2, t3):
        assert q.try_add(t, lm.root, 100) == AddResult.ADD_STATUS_PENDING
    # a DIFFERENT tx at seq+2 applied on-ledger
    other = make_tx(lm, mk, seq + 2, [op_manage_data_stub(9)])
    q.remove_applied([other])
    remaining = {t.full_hash() for t in q.get_transactions()}
    assert remaining == {t3.full_hash()}   # t1, t2 stale; t3 survives
    assert not q.is_banned(t1.full_hash())


def test_remove_applied_other_account_untouched(lm):
    mk = master_key()
    (sk, base), = fund(lm, 1)
    seq = master_seq(lm)
    q = TransactionQueue()
    t_master = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)])
    t_other = make_tx(lm, sk, base + 1, [op_manage_data_stub(1)])
    q.try_add(t_master, lm.root, 100)
    q.try_add(t_other, lm.root, 100)
    q.remove_applied([t_master])
    assert [t.full_hash() for t in q.get_transactions()] == \
        [t_other.full_hash()]


# -------------------------------------------------------- replace-by-fee --
def test_rbf_requires_fee_multiplier(lm):
    """TransactionQueueTests 'replace by fee': a same-seqnum tx must bid
    >= FEE_MULTIPLIER x the old rate."""
    mk = master_key()
    seq = master_seq(lm)
    q = TransactionQueue()
    old = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)], fee=200)
    assert q.try_add(old, lm.root, 100) == AddResult.ADD_STATUS_PENDING
    low = make_tx(lm, mk, seq + 1, [op_manage_data_stub(1)],
                  fee=FEE_MULTIPLIER * 200 - 1)
    assert q.try_add(low, lm.root, 100) == AddResult.ADD_STATUS_ERROR
    exact = make_tx(lm, mk, seq + 1, [op_manage_data_stub(2)],
                    fee=FEE_MULTIPLIER * 200)
    assert q.try_add(exact, lm.root, 100) == AddResult.ADD_STATUS_PENDING
    assert q.size_txs() == 1
    assert q.get_transactions()[0] is exact


def test_rbf_bans_the_replaced_tx(lm):
    mk = master_key()
    seq = master_seq(lm)
    q = TransactionQueue()
    old = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)], fee=100)
    q.try_add(old, lm.root, 100)
    new = make_tx(lm, mk, seq + 1, [op_manage_data_stub(1)],
                  fee=FEE_MULTIPLIER * 100)
    assert q.try_add(new, lm.root, 100) == AddResult.ADD_STATUS_PENDING
    assert q.is_banned(old.full_hash())
    assert q.try_add(old, lm.root, 100) == \
        AddResult.ADD_STATUS_TRY_AGAIN_LATER


def test_rbf_middle_of_chain_keeps_chain_valid(lm):
    mk = master_key()
    seq = master_seq(lm)
    q = TransactionQueue()
    t1 = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)], fee=100)
    t2 = make_tx(lm, mk, seq + 2, [op_manage_data_stub(1)], fee=100)
    t3 = make_tx(lm, mk, seq + 3, [op_manage_data_stub(2)], fee=100)
    for t in (t1, t2, t3):
        assert q.try_add(t, lm.root, 100) == AddResult.ADD_STATUS_PENDING
    r2 = make_tx(lm, mk, seq + 2, [op_manage_data_stub(5)],
                 fee=FEE_MULTIPLIER * 100)
    assert q.try_add(r2, lm.root, 100) == AddResult.ADD_STATUS_PENDING
    seqs = sorted(t.seq_num for t in q.get_transactions())
    assert seqs == [seq + 1, seq + 2, seq + 3]
    assert q.get_tx(r2.full_hash()) is not None
    assert q.get_tx(t2.full_hash()) is None


def test_rbf_multiplier_uses_fee_rate_not_flat_fee(lm):
    """Rates compare per-op: replacing a 1-op 100-fee tx with a 2-op tx
    needs 2 x 10 x 100 total fee (fee_rate_cmp semantics)."""
    mk = master_key()
    seq = master_seq(lm)
    q = TransactionQueue()
    old = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)], fee=100)
    q.try_add(old, lm.root, 100)
    low2 = make_tx(lm, mk, seq + 1,
                   [op_manage_data_stub(1), op_manage_data_stub(2)],
                   fee=2 * FEE_MULTIPLIER * 100 - 1)
    assert q.try_add(low2, lm.root, 100) == AddResult.ADD_STATUS_ERROR
    ok2 = make_tx(lm, mk, seq + 1,
                  [op_manage_data_stub(3), op_manage_data_stub(4)],
                  fee=2 * FEE_MULTIPLIER * 100)
    assert q.try_add(ok2, lm.root, 100) == AddResult.ADD_STATUS_PENDING


# ------------------------------------------------------------- seq chains --
def test_chained_seqnums_accepted_gap_rejected(lm):
    """Queued chains validate with predecessors' seqnums consumed; a
    gapped seqnum fails checkValid (TransactionQueueTests 'sequence')."""
    mk = master_key()
    seq = master_seq(lm)
    q = TransactionQueue()
    t1 = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)])
    t2 = make_tx(lm, mk, seq + 2, [op_manage_data_stub(1)])
    t4 = make_tx(lm, mk, seq + 4, [op_manage_data_stub(2)])
    assert q.try_add(t1, lm.root, 100) == AddResult.ADD_STATUS_PENDING
    assert q.try_add(t2, lm.root, 100) == AddResult.ADD_STATUS_PENDING
    assert q.try_add(t4, lm.root, 100) == AddResult.ADD_STATUS_ERROR


def test_first_tx_must_match_live_seqnum(lm):
    mk = master_key()
    seq = master_seq(lm)
    q = TransactionQueue()
    stale = make_tx(lm, mk, seq, [op_manage_data_stub(0)])
    assert q.try_add(stale, lm.root, 100) == AddResult.ADD_STATUS_ERROR
    future = make_tx(lm, mk, seq + 2, [op_manage_data_stub(1)])
    assert q.try_add(future, lm.root, 100) == AddResult.ADD_STATUS_ERROR


# -------------------------------------------------------------- eviction --
def test_eviction_needs_strictly_better_rate(lm):
    """TxQueueLimiter: an equal-rate newcomer cannot evict."""
    mk = master_key()
    (sk, base), = fund(lm, 1)
    q = TransactionQueue()
    incumbent = make_tx(lm, mk, master_seq(lm) + 1,
                        [op_manage_data_stub(0)], fee=500)
    assert q.try_add(incumbent, lm.root, 1) == AddResult.ADD_STATUS_PENDING
    equal = make_tx(lm, sk, base + 1, [op_manage_data_stub(1)], fee=500)
    assert q.try_add(equal, lm.root, 1) == \
        AddResult.ADD_STATUS_TRY_AGAIN_LATER
    assert q.size_txs() == 1 and not q.is_banned(incumbent.full_hash())


def test_eviction_frees_multiple_cheap_txs(lm):
    """A high-rate multi-op newcomer evicts as many low-rate txs as
    needed — all of them banned."""
    mk = master_key()
    accounts = fund(lm, 3)
    q = TransactionQueue()
    cheap = []
    for sk, base in accounts:
        t = make_tx(lm, sk, base + 1, [op_manage_data_stub(1)], fee=100)
        assert q.try_add(t, lm.root, 3) == AddResult.ADD_STATUS_PENDING
        cheap.append(t)
    rich = make_tx(lm, mk, master_seq(lm) + 1,
                   [op_manage_data_stub(0), op_manage_data_stub(1)],
                   fee=10000)
    assert q.try_add(rich, lm.root, 3) == AddResult.ADD_STATUS_PENDING
    assert q.size_txs() == 2              # rich + one cheap survivor
    assert sum(q.is_banned(t.full_hash()) for t in cheap) == 2


def test_eviction_size_ops_accounting(lm):
    mk = master_key()
    (sk, base), = fund(lm, 1)
    q = TransactionQueue()
    t2 = make_tx(lm, sk, base + 1,
                 [op_manage_data_stub(0), op_manage_data_stub(1)], fee=200)
    assert q.try_add(t2, lm.root, 2) == AddResult.ADD_STATUS_PENDING
    assert q.size_ops() == 2
    rich = make_tx(lm, mk, master_seq(lm) + 1,
                   [op_manage_data_stub(2)], fee=9000)
    assert q.try_add(rich, lm.root, 2) == AddResult.ADD_STATUS_PENDING
    assert q.size_ops() == 1
    assert q.size_txs() == 1


def test_queue_full_of_better_txs_rejects_newcomer(lm):
    mk = master_key()
    (sk, base), = fund(lm, 1)
    q = TransactionQueue()
    best = make_tx(lm, mk, master_seq(lm) + 1,
                   [op_manage_data_stub(0)], fee=10_000)
    assert q.try_add(best, lm.root, 1) == AddResult.ADD_STATUS_PENDING
    worse = make_tx(lm, sk, base + 1, [op_manage_data_stub(1)], fee=500)
    assert q.try_add(worse, lm.root, 1) == \
        AddResult.ADD_STATUS_TRY_AGAIN_LATER


def test_rbf_does_not_need_extra_capacity(lm):
    """Replacement reuses the replaced tx's capacity: works at a full
    queue without evicting anyone else."""
    mk = master_key()
    seq = master_seq(lm)
    q = TransactionQueue()
    old = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)], fee=100)
    assert q.try_add(old, lm.root, 1) == AddResult.ADD_STATUS_PENDING
    new = make_tx(lm, mk, seq + 1, [op_manage_data_stub(1)],
                  fee=FEE_MULTIPLIER * 100)
    assert q.try_add(new, lm.root, 1) == AddResult.ADD_STATUS_PENDING
    assert q.size_txs() == 1


# --------------------------------------------------------------- queries --
def test_get_tx_and_get_transactions(lm):
    mk = master_key()
    seq = master_seq(lm)
    q = TransactionQueue()
    t1 = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)])
    q.try_add(t1, lm.root, 100)
    assert q.get_tx(t1.full_hash()) is t1
    assert q.get_tx(b"\x00" * 32) is None
    assert [t.full_hash() for t in q.get_transactions()] == \
        [t1.full_hash()]
