"""Adaptive control plane (ops/controller.py, ISSUE 11).

Covers the tentpole contracts: AIMD knob moves are sample-driven and
bounded, the shed ladder ramps from SLO WARN/BREACH and the backlog
surge gate slams before verify dispatch, identical seeded schedules on
the VirtualClock replay byte-identical decision logs, a chaos `hang`
on ops.backend.dispatch mid-tune freezes tuning (breaker interplay)
without wedging the controller, shed frames never reach the batched
verify dispatch (zero crypto.verify.dispatch growth — the ordering
regression), and the `controller` route / clearmetrics epoch-rotate
reset behave like every other PR 10 surface.
"""

import json

import pytest

from stellar_core_tpu.herder.tx_queue import AddResult
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.util import chaos
from stellar_core_tpu.util.chaos import ChaosEngine, FaultSpec
from stellar_core_tpu.util.timer import ClockMode, VirtualClock


def _app(cfg=None):
    cfg = cfg or get_test_config()
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    return app


def _sample(t, close_p99=100.0, queue_wait=1.0, occ=64, flushes=10,
            pending=0, ledger=None, tx_applied=None, breaker=None,
            dispatch=None, close_median=None, verify=True, mesh=None):
    """Hand-built telemetry sample — the controller's whole world is
    the sample dict plus the watchdog state derived from it."""
    s = {
        "t": float(t),
        "ledger": ledger if ledger is not None else int(t),
        "pending_txs": pending,
        "tx_applied": tx_applied if tx_applied is not None else 0,
        "close": {"count": 5, "median_ms": close_median
                  if close_median is not None else close_p99 / 2,
                  "p99_ms": close_p99, "max_ms": close_p99},
        "tx_e2e": {"count": 0},
        "breaker": breaker,
        "breaker_open": 1.0 if breaker == "OPEN" else 0.0,
        "flood": None,
        "dispatch": dispatch,
        "mesh": mesh,
        "host": {"load1": 0.0, "ncpu": 1},
    }
    if verify:
        s["verify"] = {"flushes": flushes, "occupancy_p99": occ,
                       "queue_wait_p99_ms": queue_wait,
                       "queue_pending": pending, "queue_inflight": 0}
    else:
        s["verify"] = None
    return s


def _feed(app, sample):
    """One observed control step: the watchdog judges the sample (as
    it would on a TelemetrySampler append), then the controller ticks
    against it."""
    app.slo.observe(sample)
    app.controller.tick(sample)


# ------------------------------------------------------------- AIMD tune --

def test_aimd_increases_max_batch_when_filling_under_target():
    app = _app()
    try:
        ctl = app.controller
        before = ctl.knobs["max_batch"]
        # batches filling (occ >= 0.8 x max_batch), latency headroom
        _feed(app, _sample(1.0, queue_wait=1.0,
                           occ=int(0.9 * before)))
        assert ctl.knobs["max_batch"] == \
            before + app.config.CONTROLLER_AIMD_INCREASE
        assert any(d["kind"] == "tune" and d["field"] == "max_batch"
                   for d in ctl.decisions)
    finally:
        app.shutdown()


def test_aimd_backs_off_deadline_on_queue_wait():
    app = _app()
    try:
        ctl = app.controller
        before = ctl.knobs["deadline_ms"]
        _feed(app, _sample(1.0, queue_wait=50.0))
        assert ctl.knobs["deadline_ms"] == pytest.approx(
            before * app.config.CONTROLLER_AIMD_DECREASE)
        # and max_batch multiplicatively when the backlog is the signal
        mb = ctl.knobs["max_batch"]
        _feed(app, _sample(2.0, queue_wait=50.0, pending=5 * mb))
        assert ctl.knobs["max_batch"] == int(
            mb * app.config.CONTROLLER_AIMD_DECREASE)
    finally:
        app.shutdown()


def test_aimd_stretches_deadline_toward_device_profitability():
    app = _app()
    try:
        ctl = app.controller
        before = ctl.knobs["deadline_ms"]
        # flushes riding the host bypass: occupancy below min_batch
        _feed(app, _sample(1.0, queue_wait=0.5,
                           occ=ctl.knobs["min_batch"] - 1))
        assert ctl.knobs["deadline_ms"] == pytest.approx(
            round(before * app.config.CONTROLLER_DEADLINE_GROW, 4))
    finally:
        app.shutdown()


def test_min_batch_follows_dispatch_shape_and_bounds_hold():
    from stellar_core_tpu.ops.controller import (
        DEADLINE_CEIL_MS, DEADLINE_FLOOR_MS, MAX_BATCH_CEIL)
    app = _app()
    try:
        ctl = app.controller
        mb = ctl.knobs["min_batch"]
        # first dispatch-bearing sample only records the cumulative
        # baseline (the accounting is lifetime — judging it without a
        # delta would move knobs on stale evidence)
        _feed(app, _sample(0.5, queue_wait=1.0, occ=mb,
                           dispatch={"count": 1, "batch_p50": 3 * mb,
                                     "batch_p99": 3 * mb,
                                     "pad_waste_ratio": 0.8,
                                     "wall_p99_ms": 1.0}))
        assert ctl.knobs["min_batch"] == mb
        # pad waste on NEW small dispatches: raise the bypass cutoff
        _feed(app, _sample(1.0, queue_wait=1.0, occ=4,
                           dispatch={"count": 5, "batch_p50": mb,
                                     "batch_p99": mb,
                                     "pad_waste_ratio": 0.8,
                                     "wall_p99_ms": 1.0}))
        assert ctl.knobs["min_batch"] == mb * 2
        # big healthy dispatches: lower it back toward the device
        _feed(app, _sample(2.0, queue_wait=1.0, occ=4,
                           dispatch={"count": 9, "batch_p50": 512,
                                     "batch_p99": 9 * mb,
                                     "pad_waste_ratio": 0.0,
                                     "wall_p99_ms": 1.0}))
        assert ctl.knobs["min_batch"] == mb
        # bounds: a long congested/filling streak never escapes the
        # validated envelope
        for i in range(3, 60):
            _feed(app, _sample(float(i), queue_wait=50.0))
        assert ctl.knobs["deadline_ms"] >= DEADLINE_FLOOR_MS
        for i in range(60, 400):
            _feed(app, _sample(float(i), queue_wait=0.1,
                               occ=int(0.9 * ctl.knobs["max_batch"])))
        assert ctl.knobs["max_batch"] <= MAX_BATCH_CEIL
        assert ctl.knobs["deadline_ms"] <= DEADLINE_CEIL_MS
    finally:
        app.shutdown()


def test_knobs_apply_live_to_verify_service_and_verifier():
    """The mutable-safe plumbing: a tune lands in the running service
    (under its lock) and in the verifier's bypass cutoff through the
    supervisor proxy."""
    from stellar_core_tpu.ops.verify_service import VerifyService

    class FakeVerifier:
        _device_min_batch = 16

        def set_device_min_batch(self, n):
            self._device_min_batch = max(1, int(n))

        def verify_tuples_async(self, items):
            return lambda: [True] * len(items)

    app = _app()
    try:
        fake = FakeVerifier()
        svc = VerifyService(fake, clock=app.clock,
                            metrics=app.metrics)
        app.verify_service = svc
        app.batch_verifier = fake
        ctl = app.controller
        _feed(app, _sample(1.0, queue_wait=50.0))   # deadline back-off
        assert svc.knobs()["deadline_ms"] == \
            pytest.approx(ctl.knobs["deadline_ms"])
        _feed(app, _sample(1.5, queue_wait=1.0, occ=16,
                           dispatch={"count": 1, "batch_p50": 48,
                                     "batch_p99": 48,
                                     "pad_waste_ratio": 0.0,
                                     "wall_p99_ms": 1.0}))
        _feed(app, _sample(2.0, queue_wait=1.0, occ=4,
                           dispatch={"count": 5, "batch_p50": 16,
                                     "batch_p99": 16,
                                     "pad_waste_ratio": 0.8,
                                     "wall_p99_ms": 1.0}))
        assert fake._device_min_batch == ctl.knobs["min_batch"]
        assert ctl.knobs["min_batch"] == 32       # judged on the delta
        # shrinking max_batch below the live backlog flushes it now
        for i in range(5):
            svc.submit(b"\x00" * 32, b"\x00" * 64, b"m%d" % i,
                       use_cache=False)
        before = svc.stats()["flushes"]
        svc.set_knobs(max_batch=4)
        assert svc.stats()["flushes"] == before + 1
        svc.drain()
    finally:
        app.shutdown()


# ---------------------------------------------------------- shed ladder --

def _slo_cfg():
    cfg = get_test_config()
    cfg.SLO_CLOSE_P99_MS = 1000.0
    return cfg


def test_shed_ladder_warn_breach_and_decay():
    app = _app(_slo_cfg())
    try:
        ctl = app.controller
        step = app.config.CONTROLLER_SHED_STEP
        # WARN band (>= 0.8 x threshold): tx gate ramps, flood stays
        _feed(app, _sample(1.0, close_p99=850.0))
        assert ctl.shed_tx == pytest.approx(step)
        assert ctl.shed_flood == 0.0
        # BREACH (dwell 0): tx ramps 2x, flood 1x
        _feed(app, _sample(2.0, close_p99=1500.0))
        assert ctl.shed_tx == pytest.approx(3 * step)
        assert ctl.shed_flood == pytest.approx(step)
        # sustained WARN after a breach: tx keeps ramping but flood
        # RELIEF decays — one breach tick must not pin flood drops at
        # the high-water mark for as long as the warn band persists
        decay = app.config.CONTROLLER_SHED_DECAY
        _feed(app, _sample(2.5, close_p99=850.0))
        assert ctl.shed_tx == pytest.approx(4 * step)
        assert ctl.shed_flood == pytest.approx(step - decay)
        # recovery decays both toward zero
        _feed(app, _sample(3.0, close_p99=100.0))
        assert ctl.shed_tx == pytest.approx(4 * step - decay)
        assert ctl.shed_flood == pytest.approx(step - 2 * decay)
        for i in range(4, 20):
            _feed(app, _sample(float(i), close_p99=100.0))
        assert ctl.shed_tx == 0.0 and ctl.shed_flood == 0.0
        # the ladder never exceeds the cap
        for i in range(20, 40):
            _feed(app, _sample(float(i), close_p99=5000.0))
        assert ctl.shed_tx == app.config.CONTROLLER_SHED_MAX
    finally:
        app.shutdown()


def test_backlog_surge_gate_learns_cost_and_slams():
    app = _app(_slo_cfg())
    try:
        ctl = app.controller
        # two closes of 100 txs each at ~2ms/tx teach the cost
        _feed(app, _sample(1.0, close_p99=210.0, close_median=200.0,
                           ledger=10, tx_applied=1000))
        _feed(app, _sample(2.0, close_p99=210.0, close_median=200.0,
                           ledger=11, tx_applied=1100))
        assert ctl.status()["cost_ms_per_tx"] == pytest.approx(2.0)
        # budget = 1000ms * 0.4 => capacity ~200 txs
        cap = ctl.status()["close_capacity_txs"]
        assert cap == 200
        _feed(app, _sample(3.0, close_p99=210.0, ledger=11,
                           tx_applied=1100, pending=cap + 50))
        assert ctl.shed_tx == app.config.CONTROLLER_SHED_MAX
        assert any(d["field"] == "backlog" for d in ctl.decisions)
    finally:
        app.shutdown()


def test_backlog_gate_floored_by_demonstrated_safe_txset():
    """The average-cost model folds fixed per-ledger overhead into the
    per-tx cost; the demonstrated-safe floor keeps the gate from
    shedding baseline load the node provably closes inside the warn
    band."""
    app = _app(_slo_cfg())
    try:
        ctl = app.controller
        # 100-tx ledgers closing at 790ms: p99 below the 800ms warn
        # band, but the naive capacity (1000*0.4 / 7.9ms = 50) sits
        # UNDER the demonstrated txset
        _feed(app, _sample(1.0, close_p99=790.0, close_median=790.0,
                           ledger=10, tx_applied=1000))
        _feed(app, _sample(2.0, close_p99=790.0, close_median=790.0,
                           ledger=11, tx_applied=1100))
        st = ctl.status()
        assert st["safe_txset"] == 100
        assert st["close_capacity_txs"] == 100    # floored, not 50
        # pending at the demonstrated level must NOT trip the gate
        _feed(app, _sample(3.0, close_p99=790.0, ledger=11,
                           tx_applied=1100, pending=100))
        assert ctl.shed_tx == 0.0
        # the floor only rises while the band is clean: a warn-band
        # close does not raise it
        _feed(app, _sample(4.0, close_p99=900.0, close_median=900.0,
                           ledger=12, tx_applied=1400))
        assert ctl.status()["safe_txset"] == 100
    finally:
        app.shutdown()


def test_tx_submit_gate_returns_try_again_later():
    import test_standalone_app as m1
    from txtest_utils import op_payment

    app = _app()
    try:
        master = m1.master_account(app)
        frame = master.tx([op_payment(master.muxed, 7)])
        app.controller.shed_tx = 1.0
        res = app.herder.recv_transaction(frame)
        assert res == AddResult.ADD_STATUS_TRY_AGAIN_LATER
        assert app.herder.tx_queue.size_txs() == 0
        assert app.controller.status()["shed"]["tx_dropped"] == 1
        # gate open again: the same submission admits
        app.controller.shed_tx = 0.0
        assert app.herder.recv_transaction(frame) == \
            AddResult.ADD_STATUS_PENDING
    finally:
        app.shutdown()


# --------------------------------------- shed-before-dispatch ordering --

def test_shed_frames_never_reach_verify_dispatch():
    """ISSUE 11 satellite: flood-admission drops run BEFORE the
    batched recv_transactions verify dispatch — a shedding node
    records ZERO verify-service submissions and zero device-dispatch
    growth for shed frames, and charges them to per-peer shed
    accounting instead of bad-sig."""
    from stellar_core_tpu.ops.verify_service import VerifyService
    from stellar_core_tpu.xdr.overlay import MessageType, StellarMessage
    import test_standalone_app as m1
    from txtest_utils import op_payment

    class FakeVerifier:
        _device_min_batch = 1

        def verify_tuples_async(self, items):
            from stellar_core_tpu.crypto.keys import verify_sig_uncached
            res = [verify_sig_uncached(p, s, m) for p, s, m in items]
            return lambda: res

    class FakePeer:
        peer_id = b"\x07" * 32
        shed_drops = 0
        duplicate_messages = 0
        bad_sig_drops = 0

    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    sender = _app(get_test_config())
    receiver = Application.create(clock, get_test_config(1))
    receiver.start()
    receiver.config.NETWORK_PASSPHRASE = \
        sender.config.NETWORK_PASSPHRASE
    try:
        svc = VerifyService(FakeVerifier(), clock=clock,
                            metrics=receiver.metrics)
        receiver.verify_service = svc
        receiver.herder.verify_service = svc
        master = m1.master_account(sender)
        frames = [master.tx([op_payment(master.muxed, i + 1)])
                  for i in range(6)]
        om = receiver.overlay_manager
        peer = FakePeer()
        receiver.controller.shed_flood = 1.0
        disp_before = receiver.metrics.new_histogram(
            "crypto.verify.dispatch.batch").to_json()["count"]
        for f in frames:
            om._on_transaction(peer, StellarMessage(
                MessageType.TRANSACTION, f.envelope))
        assert om._tx_recv_buffer == []       # dropped pre-buffer
        clock.crank(False)
        # nothing submitted, nothing dispatched, nothing admitted
        assert svc.stats()["submitted"] == 0
        assert receiver.metrics.new_histogram(
            "crypto.verify.dispatch.batch").to_json()["count"] == \
            disp_before
        assert receiver.herder.tx_queue.size_txs() == 0
        # charged to shed accounting, NOT bad-sig (nothing was
        # verified, so nothing can be called invalid)
        assert peer.shed_drops == 6
        assert peer.bad_sig_drops == 0
        assert receiver.controller.status()["shed"][
            "flood_dropped"] == 6
        # gate open: the same bodies admit through one batch
        receiver.controller.shed_flood = 0.0
        for f in frames:
            om._on_transaction(peer, StellarMessage(
                MessageType.TRANSACTION, f.envelope))
        clock.crank(False)
        assert receiver.herder.tx_queue.size_txs() == 6
        assert svc.stats()["submitted"] >= 6
    finally:
        sender.shutdown()
        receiver.shutdown()


# --------------------------------------------------- breaker interplay --

def test_tuning_frozen_while_breaker_open_sheds_continue():
    app = _app(_slo_cfg())
    try:
        ctl = app.controller
        knobs = dict(ctl.knobs)
        # breaker OPEN + congested + breaching: no knob moves, shed
        # still ramps (a degraded node needs admission control MORE)
        _feed(app, _sample(1.0, close_p99=2000.0, queue_wait=50.0,
                           breaker="OPEN"))
        assert ctl.knobs == knobs
        assert ctl.shed_tx > 0.0
        assert app.metrics.counter(
            "controller", "freeze", "tick").count == 1
        # breaker back CLOSED: tuning resumes on the same evidence
        _feed(app, _sample(2.0, close_p99=2000.0, queue_wait=50.0,
                           breaker="CLOSED"))
        assert ctl.knobs["deadline_ms"] < knobs["deadline_ms"]
    finally:
        app.shutdown()


def test_partial_mesh_scales_capacity_without_freezing():
    """ISSUE 13 (the item-6 hook): a PARTIALLY degraded verify mesh —
    sample ``mesh.active < mesh.devices`` with the aggregate breaker
    CLOSED — must NOT freeze AIMD tuning (the batch path is still the
    device path), but must scale the learned close capacity and the
    demonstrated-safe floor by the surviving-device fraction, read
    from the SAMPLE for replay determinism. Full-mesh samples restore
    full capacity."""
    app = _app(_slo_cfg())
    try:
        ctl = app.controller
        full = {"devices": 8, "active": 8}
        # teach the cost model on the full mesh (2ms/tx, cap 200)
        _feed(app, _sample(1.0, close_p99=210.0, close_median=200.0,
                           ledger=10, tx_applied=1000, mesh=full))
        _feed(app, _sample(2.0, close_p99=210.0, close_median=200.0,
                           ledger=11, tx_applied=1100, mesh=full))
        assert ctl.status()["close_capacity_txs"] == 200
        freeze = app.metrics.counter("controller", "freeze", "tick")
        frozen_before = freeze.count
        knobs = dict(ctl.knobs)
        # 6/8 mesh: capacity scales to 150, tuning keeps moving
        _feed(app, _sample(3.0, queue_wait=50.0, ledger=11,
                           tx_applied=1100,
                           mesh={"devices": 8, "active": 6}))
        st = ctl.status()
        assert st["mesh_fraction"] == 0.75
        assert st["close_capacity_txs"] == 150
        assert freeze.count == frozen_before        # NOT frozen
        assert ctl.knobs["deadline_ms"] < knobs["deadline_ms"]
        assert any(d["kind"] == "mesh" and d["field"] == "fraction"
                   and d["new"] == 0.75 for d in ctl.decisions)
        # closes measured ON the shrunk mesh must not feed the cost
        # model: the capacity discount already accounts for the
        # outage, and absorbing the degraded (higher) cost too would
        # double-count it (capacity ~ frac^2)
        _feed(app, _sample(3.5, close_p99=850.0, close_median=400.0,
                           ledger=12, tx_applied=1200,
                           mesh={"devices": 8, "active": 6}))
        assert ctl.status()["cost_ms_per_tx"] == pytest.approx(2.0)
        assert ctl.status()["close_capacity_txs"] == 150
        # the surge gate sheds against the SCALED capacity
        _feed(app, _sample(4.0, ledger=11, tx_applied=1100,
                           pending=180,
                           mesh={"devices": 8, "active": 6}))
        assert ctl.shed_tx == app.config.CONTROLLER_SHED_MAX
        # canary re-probe regrows the mesh: capacity restored
        _feed(app, _sample(5.0, ledger=11, tx_applied=1100,
                           mesh=full))
        assert ctl.status()["mesh_fraction"] == 1.0
        assert ctl.status()["close_capacity_txs"] == 200
        # a WHOLE-mesh outage (aggregate OPEN) still freezes tuning
        _feed(app, _sample(6.0, queue_wait=50.0, breaker="OPEN",
                           mesh={"devices": 8, "active": 0}))
        assert freeze.count == frozen_before + 1
    finally:
        app.shutdown()


def test_chaos_hang_mid_tune_does_not_wedge_controller():
    """A hung device dispatch (chaos `hang` on ops.backend.dispatch)
    trips the breaker through the watchdog; the controller keeps
    ticking — tuning frozen, shedding live — instead of wedging on
    the dead backend."""
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.ops.backend_supervisor import (OPEN,
                                                         BackendSupervisor)

    class FakeVerifier:
        _device_min_batch = 1

        def verify_tuples_async(self, items):
            from stellar_core_tpu.crypto.keys import verify_sig_uncached
            res = [verify_sig_uncached(p, s, m) for p, s, m in items]
            return lambda: res

    app = _app(_slo_cfg())
    sup = BackendSupervisor(FakeVerifier(), clock=app.clock,
                            metrics=app.metrics,
                            dispatch_deadline_ms=40.0,
                            failure_threshold=1)
    app.batch_verifier = sup
    sk = SecretKey.pseudo_random_for_testing(4242)
    msg = b"controller-hang".ljust(32, b".")
    items = [(sk.public_key().raw, sk.sign(msg), msg)]
    chaos.install(ChaosEngine(17, [FaultSpec(
        "ops.backend.dispatch", "hang", start=0, count=1)]))
    try:
        # mid-tune: the controller was actively moving knobs
        _feed(app, _sample(1.0, queue_wait=50.0))
        assert app.controller.decisions
        # the hung dispatch resolves through the watchdog and trips
        assert sup.verify_tuples(items) == [True]
        assert sup.state == OPEN
        # the next REAL sample sees breaker=OPEN (collect_sample reads
        # the supervisor) — tick completes promptly, tuning frozen
        sample = app.telemetry.sample_now()
        assert sample["breaker"] == "OPEN"
        knobs = dict(app.controller.knobs)
        app.slo.observe(sample)
        app.controller.tick(sample)
        assert app.controller.knobs == knobs
        assert app.metrics.counter(
            "controller", "freeze", "tick").count >= 1
    finally:
        chaos.uninstall()
        sup.shutdown()
        app.shutdown()


# ----------------------------------------------------- determinism --

def _surge_schedule(i):
    """A seeded surge shape: base load, step overload, recovery —
    pure function of the tick index, so two runs see byte-identical
    samples."""
    if i < 5:
        return _sample(float(i), close_p99=150.0, queue_wait=1.0,
                       occ=200, ledger=i, tx_applied=100 * i)
    if i < 12:
        return _sample(float(i), close_p99=3000.0, queue_wait=40.0,
                       occ=250, pending=900 + 13 * i, ledger=5,
                       tx_applied=500)
    return _sample(float(i), close_p99=120.0, queue_wait=0.6, occ=4,
                   ledger=i - 6, tx_applied=500 + 40 * (i - 11))


def test_decision_log_byte_identical_across_runs():
    """The determinism contract: identical seeded surge schedules on
    the VirtualClock produce byte-identical decision logs — every
    timing read comes from sample `t`, never the wall."""
    logs = []
    for _ in range(2):
        app = _app(_slo_cfg())
        try:
            for i in range(20):
                _feed(app, _surge_schedule(i))
            assert app.controller.decisions, "schedule moved nothing"
            logs.append(json.dumps(list(app.controller.decisions),
                                   sort_keys=True))
        finally:
            app.shutdown()
    assert logs[0] == logs[1]


def test_tick_is_idempotent_per_sample():
    app = _app()
    try:
        app.telemetry.sample_now()
        app.controller.tick()
        n = app.controller.ticks
        app.controller.tick()      # same cursor: no second step
        assert app.controller.ticks == n
        app.telemetry.sample_now()
        app.controller.tick()
        assert app.controller.ticks == n + 1
    finally:
        app.shutdown()


# ------------------------------------------------- route + clean slate --

def test_controller_route_status_freeze_reset():
    app = _app(_slo_cfg())
    try:
        handle = app.command_handler.handle
        doc = handle("controller")["controller"]
        assert doc["enabled"] is False        # test config: manual
        assert doc["knobs"] == doc["config_knobs"]
        _feed(app, _sample(1.0, close_p99=2000.0, queue_wait=50.0))
        doc = handle("controller")["controller"]
        assert doc["shed"]["tx"] > 0
        assert doc["decisions"]["total"] > 0
        # freeze pins everything
        assert handle("controller", {"action": "freeze"})[
            "controller"]["frozen"] is True
        shed = app.controller.shed_tx
        _feed(app, _sample(2.0, close_p99=5000.0, queue_wait=90.0))
        assert app.controller.shed_tx == shed
        # reset restores config knobs + zero shed + rotated epoch
        epoch = app.controller.epoch
        doc = handle("controller", {"action": "reset"})["controller"]
        assert doc["frozen"] is False
        assert doc["knobs"] == doc["config_knobs"]
        assert doc["shed"]["tx"] == 0.0
        assert doc["epoch"] == epoch + 1
        assert doc["decisions"]["total"] == 0
        # actions are chaos-gated; plain status is always served
        app.config.ALLOW_CHAOS_INJECTION = False
        out = handle("controller", {"action": "freeze"})
        assert "exception" in out
        assert "controller" in handle("controller")
    finally:
        app.config.ALLOW_CHAOS_INJECTION = True
        app.shutdown()


def test_clearmetrics_resets_controller_state():
    """ISSUE 11 satellite: back-to-back bench legs in one process
    start clean — learned knobs, shed probabilities and the decision
    log all reset, epoch rotated like the PR 10 time-series."""
    app = _app(_slo_cfg())
    try:
        _feed(app, _sample(1.0, close_p99=2000.0, queue_wait=50.0))
        ctl = app.controller
        assert ctl.shed_tx > 0 and ctl.decisions \
            and ctl.knobs != ctl._cfg_knobs
        epoch = ctl.epoch
        ctl.freeze()    # even a frozen controller cannot leak tuning
        app.command_handler.handle("clearmetrics")
        assert ctl.knobs == ctl._cfg_knobs
        assert ctl.shed_tx == 0.0 and ctl.shed_flood == 0.0
        assert not ctl.decisions and not ctl.frozen
        assert ctl.epoch == epoch + 1
        assert ctl.status()["cost_ms_per_tx"] is None
    finally:
        app.shutdown()
