"""xdrquery DSL tests (reference: util/xdrquery/test/XDRQueryTests.cpp —
same matcher/extractor/accumulator semantics, our own fixtures)."""

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.strkey import StrKey
from stellar_core_tpu.util.xdrquery import (XDRAccumulator, XDRFieldExtractor,
                                            XDRMatcher, XDRQueryError)
from stellar_core_tpu.xdr.ledger_entries import (AccountEntry, Asset,
                                                 LedgerEntry, OfferEntry,
                                                 Price)
from stellar_core_tpu.xdr.types import PublicKey


def account_id(i: int):
    return PublicKey.ed25519(
        SecretKey.from_seed(bytes([i]) * 32).public_key().raw)


def make_account_entry(balance, inflation_dest=True, idx=1):
    ae = AccountEntry(
        accountID=account_id(idx), balance=balance, seqNum=7,
        numSubEntries=2,
        inflationDest=account_id(9) if inflation_dest else None,
        flags=0, homeDomain=b"example.com",
        thresholds=b"\x01\x00\x02\x00", signers=[])
    from stellar_core_tpu.xdr.ledger_entries import (LedgerEntryType,
                                                     _LedgerEntryData,
                                                     _LedgerEntryExt)
    return LedgerEntry(
        lastModifiedLedgerSeq=5,
        data=_LedgerEntryData(LedgerEntryType.ACCOUNT, ae),
        ext=_LedgerEntryExt(0))


def make_offer_entry(code: bytes, idx=2):
    oe = OfferEntry(
        sellerID=account_id(idx), offerID=10,
        selling=Asset.credit(code, account_id(3)),
        buying=Asset.native(), amount=50,
        price=Price(n=1, d=2), flags=0)
    from stellar_core_tpu.xdr.ledger_entries import (LedgerEntryType,
                                                     _LedgerEntryData,
                                                     _LedgerEntryExt)
    return LedgerEntry(
        lastModifiedLedgerSeq=8,
        data=_LedgerEntryData(LedgerEntryType.OFFER, oe),
        ext=_LedgerEntryExt(0))


@pytest.fixture
def entries():
    return [make_account_entry(100),
            make_account_entry(200, inflation_dest=False),
            make_offer_entry(b"foo"),
            make_offer_entry(b"foobar")]


def check(query, entries, expected):
    m = XDRMatcher(query)
    assert [m.match_xdr(e) for e in entries] == expected


def test_int_comparisons(entries):
    check("data.account.balance == 100", entries[:2], [True, False])
    check("100 != data.account.balance", entries[:2], [False, True])
    check("data.account.balance < 150", entries[:2], [True, False])
    check("data.account.balance <= 100", entries[:2], [True, False])
    check("data.account.balance > 150", entries[:2], [False, True])
    check("200 >= data.account.balance", entries[:2], [True, True])


def test_string_comparisons(entries):
    check("data.type == 'ACCOUNT'", entries, [True, True, False, False])
    check("data.type != 'ACCOUNT'", entries, [False, False, True, True])
    check("data.offer.selling.assetCode < 'foobar'", entries,
          [False, False, True, False])
    check("data.offer.selling.assetCode >= 'foo'", entries,
          [False, False, True, True])


def test_null_comparisons(entries):
    # unset optional == NULL; union-arm-miss is never equal to NULL
    check("data.account.inflationDest == NULL", entries,
          [False, True, False, False])
    check("NULL != data.account.inflationDest", entries,
          [True, False, False, False])


def test_bool_operators(entries):
    check("data.account.balance > 150 || "
          "data.offer.selling.assetCode == 'foo'", entries,
          [False, True, True, False])
    check("data.account.balance > 150 "
          "&& '01000200' == data.account.thresholds", entries,
          [False, True, False, False])
    # && binds tighter than ||
    check("'01000200' == data.account.thresholds || "
          "data.type != 'TRUSTLINE' && "
          "data.offer.selling.assetCode <= 'foo'", entries,
          [True, True, True, False])
    check("(('01000200' == data.account.thresholds) || "
          "data.offer.selling.assetCode <= 'foo') "
          "&& data.type != 'TRUSTLINE'", entries,
          [True, True, True, False])


def test_strkey_fields(entries):
    acc = StrKey.encode_ed25519_public(
        SecretKey.from_seed(bytes([1]) * 32).public_key().raw)
    check(f"data.account.accountID == '{acc}'", entries,
          [True, True, False, False])


def test_query_errors(entries):
    for bad in [
        "data.type == 'ACCOUNT",        # unterminated string
        "data.type = 'ACCOUNT'",        # single =
        "$data.type == 'ACCOUNT'",      # bad char
        "data.type.foo == 'ACCOUNT'",   # path past a leaf
        "data.account == 'ACCOUNT'",    # struct is not a leaf
        "data.account.accountID2 == 'A'",
        "data2.account.accountID == 'A'",
        "data.type == 123",             # type mismatch
        "data.account.balance == '123'",
        "data.account.balance <= 10000000000000000000",  # out of range
        "5000000000 > data.account.numSubEntries",
        "data.account.inflationDest <= NULL",
    ]:
        with pytest.raises(XDRQueryError):
            XDRMatcher(bad).match_xdr(entries[0])


def test_field_extractor(entries):
    ex = XDRFieldExtractor(
        "data.type, data.account.balance, data.offer.selling.assetCode")
    assert ex.field_names() == [
        "data.type", "data.account.balance",
        "data.offer.selling.assetCode"]
    assert ex.extract_fields(entries[0]) == ["ACCOUNT", 100, None]
    assert ex.extract_fields(entries[2]) == ["OFFER", None, "foo"]
    with pytest.raises(XDRQueryError):
        XDRFieldExtractor("data.account.balance ==")
    with pytest.raises(XDRQueryError):
        XDRFieldExtractor("data.bogus").extract_fields(entries[0])


def test_accumulators(entries):
    acc = XDRAccumulator(
        "sum(data.account.balance), avg(data.account.balance), count()")
    for e in entries:
        acc.add_entry(e)
    vals = acc.get_values()
    assert vals["sum(data.account.balance)"] == 300
    assert vals["avg(data.account.balance)"] == 150.0
    assert vals["count"] == 4
    with pytest.raises(XDRQueryError):
        XDRAccumulator("max(data.account.balance)")
    with pytest.raises(XDRQueryError):
        XDRAccumulator("sum()")


def test_field_vs_field_type_mismatch(entries):
    with pytest.raises(XDRQueryError):
        XDRMatcher("data.account.balance < data.account.homeDomain"
                   ).match_xdr(entries[0])
    # same-kind field-vs-field comparison works
    assert XDRMatcher("data.account.balance >= data.account.seqNum"
                      ).match_xdr(entries[0]) is True


def test_json_repr_matches_query_leaves(entries):
    """A value copied out of the JSON dump matches the same entry via a
    filter query (shared leaf conversion)."""
    from stellar_core_tpu.xdr.json_repr import to_jsonable
    doc = to_jsonable(entries[0])
    acc = doc["data"]["account"]["accountID"]
    assert acc.startswith("G")
    assert XDRMatcher(
        f"data.account.accountID == '{acc}'").match_xdr(entries[0])
    assert doc["data"]["account"]["thresholds"] == "01000200"
