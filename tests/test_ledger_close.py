"""End-to-end ledger close: genesis -> txset -> closeLedger.

Mirrors the reference's LedgerManager/TxSetFrame test strategy
(src/ledger/test/LedgerManagerTests.cpp, src/herder/test/TxSetTests.cpp):
drive closeLedger with real tx sets and check header chaining, fee
processing, apply order determinism and invariant enforcement.
"""

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.db.database import Database
from stellar_core_tpu.herder import (TransactionQueue, AddResult,
                                     make_tx_set_from_transactions)
from stellar_core_tpu.herder.surge_pricing import SurgePricingLaneConfig
from stellar_core_tpu.herder.upgrades import Upgrades, UpgradeParameters
from stellar_core_tpu.invariant import (InvariantManager,
                                        register_default_invariants)
from stellar_core_tpu.ledger.ledger_manager import (GENESIS_LEDGER_TOTAL_COINS,
                                                    LedgerCloseData,
                                                    LedgerManager,
                                                    ledger_header_hash)
from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
from stellar_core_tpu.tx.frame import make_frame
from stellar_core_tpu.xdr.ledger import LedgerUpgrade, LedgerUpgradeType, \
    StellarValue
from stellar_core_tpu.xdr.ledger_entries import LedgerKey

from txtest_utils import (op_create_account, op_payment, sign_frame)
from stellar_core_tpu.xdr.transaction import (MuxedAccount, Preconditions,
                                              Transaction, TransactionV1Envelope,
                                              TransactionEnvelope)
from stellar_core_tpu.xdr.types import EnvelopeType, PublicKey


def xpk(sk):
    return PublicKey.ed25519(sk.public_key().raw)

NETWORK_ID = sha256(b"test close network")


def make_manager(db=None, invariants=True):
    inv = None
    if invariants:
        inv = InvariantManager()
        register_default_invariants(inv)
        inv.enable([
            "ConservationOfLumens", "LedgerEntryIsValid",
            "AccountSubEntriesCountIsValid", "LiabilitiesMatchOffers",
            "SponsorshipCountIsValid", "ConstantProductInvariant",
        ])
    lm = LedgerManager(db=db, invariants=inv)
    lm.start_new_ledger(NETWORK_ID, protocol_version=21)
    return lm


def master_key():
    return SecretKey.from_seed(NETWORK_ID)


def make_tx(lm, sk, seq, ops, fee=None):
    src = MuxedAccount.from_ed25519(sk.public_key().raw)
    tx = Transaction(sourceAccount=src,
                     fee=fee if fee is not None else 100 * len(ops),
                     seqNum=seq, cond=Preconditions(0),
                     operations=list(ops))
    env = TransactionEnvelope(
        EnvelopeType.ENVELOPE_TYPE_TX,
        TransactionV1Envelope(tx=tx, signatures=[]))
    frame = make_frame(env, NETWORK_ID)
    sign_frame(frame, sk)
    return frame


def close_with(lm, txs, close_time=1000):
    lcl = lm.get_last_closed_ledger_header()
    frame, applicable, excluded = make_tx_set_from_transactions(
        txs, lcl, NETWORK_ID)
    value = StellarValue(txSetHash=frame.get_contents_hash(),
                         closeTime=close_time)
    lcd = LedgerCloseData(lcl.ledgerSeq + 1, frame, value)
    lm.close_ledger(lcd)
    return applicable, excluded


def master_seq(lm):
    with LedgerTxn(lm.root) as ltx:
        le = ltx.load(LedgerKey.account(xpk(master_key())))
        seq = le.data.value.seqNum
        ltx.rollback()
    return seq


def test_genesis_header():
    lm = make_manager()
    h = lm.get_last_closed_ledger_header()
    assert h.ledgerSeq == 1
    assert h.totalCoins == GENESIS_LEDGER_TOTAL_COINS
    assert lm.get_last_closed_ledger_hash() == ledger_header_hash(h)


def test_close_empty_ledger():
    lm = make_manager()
    close_with(lm, [])
    h = lm.get_last_closed_ledger_header()
    assert h.ledgerSeq == 2
    assert h.scpValue.closeTime == 1000


def test_close_with_payment_chain():
    lm = make_manager()
    mk = master_key()
    seq = master_seq(lm)
    dest = SecretKey.random()
    t1 = make_tx(lm, mk, seq + 1,
                 [op_create_account(xpk(dest), 10**9)])
    t2 = make_tx(lm, mk, seq + 2,
                 [op_payment(MuxedAccount.from_ed25519(
                     dest.public_key().raw), 5 * 10**8)])
    close_with(lm, [t2, t1])  # order in the candidate list must not matter
    h = lm.get_last_closed_ledger_header()
    assert h.ledgerSeq == 2
    with LedgerTxn(lm.root) as ltx:
        dle = ltx.load(LedgerKey.account(xpk(dest)))
        assert dle.data.value.balance == 10**9 + 5 * 10**8
        ltx.rollback()
    # fees charged into the pool
    assert h.feePool == t1.full_fee() + t2.full_fee()
    # lumens conserved
    assert h.totalCoins == GENESIS_LEDGER_TOTAL_COINS


def test_header_hash_chain():
    lm = make_manager()
    h1 = lm.get_last_closed_ledger_hash()
    close_with(lm, [])
    h2 = lm.get_last_closed_ledger_header()
    assert h2.previousLedgerHash == h1


def test_close_rejects_wrong_seq():
    lm = make_manager()
    lcl = lm.get_last_closed_ledger_header()
    frame, _, _ = make_tx_set_from_transactions([], lcl, NETWORK_ID)
    value = StellarValue(txSetHash=frame.get_contents_hash(), closeTime=1)
    with pytest.raises(ValueError):
        lm.close_ledger(LedgerCloseData(lcl.ledgerSeq + 5, frame, value))


def test_close_rejects_wrong_txset_hash():
    lm = make_manager()
    lcl = lm.get_last_closed_ledger_header()
    frame, _, _ = make_tx_set_from_transactions([], lcl, NETWORK_ID)
    value = StellarValue(txSetHash=b"\x01" * 32, closeTime=1)
    with pytest.raises(ValueError):
        lm.close_ledger(LedgerCloseData(lcl.ledgerSeq + 1, frame, value))


def test_apply_order_deterministic_and_seq_monotonic():
    lm = make_manager()
    mk = master_key()
    seq = master_seq(lm)
    txs = [make_tx(lm, mk, seq + i + 1,
                   [op_manage_data_stub(i)]) for i in range(5)]
    lcl = lm.get_last_closed_ledger_header()
    _, applicable, _ = make_tx_set_from_transactions(txs, lcl, NETWORK_ID)
    order1 = [t.full_hash() for t in applicable.get_txs_in_apply_order()]
    order2 = [t.full_hash() for t in applicable.get_txs_in_apply_order()]
    assert order1 == order2
    # same-account txs stay in seqnum order
    seqs = [t.seq_num for t in applicable.get_txs_in_apply_order()]
    assert seqs == sorted(seqs)


def op_manage_data_stub(i):
    from txtest_utils import op_manage_data
    return op_manage_data(b"key%d" % i, b"val")


def test_db_backed_close_and_reload():
    db = Database(":memory:")
    db.initialize()
    lm = make_manager(db=db)
    mk = master_key()
    seq = master_seq(lm)
    dest = SecretKey.random()
    t1 = make_tx(lm, mk, seq + 1,
                 [op_create_account(xpk(dest), 10**9)])
    close_with(lm, [t1])
    # tx history persisted
    row = db.query_one("SELECT txbody FROM txhistory WHERE ledgerseq=2")
    assert row is not None
    # reload from DB
    lm2 = LedgerManager(db=db)
    assert lm2.load_last_known_ledger()
    assert lm2.get_last_closed_ledger_num() == 2
    assert (lm2.get_last_closed_ledger_hash()
            == lm.get_last_closed_ledger_hash())


def test_upgrade_applied_through_close():
    lm = make_manager()
    lcl = lm.get_last_closed_ledger_header()
    frame, _, _ = make_tx_set_from_transactions([], lcl, NETWORK_ID)
    up = LedgerUpgrade(LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE, 250)
    value = StellarValue(txSetHash=frame.get_contents_hash(), closeTime=1,
                         upgrades=[up.to_bytes()])
    lm.close_ledger(LedgerCloseData(lcl.ledgerSeq + 1, frame, value))
    assert lm.get_last_closed_ledger_header().baseFee == 250


def test_upgrades_voting():
    u = Upgrades(UpgradeParameters(upgrade_time=100, base_fee=500),
                 current_protocol_version=21)
    from txtest_utils import make_header
    header = make_header(ledger_version=21)
    assert u.create_upgrades_for(header, close_time=50) == []
    ups = u.create_upgrades_for(header, close_time=150)
    assert len(ups) == 1 and ups[0].value == 500
    assert u.is_valid(ups[0], header, nomination=True, close_time=150)
    assert not u.is_valid(
        LedgerUpgrade(LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE, 400),
        header, nomination=True, close_time=150)
    # structural validity only after externalization
    assert u.is_valid(
        LedgerUpgrade(LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE, 400),
        header, nomination=False)


def test_surge_pricing_excludes_lowest_fee():
    """Across ACCOUNTS, the lowest fee rates lose (reference:
    SurgePricingPriorityQueue; one tx per account so chain order does
    not constrain selection)."""
    lm = make_manager()
    mk = master_key()
    seq = master_seq(lm)
    sks = [SecretKey.from_seed(sha256(b"surge-%d" % i)) for i in range(5)]
    close_with(lm, [make_tx(lm, mk, seq + 1,
                            [op_create_account(xpk(sk), 10**9)
                             for sk in sks])])
    created = lm.get_last_closed_ledger_num()
    txs = []
    for i, sk in enumerate(sks):
        txs.append(make_tx(lm, sk, (created << 32) + 1,
                           [op_manage_data_stub(i)], fee=100 + 50 * i))
    lcl = lm.get_last_closed_ledger_header()
    cfg = SurgePricingLaneConfig([3])
    frame, applicable, excluded = make_tx_set_from_transactions(
        txs, lcl, NETWORK_ID, cfg)
    assert len(excluded) == 2
    incl_fees = sorted(t.full_fee() for t in applicable.txs)
    assert incl_fees == [200, 250, 300]
    # clearing base fee = lowest included rate
    for t in applicable.txs:
        assert applicable.base_fee_for(t) == 200
    # and the produced set is actually valid against the ledger
    assert applicable.check_valid(lm.root)


def test_surge_pricing_keeps_account_chains_contiguous():
    """Same-account txs are only included in seqnum order, even when
    later txs bid more — trimming must never create a seqnum gap
    (reference: per-account TxStacks in SurgePricingPriorityQueue)."""
    lm = make_manager()
    mk = master_key()
    seq = master_seq(lm)
    txs = [make_tx(lm, mk, seq + i + 1,
                   [op_manage_data_stub(i)], fee=100 + 50 * i)
           for i in range(5)]
    lcl = lm.get_last_closed_ledger_header()
    cfg = SurgePricingLaneConfig([3])
    frame, applicable, excluded = make_tx_set_from_transactions(
        txs, lcl, NETWORK_ID, cfg)
    assert len(excluded) == 2
    # the FIRST three of the chain are kept (fees 100..200), so the
    # produced set validates
    assert sorted(t.seq_num for t in applicable.txs) == \
        [seq + 1, seq + 2, seq + 3]
    assert applicable.check_valid(lm.root)


def test_tx_queue_lifecycle():
    lm = make_manager()
    mk = master_key()
    seq = master_seq(lm)
    q = TransactionQueue(pending_depth=2, ban_depth=3)
    t1 = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)])
    t2 = make_tx(lm, mk, seq + 2, [op_manage_data_stub(1)])
    assert q.try_add(t1, lm.root, 100) == AddResult.ADD_STATUS_PENDING
    assert q.try_add(t1, lm.root, 100) == AddResult.ADD_STATUS_DUPLICATE
    assert q.try_add(t2, lm.root, 100) == AddResult.ADD_STATUS_PENDING
    assert q.size_txs() == 2
    # ageing: after pending_depth shifts unapplied txs get banned
    q.shift()
    q.shift()
    assert q.size_txs() == 0
    assert q.is_banned(t1.full_hash())
    assert q.try_add(t1, lm.root, 100) == AddResult.ADD_STATUS_TRY_AGAIN_LATER
    # bans expire after ban_depth shifts
    q.shift()
    q.shift()
    q.shift()
    assert not q.is_banned(t1.full_hash())


def test_tx_queue_eviction_by_fee():
    lm = make_manager()
    mk = master_key()
    seq = master_seq(lm)
    q = TransactionQueue()
    cheap = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)], fee=100)
    assert q.try_add(cheap, lm.root, 1) == AddResult.ADD_STATUS_PENDING
    rich_sk = SecretKey.random()
    # fund a second account so its tx validates
    t = make_tx(lm, mk, seq + 1,
                [op_create_account(xpk(rich_sk), 10**10)])
    close_with(lm, [t])
    rich = make_tx(lm, rich_sk, (2 << 32) + 1,
                   [op_manage_data_stub(1)], fee=5000)
    assert q.try_add(rich, lm.root, 1) == AddResult.ADD_STATUS_PENDING
    assert q.size_txs() == 1
    assert q.get_transactions()[0] is rich
    assert q.is_banned(cheap.full_hash())


def test_tx_queue_two_phase_eviction_no_partial_drop():
    """If the newcomer cannot free enough capacity (it only outbids part
    of the eviction set), NOTHING is evicted or banned (reference:
    TxQueueLimiter evaluates the full eviction set first)."""
    lm = make_manager()
    mk = master_key()
    seq = master_seq(lm)
    q = TransactionQueue()
    cheap = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)], fee=100)
    pricey = make_tx(lm, mk, seq + 2, [op_manage_data_stub(1)], fee=9000)
    assert q.try_add(cheap, lm.root, 2) == AddResult.ADD_STATUS_PENDING
    assert q.try_add(pricey, lm.root, 2) == AddResult.ADD_STATUS_PENDING
    # a 2-op tx needing both slots, outbidding only the cheap one
    rich_sk = SecretKey.random()
    t = make_tx(lm, mk, seq + 1,
                [op_create_account(xpk(rich_sk), 10**10)])
    close_with(lm, [t])
    mid = make_tx(lm, rich_sk, (2 << 32) + 1,
                  [op_manage_data_stub(2), op_manage_data_stub(3)],
                  fee=1000)   # rate 500/op: beats 100, loses to 9000
    assert q.try_add(mid, lm.root, 2) == \
        AddResult.ADD_STATUS_TRY_AGAIN_LATER
    # nothing was dropped or banned
    assert q.size_txs() == 2
    assert not q.is_banned(cheap.full_hash())


def test_invariant_violation_crashes_close():
    """A corrupting operation must raise InvariantDoesNotHold, not be
    swallowed as txINTERNAL_ERROR."""
    from stellar_core_tpu.invariant import InvariantDoesNotHold
    lm = make_manager()
    mk = master_key()
    seq = master_seq(lm)
    dest = SecretKey.random()
    t1 = make_tx(lm, mk, seq + 1,
                 [op_create_account(xpk(dest), 10**9)])

    # sabotage: an invariant that always fails stands in for corruption
    class AlwaysFails:
        name = "AlwaysFails"

        def check_on_operation_apply(self, op, result, delta):
            return "sabotage"

        def check_on_bucket_apply(self, *a):
            return None

    lm.invariants.register(AlwaysFails())
    lm.invariants.enable(["AlwaysFails"])
    with pytest.raises(InvariantDoesNotHold):
        close_with(lm, [t1])
