"""Executable int32 overflow budget for the fe8 carry schedule.

Round-4 kernel change (docs/LIMB_WIDTHS.md): the rolled TPU multiply
carries THREE passes (not four), group-law sums feeding a multiply use
add_c (one pass), differences feeding a multiply use sub1 (one pass).
This file is the proof obligation: per-limb interval arithmetic over
exactly the formulas fe8 implements, asserting

  * every schoolbook column stays < 2^31 at the worst legal inputs,
  * three passes bound rolled-mul outputs <= 711 (a stable fixpoint),
  * sub1 outputs stay <= 1053 < MUL_INPUT_BOUND = 1349,
  * the full group-law op graph (dbl / cached-add / to_cached /
    decompress shapes) never feeds a multiply anything >= 1349,

plus randomized exactness checks of the actual jax ops at those same
extreme inputs (which real field values never reach).
"""

import numpy as np

import stellar_core_tpu.ops.fe8 as fe8

MUL_INPUT_BOUND = 1349      # max B with 1179 * B^2 < 2^31
INT32_MAX = 2**31 - 1

# per-limb bias of 16p (mirrors fe8._BIAS16P)
BIAS = np.full(32, 16 * 0xFF, dtype=np.int64)
BIAS[0] = 16 * 0xED
BIAS[31] = 16 * 0x7F


# ------------------------- interval model of the fe8 ops (upper bounds) --

def col_bounds(a, b):
    """Upper bounds of the 32 folded schoolbook columns for inputs with
    per-limb bounds a, b (the rolled and scatter forms share these
    column sums)."""
    out = np.zeros(32, dtype=np.int64)
    for i in range(32):
        for j in range(32):
            k = (i + j) % 32
            w = 38 if i + j >= 32 else 1
            out[k] += w * a[i] * b[j]
    return out


def carry_bounds(c):
    """carry_pass upper bounds: l <= 255, limb0 += 38*(c31>>8),
    limb i += c_{i-1}>>8."""
    out = np.full(32, 255, dtype=np.int64)
    out[0] += 38 * (c[31] >> 8)
    out[1:] += c[:-1] >> 8
    return out


def mul_bounds(a, b, passes=3):
    c = col_bounds(a, b)
    assert c.max() <= INT32_MAX, f"column overflow {c.max():.3e}"
    for _ in range(passes):
        c = carry_bounds(c)
    return c


def add_c_bounds(a, b):
    return carry_bounds(a + b)


def sub1_bounds(a, b):
    assert (b <= BIAS).all(), "sub bias floor violated"
    return carry_bounds(a + BIAS)


def sub_bounds(a, b):
    return carry_bounds(sub1_bounds(a, b))


def v(x):
    return np.full(32, x, dtype=np.int64)


def test_three_pass_mul_fixpoint():
    # worst legal mul input (sub1 output) keeps columns in int32
    out = mul_bounds(v(1053), v(1053))
    assert out.max() <= 711, out.max()
    # and the bound is a fixpoint: 711-in -> 711-out
    out2 = mul_bounds(v(711), v(711))
    assert out2.max() <= 711, out2.max()
    # the documented absolute input ceiling still fits int32 columns
    col_max = col_bounds(v(MUL_INPUT_BOUND), v(MUL_INPUT_BOUND)).max()
    assert col_max <= INT32_MAX
    assert col_bounds(v(MUL_INPUT_BOUND + 1),
                      v(MUL_INPUT_BOUND + 1)).max() > INT32_MAX


def test_group_law_budget():
    """Walk the exact op graph of ge_dbl_w / to_cached / ge_add_cached /
    decompress with interval bounds; assert every multiply input is
    below MUL_INPUT_BOUND (so every column < 2^31)."""
    M = v(711)          # any mul/sq output

    def check_mul(a, b):
        assert a.max() < MUL_INPUT_BOUND, a.max()
        assert b.max() < MUL_INPUT_BOUND, b.max()
        return mul_bounds(a, b)

    # --- ge_dbl_w(p) with coords bounded by mul outputs
    x1 = y1 = z1 = M
    a = check_mul(x1, x1)
    b = check_mul(y1, y1)
    zz = check_mul(z1, z1)
    e0 = check_mul(add_c_bounds(x1, y1), add_c_bounds(x1, y1))
    c = zz + zz
    s1 = add_c_bounds(a, b)
    e = sub1_bounds(e0, s1)
    g = sub1_bounds(b, a)
    f = sub1_bounds(c, g)
    x3 = check_mul(e, f)
    y3 = check_mul(g, s1)
    z3 = check_mul(f, g)
    t3 = check_mul(e, s1)

    # --- to_cached(q)
    yx2 = add_c_bounds(y3, x3)
    ym2 = sub1_bounds(y3, x3)
    z22 = add_c_bounds(z3, z3)
    t2d = check_mul(t3, v(255))           # D2 is canonical

    # --- ge_add_cached(p, cq)
    aa = check_mul(sub1_bounds(y3, x3), ym2)
    bb = check_mul(add_c_bounds(y3, x3), yx2)
    cc = check_mul(t3, t2d)
    dd = check_mul(z3, z22)
    e2 = sub1_bounds(bb, aa)
    f2 = sub1_bounds(dd, cc)
    g2 = add_c_bounds(dd, cc)
    h2 = add_c_bounds(bb, aa)
    for p, q in ((e2, f2), (g2, h2), (f2, g2), (e2, h2)):
        check_mul(p, q)

    # --- decompress shapes
    y = v(255)                            # byte input
    y2b = check_mul(y, y)
    u = sub1_bounds(y2b, v(1))
    vv = add_c_bounds(check_mul(v(255), y2b), v(1))
    vx2 = check_mul(vv, check_mul(M, M))
    sub1_bounds(vx2, u)                   # feeds to_canonical (loose ok)
    x_signed = sub1_bounds(v(0), v(255))
    neg_x = sub1_bounds(v(0), x_signed)
    check_mul(neg_x, y)


# --------------------------------- exactness at the interval extremes --

def _int_of(limbs):
    return sum(int(limbs[i]) << (8 * i) for i in range(32))


def test_rolled_mul_three_pass_exact_and_bounded():
    """The rolled form (TPU formulation, forced on CPU here) at the
    worst legal inputs: exact mod p and within the documented 711
    output bound."""
    import jax.numpy as jnp
    rng = np.random.default_rng(11)
    B = 16
    a = rng.integers(0, 1054, size=(32, B), dtype=np.int64).astype(np.int32)
    b = rng.integers(0, 1054, size=(32, B), dtype=np.int64).astype(np.int32)
    # include the all-max adversarial lane
    a[:, 0] = 1053
    b[:, 0] = 1053
    c = np.asarray(fe8._mul_rolled(jnp.asarray(a), jnp.asarray(b)))
    assert c.min() >= 0 and c.max() <= 711, (c.min(), c.max())
    for j in range(B):
        assert _int_of(c[:, j]) % fe8.P == \
            (_int_of(a[:, j]) * _int_of(b[:, j])) % fe8.P


def test_sub1_exact_and_bounded():
    import jax.numpy as jnp
    rng = np.random.default_rng(12)
    B = 16
    a = rng.integers(0, 1425, size=(32, B), dtype=np.int64).astype(np.int32)
    b = rng.integers(0, 712, size=(32, B), dtype=np.int64).astype(np.int32)
    a[:, 0] = 1424
    b[:, 0] = 711
    c = np.asarray(fe8.sub1(jnp.asarray(a), jnp.asarray(b)))
    assert c.min() >= 0 and c.max() <= 1053, (c.min(), c.max())
    for j in range(B):
        assert _int_of(c[:, j]) % fe8.P == \
            (_int_of(a[:, j]) - _int_of(b[:, j])) % fe8.P


def test_add_c_bounded():
    import jax.numpy as jnp
    a = np.full((32, 4), 711, dtype=np.int32)
    c = np.asarray(fe8.add_c(jnp.asarray(a), jnp.asarray(a)))
    assert c.max() <= 445, c.max()
    assert _int_of(c[:, 0]) % fe8.P == (2 * _int_of(a[:, 0])) % fe8.P
