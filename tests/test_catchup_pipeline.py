"""Streaming parallel catchup (catchup/pipeline.py): the pipelined
replay path is pinned byte-identical to the sequential reference, the
coalescer's padding math is exact, device prevalidation carries the
verifies, injected archive faults drain-and-resume deterministically, a
crash mid-apply resumes from the last committed ledger, and the
`trace_report --catchup` occupancy report proves stage overlap from a
real trace.
"""

import json
import os
import sys
import time

import pytest

from stellar_core_tpu.catchup import (CatchupConfiguration, CatchupWork,
                                      StreamingCatchupWork)
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.ops.verifier import prevalidate_coalesce
from stellar_core_tpu.util import chaos
from stellar_core_tpu.util.chaos import (ChaosEngine, FaultSpec,
                                         SimulatedCrash)
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.work import State, run_work_to_completion

import test_history_catchup as hc

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts"))
import trace_report  # noqa: E402


@pytest.fixture(autouse=True)
def _no_leftover_engine():
    """Every test starts and ends with chaos disabled."""
    chaos.uninstall()
    yield
    chaos.uninstall()


def _fresh_node(app_a, **cfg_overrides):
    cfg = get_test_config()
    cfg.NETWORK_PASSPHRASE = app_a.config.NETWORK_PASSPHRASE
    for k, v in cfg_overrides.items():
        setattr(cfg, k, v)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    return app


def _header_chain(app):
    return [(int(r[0]), bytes(r[1]), bytes(r[2]))
            for r in app.database.query_all(
                "SELECT ledgerseq, ledgerhash, data FROM ledgerheaders "
                "ORDER BY ledgerseq")]


# ----------------------------------------------------------- coalescing --

def test_prevalidate_coalesce_padding_math():
    # empty window: nothing to dispatch
    assert prevalidate_coalesce([], 4) == 0
    # 300+300: bucket(600)=1024 == bucket(300)+bucket(300): fusing
    # halves the dispatches at zero padding cost
    assert prevalidate_coalesce([300, 300], 4) == 2
    # 512+10: bucket(522)=1024 > 512+16: fusing pads the big bucket to
    # carry the tiny one — keep them separate
    assert prevalidate_coalesce([512, 10], 4) == 1
    # empty checkpoints fuse for free and don't break a fusion chain
    assert prevalidate_coalesce([300, 0, 300], 4) == 3
    assert prevalidate_coalesce([0, 0, 5], 4) == 3
    # the window cap bounds the fusion regardless of the math
    assert prevalidate_coalesce([5, 0, 0, 0, 0, 0], 3) == 3


# ---------------------------------------------------------- differential --

def test_pipeline_differential_vs_sequential(tmp_path):
    """The pinning test: pipelined catchup lands on a final state
    byte-identical to sequential catchup — same LCL, same hash, same
    full ledgerheaders chain (seq, hash, and header XDR per row)."""
    # three checkpoints (63, 127, 191): enough depth for the byte
    # budget to actually park admission behind a slow apply head
    app_a, archive, root = hc.make_publishing_app(tmp_path,
                                                  n_ledgers=200)
    try:
        app_seq = _fresh_node(app_a)
        try:
            work = CatchupWork(app_seq, archive,
                               CatchupConfiguration(to_ledger=0))
            assert run_work_to_completion(app_seq, work,
                                          timeout_virtual=3000) == \
                State.WORK_SUCCESS
            chain_seq = _header_chain(app_seq)
        finally:
            app_seq.shutdown()

        # small window + tight byte budget: the admission gate and
        # byte-budget backpressure both exercise without changing the
        # replayed bytes
        app_pipe = _fresh_node(
            app_a, CATCHUP_PIPELINE_AHEAD_CHECKPOINTS=2,
            CATCHUP_PIPELINE_BYTE_BUDGET=1)
        try:
            work = StreamingCatchupWork(app_pipe, archive,
                                        CatchupConfiguration(to_ledger=0))
            assert run_work_to_completion(app_pipe, work,
                                          timeout_virtual=3000) == \
                State.WORK_SUCCESS
            assert app_pipe.ledger_manager \
                .get_last_closed_ledger_num() == 191
            chain_pipe = _header_chain(app_pipe)
            report = work.stats.report()
        finally:
            app_pipe.shutdown()

        assert chain_pipe == chain_seq
        # stats carry the artifact's stage shape and saw every item
        assert set(report["stages"]) == {"download", "verify",
                                         "prevalidate", "apply"}
        assert report["stages"]["download"]["items"] == 3  # cp 63..191
        assert report["stages"]["verify"]["items"] == 3
        assert report["stages"]["apply"]["items"] == 190  # ledgers 2..191
        assert report["queues"]["bytes_hwm"] > 0
        # byte budget of 1 forces at least one admission stall episode
        assert report["queues"]["backpressure_stalls"] >= 1
    finally:
        app_a.shutdown()


# ------------------------------------------------- device prevalidation --

def test_pipeline_tpu_batch_prevalidation(tmp_path):
    """Coalesced device batches carry the replay's signature verifies:
    every checkpoint signature lands as a prevalidation hit, none fall
    through to the native path."""
    app_a, archive, root = hc.make_publishing_app(tmp_path)
    try:
        app_b = _fresh_node(app_a, SIGNATURE_VERIFY_BACKEND="tpu")
        try:
            # long batch_grace: deterministically observe the batch
            # results being consumed (production default is a 50ms
            # bounded stall with sync fallback)
            work = StreamingCatchupWork(app_b, archive,
                                        CatchupConfiguration(to_ledger=0),
                                        batch_grace=60.0)
            assert work.batch_verifier is not None
            assert run_work_to_completion(app_b, work,
                                          timeout_virtual=3000) == \
                State.WORK_SUCCESS
            assert app_b.ledger_manager \
                .get_last_closed_ledger_num() == 127
            assert work.batches, "no coalesced batch was dispatched"
            hits = sum(b.pv.hits for b in work.batches
                       if b.pv is not None)
            misses = sum(b.pv.misses for b in work.batches
                         if b.pv is not None)
            assert hits > 0
            assert misses == 0  # single-signer txs: all table hits
            assert not any(b.failed for b in work.batches)
        finally:
            app_b.shutdown()
    finally:
        app_a.shutdown()


# ----------------------------------------------------------------- chaos --

@pytest.mark.chaos
def test_pipeline_archive_io_error_drains_and_resumes(tmp_path):
    """Injected archive fetch faults mid-stream: the hit stage retries
    (GetRemoteFileWork's seeded backoff), the pipeline drains and
    resumes without wedging, the final chain is intact — and the whole
    fault schedule replays identically from the same seed."""
    app_a, archive, root = hc.make_publishing_app(tmp_path)
    try:
        hash_a = bytes(app_a.database.query_one(
            "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=127")[0])

        def one_run():
            eng = ChaosEngine(11, [FaultSpec(
                "history.get", "io_error", start=2, count=2)])
            chaos.install(eng)
            app_b = _fresh_node(app_a)
            try:
                work = StreamingCatchupWork(
                    app_b, archive, CatchupConfiguration(to_ledger=0))
                assert run_work_to_completion(app_b, work,
                                              timeout_virtual=3000) == \
                    State.WORK_SUCCESS
                assert app_b.ledger_manager \
                    .get_last_closed_ledger_num() == 127
                assert app_b.ledger_manager \
                    .get_last_closed_ledger_hash() == hash_a
            finally:
                chaos.uninstall()
                app_b.shutdown()
            return list(eng.log), dict(eng.injected)

        log1, injected1 = one_run()
        log2, injected2 = one_run()
        assert injected1["chaos.injected.io_error"] == 2
        # same seed, same schedule: the fault replay is deterministic
        assert log1 == log2
        assert injected1 == injected2
    finally:
        app_a.shutdown()


@pytest.mark.chaos
def test_pipeline_crash_mid_apply_resumes_from_committed(tmp_path):
    """`crash` at the catchup.apply seam mid-replay: the node dies
    between committed ledgers; a restart from the same DB + bucket dir
    resumes from the last committed ledger and a fresh streaming catchup
    completes to the identical chain."""
    app_a, archive, root = hc.make_publishing_app(tmp_path)
    try:
        hash_a = bytes(app_a.database.query_one(
            "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=127")[0])
        cfg = get_test_config()
        cfg.NETWORK_PASSPHRASE = app_a.config.NETWORK_PASSPHRASE
        cfg.DATABASE = f"sqlite3://{tmp_path}/node_b.db"
        cfg.BUCKET_DIR_PATH = str(tmp_path / "buckets_b")
        app_b = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                                   cfg)
        app_b.start()
        # fresh node replays 2..127; apply hit i is ledger 2+i, so
        # start=40 crashes entering ledger 42 with 41 committed
        chaos.install(ChaosEngine(8, [FaultSpec(
            "catchup.apply", "crash", start=40, count=1)]))
        crashed = False
        try:
            work = StreamingCatchupWork(app_b, archive,
                                        CatchupConfiguration(to_ledger=0))
            try:
                run_work_to_completion(app_b, work, timeout_virtual=3000)
            except SimulatedCrash:
                crashed = True
        finally:
            chaos.uninstall()
        assert crashed
        # abandon the crashed process image (no shutdown — a crash
        # doesn't get to run destructors); restart from the same files
        app_b2 = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                                    cfg)
        app_b2.start()
        try:
            assert app_b2.ledger_manager \
                .get_last_closed_ledger_num() == 41
            work = StreamingCatchupWork(app_b2, archive,
                                        CatchupConfiguration(to_ledger=0))
            assert run_work_to_completion(app_b2, work,
                                          timeout_virtual=3000) == \
                State.WORK_SUCCESS
            assert app_b2.ledger_manager \
                .get_last_closed_ledger_num() == 127
            assert app_b2.ledger_manager \
                .get_last_closed_ledger_hash() == hash_a
        finally:
            app_b2.shutdown()
    finally:
        app_a.shutdown()


# ---------------------------------------------------------- trace report --

def test_trace_report_catchup_occupancy(tmp_path):
    """`trace_report --catchup` over a real traced pipeline run: the
    stage table carries busy time for every stage, the device batches
    appear as dispatch/land intervals, and queue high-water marks come
    from the queue instants."""
    app_a, archive, root = hc.make_publishing_app(tmp_path)
    try:
        app_b = _fresh_node(app_a, SIGNATURE_VERIFY_BACKEND="tpu")
        app_b.flight_recorder.start()
        try:
            work = StreamingCatchupWork(app_b, archive,
                                        CatchupConfiguration(to_ledger=0),
                                        batch_grace=60.0)
            assert run_work_to_completion(app_b, work,
                                          timeout_virtual=3000) == \
                State.WORK_SUCCESS
            doc = app_b.flight_recorder.to_chrome_trace()
        finally:
            app_b.flight_recorder.stop()
            app_b.shutdown()
        path = str(tmp_path / "catchup_trace.json")
        with open(path, "w") as f:
            json.dump(doc, f)

        summary = trace_report.report_catchup(path)
        assert set(summary["stages"]) == {"download", "verify",
                                          "device", "apply"}
        assert summary["wall_ms"] > 0
        for stage in ("download", "verify", "apply"):
            assert summary["stages"][stage]["busy_ms"] > 0
            assert summary["stages"][stage]["items"] > 0
        # the device batches landed as paired dispatch/land instants
        assert summary["stages"]["device"]["items"] >= 1
        assert summary["queues"]["bytes_hwm"] > 0
        assert summary["queues"]["ready_hwm"] >= 1
        assert "device_idle" in summary
        assert summary["overlap"]["device_busy_while_download_ms"] >= 0
    finally:
        app_a.shutdown()
