"""Liquidity-pool routing through the exchange engine (protocol 18+).

Reference behaviors: OfferExchange convertWithOffersAndPools — path
payments route through whichever of the order book or the
constant-product pool gives the taker the strictly better price;
exchangeWithPool's exact fee/rounding math (30 bps, floor on the
strict-send payout, ceil on the strict-receive charge); the claimed
trail records a CLAIM_ATOM_TYPE_LIQUIDITY_POOL atom.
"""

import pytest

from stellar_core_tpu.tx.offer_exchange import (INT64_MAX, RoundingType,
                                                exchange_with_pool_amounts)
from stellar_core_tpu.xdr.ledger_entries import (
    AssetType, LiquidityPoolConstantProductParameters, Price)
from stellar_core_tpu.xdr.results import ClaimAtomType
from stellar_core_tpu.xdr.transaction import (ChangeTrustOp,
                                              ChangeTrustAsset,
                                              LiquidityPoolDepositOp,
                                              OperationType)

from test_dex_ops import _LPParams, setup_pool_trust
from txtest_utils import (TestAccount, TestLedger, _op, native,
                          op_change_trust, op_manage_sell_offer,
                          op_path_payment_strict_receive,
                          op_path_payment_strict_send, op_payment)

XLM = 10_000_000
FEE_BPS = 30


@pytest.fixture
def ledger():
    return TestLedger()


@pytest.fixture
def root(ledger):
    return ledger.root_account


# -------------------------------------------------------- pure swap math --

class TestPoolSwapMath:
    def test_strict_send_floor_and_fee(self):
        # independent model: from = floor((1-f) R_out x / (R_in + (1-f) x))
        r_in, r_out, x = 1_000_000, 2_000_000, 30_000
        got = exchange_with_pool_amounts(
            r_in, x, r_out, INT64_MAX,
            FEE_BPS, RoundingType.PATH_PAYMENT_STRICT_SEND)
        want = (9970 * r_out * x) // (10_000 * r_in + 9970 * x)
        assert got == (x, want)
        # pool invariant never decreases for the pool
        to_pool, from_pool = got
        assert (r_in + to_pool) * (r_out - from_pool) >= r_in * r_out

    def test_strict_receive_ceil(self):
        r_in, r_out, y = 5_000_000, 3_000_000, 10_000
        got = exchange_with_pool_amounts(
            r_in, INT64_MAX, r_out, y,
            FEE_BPS, RoundingType.PATH_PAYMENT_STRICT_RECEIVE)
        num = 10_000 * r_in * y
        den = (r_out - y) * 9970
        want = (num + den - 1) // den          # ceil: taker pays up
        assert got == (want, y)
        to_pool, from_pool = got
        assert (r_in + to_pool) * (r_out - from_pool) >= r_in * r_out

    def test_rejections(self):
        # receiving the whole reserve (or more) is impossible
        assert exchange_with_pool_amounts(
            10**6, INT64_MAX, 10**6, 10**6,
            FEE_BPS, RoundingType.PATH_PAYMENT_STRICT_RECEIVE) is None
        # dust send whose payout floors to zero
        assert exchange_with_pool_amounts(
            10**12, 1, 10, INT64_MAX,
            FEE_BPS, RoundingType.PATH_PAYMENT_STRICT_SEND) is None


# ------------------------------------------------------ ledger-level flow --

def _setup_pool(ledger, root, a_native=100 * XLM, b_usd=100 * XLM):
    """setup_pool_trust (shared with test_dex_ops) + a funded deposit."""
    issuer, usd, alice, pool_id = setup_pool_trust(ledger, root,
                                                   funded_usd=2_000 * XLM)
    assert alice.apply([_op(OperationType.LIQUIDITY_POOL_DEPOSIT,
                            LiquidityPoolDepositOp(
                                liquidityPoolID=pool_id,
                                maxAmountA=a_native, maxAmountB=b_usd,
                                minPrice=Price(n=1, d=100),
                                maxPrice=Price(n=100, d=1)))])
    return issuer, usd, alice, pool_id


def _reserves(ledger, pool_id):
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    from stellar_core_tpu.tx.pool_trust import load_pool
    with LedgerTxn(ledger.root) as ltx:
        cp = load_pool(ltx, pool_id).data.value.body.value
        return cp.reserveA, cp.reserveB


def _pp_result(frame):
    r = frame.result.result.value[0]
    while not hasattr(r, "offers"):
        r = r.value
    return r


class TestPathThroughPool:
    def test_strict_receive_via_pool_only(self, ledger, root):
        issuer, usd, alice, pool_id = _setup_pool(ledger, root)
        bob = TestAccount.fresh(ledger)
        root.create(bob, 1_000 * XLM)
        bob.sync_seq()
        assert bob.apply([op_change_trust(usd, 10**15)])
        ra0, rb0 = _reserves(ledger, pool_id)
        want_usd = 10 * XLM
        frame = bob.tx([op_path_payment_strict_receive(
            native(), 100 * XLM, bob.muxed, usd, want_usd)])
        assert ledger.apply_tx(frame), frame.result
        # trail records the pool atom, not an order-book claim
        succ = _pp_result(frame)
        atoms = list(succ.offers)
        assert len(atoms) == 1
        assert atoms[0].disc == \
            ClaimAtomType.CLAIM_ATOM_TYPE_LIQUIDITY_POOL
        atom = atoms[0].value
        assert atom.liquidityPoolID == pool_id
        assert atom.amountSold == want_usd
        # reserves moved by exactly the claimed amounts
        ra1, rb1 = _reserves(ledger, pool_id)
        assert ra1 - ra0 == atom.amountBought
        assert rb0 - rb1 == want_usd
        # constant product non-decreasing
        assert ra1 * rb1 >= ra0 * rb0
        # bob got the usd
        assert ledger.trustline(bob.account_id, usd).balance == want_usd

    def test_strict_send_via_pool_only(self, ledger, root):
        issuer, usd, alice, pool_id = _setup_pool(ledger, root)
        bob = TestAccount.fresh(ledger)
        root.create(bob, 1_000 * XLM)
        bob.sync_seq()
        assert bob.apply([op_change_trust(usd, 10**15)])
        ra0, rb0 = _reserves(ledger, pool_id)
        send = 5 * XLM
        frame = bob.tx([op_path_payment_strict_send(
            native(), send, bob.muxed, usd, 1)])
        assert ledger.apply_tx(frame), frame.result
        ra1, rb1 = _reserves(ledger, pool_id)
        assert ra1 - ra0 == send
        # payout matches the closed-form floor
        want = (9970 * rb0 * send) // (10_000 * ra0 + 9970 * send)
        assert rb0 - rb1 == want
        assert ledger.trustline(bob.account_id, usd).balance == want

    def test_book_beats_pool_when_strictly_better(self, ledger, root):
        issuer, usd, alice, pool_id = _setup_pool(ledger, root)
        # alice offers usd at a price strictly better than the pool spot
        # (pool is ~1:1; sell 50 usd at 0.5 XLM each)
        assert alice.apply([op_manage_sell_offer(
            usd, native(), 50 * XLM, Price(n=1, d=2))])
        bob = TestAccount.fresh(ledger)
        root.create(bob, 1_000 * XLM)
        bob.sync_seq()
        assert bob.apply([op_change_trust(usd, 10**15)])
        ra0, rb0 = _reserves(ledger, pool_id)
        frame = bob.tx([op_path_payment_strict_receive(
            native(), 100 * XLM, bob.muxed, usd, 10 * XLM)])
        assert ledger.apply_tx(frame), frame.result
        atoms = list(_pp_result(frame).offers)
        assert atoms and all(
            a.disc == ClaimAtomType.CLAIM_ATOM_TYPE_ORDER_BOOK
            for a in atoms)
        # the pool was untouched
        assert _reserves(ledger, pool_id) == (ra0, rb0)

    def test_pool_beats_worse_book(self, ledger, root):
        issuer, usd, alice, pool_id = _setup_pool(ledger, root)
        # alice's offer is much worse than the pool (2 XLM per usd)
        assert alice.apply([op_manage_sell_offer(
            usd, native(), 50 * XLM, Price(n=2, d=1))])
        bob = TestAccount.fresh(ledger)
        root.create(bob, 1_000 * XLM)
        bob.sync_seq()
        assert bob.apply([op_change_trust(usd, 10**15)])
        ra0, rb0 = _reserves(ledger, pool_id)
        frame = bob.tx([op_path_payment_strict_receive(
            native(), 100 * XLM, bob.muxed, usd, 10 * XLM)])
        assert ledger.apply_tx(frame), frame.result
        atoms = list(_pp_result(frame).offers)
        assert [a.disc for a in atoms] == \
            [ClaimAtomType.CLAIM_ATOM_TYPE_LIQUIDITY_POOL]
        assert _reserves(ledger, pool_id) != (ra0, rb0)


class TestPoolDisableFlags:
    """The voted LEDGER_UPGRADE_FLAGS bits (reference: isPoolTradingDisabled
    + the LiquidityPool*OpFrame::isOpSupported checks)."""

    def _set_flags(self, ledger, flags):
        from stellar_core_tpu.xdr.ledger import (LedgerHeaderExtensionV1,
                                                 _LedgerHeaderExt)
        from stellar_core_tpu.xdr.types import ExtensionPoint
        ledger.root._header.ext = _LedgerHeaderExt(
            1, LedgerHeaderExtensionV1(flags=flags, ext=ExtensionPoint(0)))

    def test_trading_disabled_skips_pool(self, ledger, root):
        from stellar_core_tpu.xdr.ledger import LedgerHeaderFlags
        issuer, usd, alice, pool_id = _setup_pool(ledger, root)
        bob = TestAccount.fresh(ledger)
        root.create(bob, 1_000 * XLM)
        bob.sync_seq()
        assert bob.apply([op_change_trust(usd, 10**15)])
        self._set_flags(
            ledger, LedgerHeaderFlags.DISABLE_LIQUIDITY_POOL_TRADING_FLAG)
        ra0, rb0 = _reserves(ledger, pool_id)
        # no offers exist and the pool is off-limits: too few offers
        frame = bob.tx([op_path_payment_strict_receive(
            native(), 100 * XLM, bob.muxed, usd, 10 * XLM)])
        assert not ledger.apply_tx(frame)
        assert _reserves(ledger, pool_id) == (ra0, rb0)
        # clearing the flag restores routing
        self._set_flags(ledger, 0)
        frame = bob.tx([op_path_payment_strict_receive(
            native(), 100 * XLM, bob.muxed, usd, 10 * XLM)])
        assert ledger.apply_tx(frame), frame.result

    def test_deposit_and_withdraw_disabled(self, ledger, root):
        from stellar_core_tpu.xdr.ledger import LedgerHeaderFlags
        from stellar_core_tpu.xdr.results import OperationResultCode
        issuer, usd, alice, pool_id = _setup_pool(ledger, root)
        self._set_flags(
            ledger, LedgerHeaderFlags.DISABLE_LIQUIDITY_POOL_DEPOSIT_FLAG
            | LedgerHeaderFlags.DISABLE_LIQUIDITY_POOL_WITHDRAWAL_FLAG)
        dep = _op(OperationType.LIQUIDITY_POOL_DEPOSIT,
                  LiquidityPoolDepositOp(
                      liquidityPoolID=pool_id,
                      maxAmountA=XLM, maxAmountB=XLM,
                      minPrice=Price(n=1, d=100), maxPrice=Price(n=100, d=1)))
        frame = alice.tx([dep])
        assert not ledger.apply_tx(frame)
        assert frame.result.result.value[0].disc == \
            OperationResultCode.opNOT_SUPPORTED
        from stellar_core_tpu.xdr.transaction import LiquidityPoolWithdrawOp
        wd = _op(OperationType.LIQUIDITY_POOL_WITHDRAW,
                 LiquidityPoolWithdrawOp(
                     liquidityPoolID=pool_id, amount=1,
                     minAmountA=0, minAmountB=0))
        frame = alice.tx([wd])
        assert not ledger.apply_tx(frame)
        assert frame.result.result.value[0].disc == \
            OperationResultCode.opNOT_SUPPORTED
