"""Fee-bump transaction tier (reference: FeeBumpTransactionTests.cpp /
FeeBumpTransactionFrame.cpp): the outer fee source pays and signs for
the bump; the inner tx executes with its own auth and seqnum; the outer
result embeds the inner result pair. Pins: fee accounting split between
the two sources, the fee-per-op bid rule against the inner fee,
txFEE_BUMP_INNER_FAILED with fee still charged, inner seq consumption
on inner failure, and outer auth/balance rejections.
"""

import pytest

from stellar_core_tpu.tx.frame import make_frame
from stellar_core_tpu.xdr.results import TransactionResultCode
from stellar_core_tpu.xdr.transaction import (DecoratedSignature,
                                              FeeBumpTransaction,
                                              FeeBumpTransactionEnvelope,
                                              TransactionEnvelope,
                                              _FeeBumpInnerTx, _TxExt)
from stellar_core_tpu.xdr.types import EnvelopeType

from txtest_utils import (TEST_NETWORK_ID, TestAccount, TestLedger,
                          op_payment)

XLM = 10_000_000


@pytest.fixture
def ledger():
    return TestLedger()


@pytest.fixture
def root(ledger):
    return ledger.root_account


def tx_code(frame):
    return frame.result.result.disc


def _mk(ledger, root):
    a = TestAccount.fresh(ledger)
    b = TestAccount.fresh(ledger)
    payer = TestAccount.fresh(ledger)
    assert root.create(a, 100 * XLM)
    assert root.create(b, 100 * XLM)
    assert root.create(payer, 100 * XLM)
    a.sync_seq()
    payer.sync_seq()
    return a, b, payer


def bump(inner_frame, payer, fee, sign=True):
    """Wrap an inner v1 frame in a fee-bump envelope signed by payer."""
    fb = FeeBumpTransaction(
        feeSource=payer.muxed, fee=fee,
        innerTx=_FeeBumpInnerTx(EnvelopeType.ENVELOPE_TYPE_TX,
                                inner_frame.envelope.value),
        ext=_TxExt(0))
    env = FeeBumpTransactionEnvelope(tx=fb, signatures=[])
    outer = TransactionEnvelope(
        EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP, env)
    frame = make_frame(outer, TEST_NETWORK_ID)
    if sign:
        sig = payer.key.sign(frame.contents_hash())
        env.signatures = [DecoratedSignature(
            hint=payer.key.public_key().hint(), signature=sig)]
        frame.signatures = env.signatures
    return frame


class TestFeeBump:
    def test_payer_pays_inner_source_does_not(self, ledger, root):
        a, b, payer = _mk(ledger, root)
        inner = a.tx([op_payment(b.muxed, XLM)])
        frame = bump(inner, payer, 400)
        a_before = ledger.balance(a.account_id)
        p_before = ledger.balance(payer.account_id)
        assert ledger.apply_tx(frame), frame.result
        assert tx_code(frame) == \
            TransactionResultCode.txFEE_BUMP_INNER_SUCCESS
        # payer covered the whole CHARGED fee — min(bid 400, baseFee
        # 100 x 2 ops) = 200 (reference getFee applying branch); a paid
        # only the payment amount
        charged = frame.result.feeCharged
        assert charged == 200
        assert p_before - ledger.balance(payer.account_id) == charged
        assert a_before - ledger.balance(a.account_id) == XLM
        # the embedded pair carries the INNER contents hash
        pair = frame.result.result.value
        assert pair.transactionHash == frame.inner.contents_hash()
        # and the inner seq was consumed
        assert ledger.account(a.account_id).seqNum == inner.seq_num

    def test_fee_must_cover_inner_plus_one_op(self, ledger, root):
        a, b, payer = _mk(ledger, root)
        inner = a.tx([op_payment(b.muxed, XLM)])     # 1 op
        # num_operations = inner + 1 = 2; fee 150 < 2 * baseFee(100)
        frame = bump(inner, payer, 150)
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txINSUFFICIENT_FEE

    def test_bump_bid_must_beat_inner_bid(self, ledger, root):
        """fee-per-op of the bump must be >= the inner tx's bid
        (reference: FeeBumpTransactionFrame::checkValid)."""
        a, b, payer = _mk(ledger, root)
        inner = a.tx([op_payment(b.muxed, XLM)], fee=1000)  # high bid
        # 2 ops at 400 -> 200/op < inner's 1000/op
        frame = bump(inner, payer, 400)
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txINSUFFICIENT_FEE
        # 2000/2 = 1000/op matches the inner bid: valid
        frame = bump(inner, payer, 2000)
        assert ledger.check_valid(frame), frame.result

    def test_unsigned_outer_is_bad_auth(self, ledger, root):
        a, b, payer = _mk(ledger, root)
        inner = a.tx([op_payment(b.muxed, XLM)])
        frame = bump(inner, payer, 400, sign=False)
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txBAD_AUTH

    def test_inner_failure_charges_fee_and_consumes_seq(self, ledger,
                                                        root):
        a, b, payer = _mk(ledger, root)
        inner = a.tx([op_payment(b.muxed, 10_000 * XLM)])   # overdraw
        frame = bump(inner, payer, 400)
        p_before = ledger.balance(payer.account_id)
        assert not ledger.apply_tx(frame)
        assert tx_code(frame) == \
            TransactionResultCode.txFEE_BUMP_INNER_FAILED
        # fee still charged to the payer, inner seq still consumed
        assert p_before - ledger.balance(payer.account_id) == \
            frame.result.feeCharged == 200
        assert ledger.account(a.account_id).seqNum == inner.seq_num
        # the inner pair records the inner failure
        pair = frame.result.result.value
        assert pair.result.result.disc == TransactionResultCode.txFAILED

    def test_inner_bad_signature_fails_the_bump(self, ledger, root):
        a, b, payer = _mk(ledger, root)
        inner = a.tx([op_payment(b.muxed, XLM)])
        inner.signatures.clear()        # inner has NO valid signatures
        inner.envelope.value.signatures = inner.signatures
        frame = bump(inner, payer, 400)
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == \
            TransactionResultCode.txFEE_BUMP_INNER_FAILED

    def test_broke_payer_rejected(self, ledger, root):
        a, b, _ = _mk(ledger, root)
        poor = TestAccount.fresh(ledger)
        # just the base reserves: no available balance for a fee
        assert root.create(poor, 2 * 5_000_000)
        inner = a.tx([op_payment(b.muxed, XLM)])
        frame = bump(inner, poor, 400)
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == \
            TransactionResultCode.txINSUFFICIENT_BALANCE
