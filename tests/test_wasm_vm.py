"""Wasm VM unit tests: binary round-trip, validation rejections,
numeric/control/memory semantics, deterministic traps, fuel metering.

Behavior model: the WebAssembly core spec's integer subset, with the
deterministic-profile restrictions the Soroban host imposes (floats
rejected), mirroring how the reference executes contracts through Wasmi
(reference: src/rust/src/contract.rs:261-340 + soroban-env-host's
wasmi config; test shape mirrors wasmi's spec-suite usage)."""

import pytest

from stellar_core_tpu.soroban.wasm import (HostFunc, I32, I64, Instance,
                                           ModuleBuilder, WasmFormatError,
                                           WasmTrap, WasmValidationError,
                                           decode_module, validate_module)
from stellar_core_tpu.soroban.wasm.module import BLOCK_EMPTY, encode_module


def run1(build, name="f", args=(), imports=None, meter=None):
    """Build, encode, decode, validate, instantiate, invoke: the full
    production path for a one-function module."""
    b = ModuleBuilder()
    build(b)
    raw = b.encode()
    m = decode_module(raw)
    validate_module(m)
    inst = Instance(m, imports=imports, meter=meter)
    return inst.invoke(name, list(args))


def unary64(emit):
    """Module computing f(x:i64)->i64 with `emit` writing the body."""
    def build(b):
        fidx, f = b.add_func([I64], [I64])
        f.local_get(0)
        emit(f)
        b.export_func("f", fidx)
    return build


def binop64(op):
    def build(b):
        fidx, f = b.add_func([I64, I64], [I64])
        f.local_get(0)
        f.local_get(1)
        f.op(op)
        b.export_func("f", fidx)
    return build


def binop32(op):
    def build(b):
        fidx, f = b.add_func([I32, I32], [I32])
        f.local_get(0)
        f.local_get(1)
        f.op(op)
        b.export_func("f", fidx)
    return build


# ---------------------------------------------------------------- binary ---
def test_roundtrip_encode_decode():
    b = ModuleBuilder()
    b.add_memory(1, 2)
    b.add_table(4)
    g = b.add_global(I64, True, 7)
    fidx, f = b.add_func([I64], [I64], locals_=[I64, I32])
    f.local_get(0)
    f.global_get(g)
    f.op(0x7C)
    b.export_func("f", fidx)
    b.add_element(0, [fidx])
    b.add_data(8, b"hello")
    raw = b.encode()
    m = decode_module(raw)
    validate_module(m)
    # re-encode the decoded module: must be byte-identical (canonical)
    assert encode_module(m) == raw
    inst = Instance(m)
    assert inst.invoke("f", [35]) == [42]
    assert inst.memory[8:13] == b"hello"


def test_bad_magic_and_truncation():
    with pytest.raises(WasmFormatError):
        decode_module(b"\x00asmX\x00\x00\x00")
    with pytest.raises(WasmFormatError):
        decode_module(b"\x01asm\x01\x00\x00\x00")
    b = ModuleBuilder()
    fidx, f = b.add_func([], [I32])
    f.i32_const(1)
    b.export_func("f", fidx)
    raw = b.encode()
    for cut in (9, len(raw) // 2, len(raw) - 1):
        with pytest.raises(WasmFormatError):
            decode_module(raw[:cut])


def test_unknown_opcode_rejected():
    # hand-build a body with opcode 0xD0 (ref.null — not in MVP profile)
    b = ModuleBuilder()
    fidx, f = b.add_func([], [])
    b.export_func("f", fidx)
    raw = bytearray(b.encode())
    idx = raw.rfind(bytes([0x0B]))          # final end opcode
    raw[idx:idx] = bytes([0xD0])
    # code-section / body sizes grew by 1
    # easier: rebuild via the builder's raw op
    b2 = ModuleBuilder()
    fidx, f = b2.add_func([], [])
    f.op(0xD0)
    b2.export_func("f", fidx)
    with pytest.raises(WasmFormatError):
        decode_module(b2.encode())


def test_truncated_blocktype_rejected():
    """A module whose last byte is a block opcode must raise
    WasmFormatError, not IndexError (hostile-input totality)."""
    b = ModuleBuilder()
    fidx, f = b.add_func([], [])
    b.export_func("f", fidx)
    raw = bytearray(b.encode())
    # body is "0b" (just end); replace with a bare block opcode and
    # let the section end right there
    idx = raw.rfind(bytes([0x0B]))
    raw[idx] = 0x02                          # block, missing blocktype
    with pytest.raises(WasmFormatError):
        decode_module(bytes(raw))


def test_block_params_rejected():
    """Type-index blocktypes with parameters are outside the MVP arity
    profile and must be rejected at validation (the interpreter's label
    heights assume empty block params)."""
    b = ModuleBuilder()
    bt = b.functype([I64], [I64])
    fidx, f = b.add_func([], [I64])
    f.i64_const(7)
    f.block(bt)
    f.end()
    b.export_func("f", fidx)
    with pytest.raises(WasmValidationError, match="block parameters"):
        validate_module(decode_module(b.encode()))


def test_huge_align_rejected_cheaply():
    """align is compared by exponent — a 2^32 alignment must fail fast
    without materializing a half-GB bignum."""
    import time
    b = ModuleBuilder()
    b.add_memory(1)
    fidx, f = b.add_func([], [I64])
    f.i32_const(0)
    f.load(0x29, offset=0, align=0xFFFFFFF0)
    b.export_func("f", fidx)
    raw = b.encode()
    t0 = time.monotonic()
    with pytest.raises(WasmValidationError, match="alignment"):
        validate_module(decode_module(raw))
    assert time.monotonic() - t0 < 0.05


def test_global_init_type_mismatch_rejected():
    """An i32 global initialized by i64.const must be rejected at
    decode, not silently produce an out-of-range i32."""
    from stellar_core_tpu.soroban.wasm.module import Global, I64_CONST
    b = ModuleBuilder()
    b.add_global(I32, False, 5)
    raw = bytearray(b.encode())
    # global section payload: 7f 00 41 05 0b → swap const opcode to 0x42
    i = raw.find(bytes([0x7F, 0x00, 0x41, 0x05, 0x0B]))
    assert i > 0
    raw[i + 2] = 0x42                        # i64.const
    with pytest.raises(WasmFormatError, match="type mismatch"):
        decode_module(bytes(raw))


def test_duplicate_export_rejected():
    b = ModuleBuilder()
    fidx, f = b.add_func([], [])
    b.export_func("f", fidx)
    b.export_func("f", fidx)
    with pytest.raises(WasmFormatError):
        decode_module(b.encode())


# ------------------------------------------------------------ validation ---
def test_float_code_rejected():
    b = ModuleBuilder()
    fidx, f = b.add_func([], [I32])
    f.op(0x43, b"\x00\x00\x80\x3f")         # f32.const 1.0
    b.export_func("f", fidx)
    m = decode_module(b.encode())
    with pytest.raises(WasmValidationError, match="float"):
        validate_module(m)


def test_float_type_rejected():
    b = ModuleBuilder()
    b.functype([0x7D], [])                  # f32 param
    m = decode_module(b.encode())
    with pytest.raises(WasmValidationError, match="float"):
        validate_module(m)


def test_type_mismatch_rejected():
    b = ModuleBuilder()
    fidx, f = b.add_func([], [I64])
    f.i32_const(1)                          # i32 where i64 expected
    b.export_func("f", fidx)
    with pytest.raises(WasmValidationError, match="type mismatch"):
        validate_module(decode_module(b.encode()))


def test_stack_underflow_rejected():
    b = ModuleBuilder()
    fidx, f = b.add_func([], [])
    f.drop()
    b.export_func("f", fidx)
    with pytest.raises(WasmValidationError, match="underflow"):
        validate_module(decode_module(b.encode()))


def test_unknown_local_and_call_rejected():
    b = ModuleBuilder()
    fidx, f = b.add_func([], [])
    f.local_get(3)
    with pytest.raises(WasmValidationError, match="local"):
        validate_module(decode_module(b.encode()))
    b2 = ModuleBuilder()
    fidx, f = b2.add_func([], [])
    f.call(9)
    with pytest.raises(WasmValidationError, match="unknown function"):
        validate_module(decode_module(b2.encode()))


def test_branch_depth_rejected():
    b = ModuleBuilder()
    fidx, f = b.add_func([], [])
    f.br(2)
    with pytest.raises(WasmValidationError, match="depth"):
        validate_module(decode_module(b.encode()))


def test_if_without_else_needing_value_rejected():
    b = ModuleBuilder()
    fidx, f = b.add_func([], [I64])
    f.i32_const(1)
    f.if_(I64)
    f.i64_const(5)
    f.end()
    b.export_func("f", fidx)
    with pytest.raises(WasmValidationError):
        validate_module(decode_module(b.encode()))


def test_memory_cap_enforced():
    b = ModuleBuilder()
    b.add_memory(100000)
    with pytest.raises(WasmValidationError, match="cap"):
        validate_module(decode_module(b.encode()))


def test_values_left_on_stack_rejected():
    b = ModuleBuilder()
    fidx, f = b.add_func([], [])
    f.i64_const(1)
    b.export_func("f", fidx)
    with pytest.raises(WasmValidationError):
        validate_module(decode_module(b.encode()))


# --------------------------------------------------------------- numeric ---
@pytest.mark.parametrize("op,a,b,expect", [
    (0x7C, 2**64 - 1, 1, 0),                        # i64.add wraps
    (0x7D, 0, 1, 2**64 - 1),                        # i64.sub wraps
    (0x7E, 2**32, 2**32, 0),                        # i64.mul wraps
    (0x80, 2**64 - 1, 10, (2**64 - 1) // 10),       # div_u
    (0x7F, (-7) & (2**64 - 1), 2, (-3) & (2**64 - 1)),   # div_s truncates
    (0x81, (-7) & (2**64 - 1), 3, (-1) & (2**64 - 1)),   # rem_s sign
    (0x82, 7, 3, 1),                                # rem_u
    (0x86, 1, 65, 2),                               # shl masks count
    (0x88, 2**63, 63, 1),                           # shr_u
    (0x87, 2**63, 1, 0xC000000000000000),           # shr_s arithmetic
    (0x89, 2**63, 1, 1),                            # rotl
    (0x8A, 1, 1, 2**63),                            # rotr
])
def test_i64_binops(op, a, b, expect):
    assert run1(binop64(op), args=[a, b]) == [expect]


@pytest.mark.parametrize("op,a,b,expect", [
    (0x6A, 2**32 - 1, 1, 0),                        # i32.add wraps
    (0x6D, (-8) & 0xFFFFFFFF, 2, (-4) & 0xFFFFFFFF),     # div_s
    (0x6F, (-8) & 0xFFFFFFFF, 3, (-2) & 0xFFFFFFFF),     # rem_s
    (0x74, 1, 33, 2),                               # shl masks
    (0x48, 5, (-1) & 0xFFFFFFFF, 0),                # lt_s: 5 < -1 false
    (0x49, 5, (-1) & 0xFFFFFFFF, 1),                # lt_u: 5 < huge true
])
def test_i32_binops(op, a, b, expect):
    assert run1(binop32(op), args=[a, b]) == [expect]


@pytest.mark.parametrize("op,args", [
    (0x7F, [1, 0]), (0x80, [1, 0]), (0x81, [1, 0]), (0x82, [1, 0]),
])
def test_i64_div_by_zero_traps(op, args):
    with pytest.raises(WasmTrap, match="div0"):
        run1(binop64(op), args=args)


def test_div_s_overflow_traps():
    imin = 1 << 63
    with pytest.raises(WasmTrap, match="overflow"):
        run1(binop64(0x7F), args=[imin, (-1) & (2**64 - 1)])
    # but INT_MIN rem -1 == 0, no trap (spec)
    assert run1(binop64(0x81), args=[imin, (-1) & (2**64 - 1)]) == [0]


def test_clz_ctz_popcnt_and_extends():
    assert run1(unary64(lambda f: f.op(0x79)), args=[0]) == [64]  # clz(0)
    assert run1(unary64(lambda f: f.op(0x79)), args=[1]) == [63]
    assert run1(unary64(lambda f: f.op(0x7A)), args=[8]) == [3]
    assert run1(unary64(lambda f: f.op(0x7A)), args=[0]) == [64]  # ctz(0)
    assert run1(unary64(lambda f: f.op(0x7B)),
                args=[0xFF00FF]) == [16]                      # popcnt
    # i64.extend8_s
    assert run1(unary64(lambda f: f.op(0xC2)),
                args=[0x80]) == [(-128) & (2**64 - 1)]
    # i64.extend32_s
    assert run1(unary64(lambda f: f.op(0xC4)),
                args=[0x80000000]) == [(-2**31) & (2**64 - 1)]


def test_wrap_and_extend():
    def build(b):
        fidx, f = b.add_func([I64], [I64])
        f.local_get(0)
        f.op(0xA7)          # i32.wrap_i64
        f.op(0xAC)          # i64.extend_i32_s
        b.export_func("f", fidx)
    assert run1(build, args=[0x1_FFFFFFFF]) == [(2**64 - 1)]  # -1


# ---------------------------------------------------------- control flow ---
def test_br_table():
    def build(b):
        fidx, f = b.add_func([I32], [I64])
        f.block(I64)
        f.block()
        f.block()
        f.block()
        f.local_get(0)
        f.br_table([0, 1, 2], 2)
        f.end()
        f.i64_const(100)
        f.br(2)
        f.end()
        f.i64_const(200)
        f.br(1)
        f.end()
        f.i64_const(300)
        f.end()
        b.export_func("f", fidx)
    assert run1(build, args=[0]) == [100]
    assert run1(build, args=[1]) == [200]
    assert run1(build, args=[2]) == [300]
    assert run1(build, args=[77]) == [300]   # default


def test_nested_loop_sum():
    # sum of i*j for i,j in [0,n): two nested loops
    def build(b):
        fidx, f = b.add_func([I64], [I64], locals_=[I64] * 3)
        # locals: 1=i 2=j 3=acc
        f.block()
        f.loop()
        f.local_get(1)
        f.local_get(0)
        f.op(0x5A)          # i >= n
        f.br_if(1)
        f.i64_const(0)
        f.local_set(2)
        f.block()
        f.loop()
        f.local_get(2)
        f.local_get(0)
        f.op(0x5A)
        f.br_if(1)
        f.local_get(3)
        f.local_get(1)
        f.local_get(2)
        f.op(0x7E)
        f.op(0x7C)
        f.local_set(3)
        f.local_get(2)
        f.i64_const(1)
        f.op(0x7C)
        f.local_set(2)
        f.br(0)
        f.end()
        f.end()
        f.local_get(1)
        f.i64_const(1)
        f.op(0x7C)
        f.local_set(1)
        f.br(0)
        f.end()
        f.end()
        f.local_get(3)
        b.export_func("f", fidx)
    n = 10
    expect = sum(i * j for i in range(n) for j in range(n))
    assert run1(build, args=[n]) == [expect]


def test_if_else_and_select():
    def build(b):
        fidx, f = b.add_func([I32], [I64])
        f.local_get(0)
        f.if_(I64)
        f.i64_const(10)
        f.else_()
        f.i64_const(20)
        f.end()
        b.export_func("f", fidx)
    assert run1(build, args=[1]) == [10]
    assert run1(build, args=[0]) == [20]

    def build2(b):
        fidx, f = b.add_func([I32], [I64])
        f.i64_const(10)
        f.i64_const(20)
        f.local_get(0)
        f.select()
        b.export_func("f", fidx)
    assert run1(build2, args=[1]) == [10]
    assert run1(build2, args=[0]) == [20]


def test_early_return_and_unreachable():
    def build(b):
        fidx, f = b.add_func([I32], [I64])
        f.local_get(0)
        f.if_(BLOCK_EMPTY)
        f.i64_const(1)
        f.ret()
        f.end()
        f.i64_const(2)
        b.export_func("f", fidx)
    assert run1(build, args=[1]) == [1]
    assert run1(build, args=[0]) == [2]

    def build2(b):
        fidx, f = b.add_func([], [])
        f.unreachable()
        b.export_func("f", fidx)
    with pytest.raises(WasmTrap, match="unreachable"):
        run1(build2)


def test_recursion_and_depth_limit():
    # f(n) = n == 0 ? 0 : f(n-1) + n  (triangular numbers via recursion)
    def build(b):
        fidx, f = b.add_func([I64], [I64])
        f.local_get(0)
        f.op(0x50)          # i64.eqz
        f.if_(I64)
        f.i64_const(0)
        f.else_()
        f.local_get(0)
        f.i64_const(1)
        f.op(0x7D)
        f.call(fidx)
        f.local_get(0)
        f.op(0x7C)
        f.end()
        b.export_func("f", fidx)
    assert run1(build, args=[10]) == [55]
    with pytest.raises(WasmTrap, match="stack"):
        run1(build, args=[100000])


def test_call_indirect():
    def build(b):
        add_t = b.functype([I64, I64], [I64])
        a_idx, fa = b.add_func([I64, I64], [I64])
        fa.local_get(0)
        fa.local_get(1)
        fa.op(0x7C)
        s_idx, fs = b.add_func([I64, I64], [I64])
        fs.local_get(0)
        fs.local_get(1)
        fs.op(0x7D)
        b.add_table(2)
        b.add_element(0, [a_idx, s_idx])
        fidx, f = b.add_func([I32, I64, I64], [I64])
        f.local_get(1)
        f.local_get(2)
        f.local_get(0)
        f.call_indirect(add_t)
        b.export_func("f", fidx)
    assert run1(build, args=[0, 30, 12]) == [42]
    assert run1(build, args=[1, 30, 12]) == [18]
    with pytest.raises(WasmTrap, match="indirect"):
        run1(build, args=[5, 1, 1])          # out of table bounds


def test_call_indirect_type_mismatch_traps():
    def build(b):
        other_t = b.functype([I64], [I64])
        a_idx, fa = b.add_func([I64, I64], [I64])
        fa.local_get(0)
        fa.local_get(1)
        fa.op(0x7C)
        b.add_table(1)
        b.add_element(0, [a_idx])
        fidx, f = b.add_func([], [I64])
        f.i64_const(1)
        f.i32_const(0)
        f.call_indirect(other_t)
        b.export_func("f", fidx)
    with pytest.raises(WasmTrap, match="signature"):
        run1(build)


# ----------------------------------------------------------------- memory ---
def test_memory_load_store_endianness():
    def build(b):
        b.add_memory(1)
        fidx, f = b.add_func([], [I64])
        f.i32_const(16)
        f.i64_const(0x0102030405060708)
        f.store(0x37)                    # i64.store
        f.i32_const(16)
        f.load(0x2D)                     # i32.load8_u → LSB first
        f.op(0xAD)
        b.export_func("f", fidx)
    assert run1(build) == [0x08]         # little-endian


def test_memory_oob_traps():
    def build(b):
        b.add_memory(1)
        fidx, f = b.add_func([I32], [I64])
        f.local_get(0)
        f.load(0x29)                     # i64.load
        b.export_func("f", fidx)
    assert run1(build, args=[0]) == [0]
    with pytest.raises(WasmTrap, match="oob"):
        run1(build, args=[65536 - 7])
    # offset overflow also traps
    def build2(b):
        b.add_memory(1)
        fidx, f = b.add_func([], [I64])
        f.i32_const(65535)
        f.load(0x29, offset=65535)
        b.export_func("f", fidx)
    with pytest.raises(WasmTrap, match="oob"):
        run1(build2)


def test_memory_size_and_grow():
    def build(b):
        b.add_memory(1, 3)
        fidx, f = b.add_func([], [I32])
        f.i32_const(1)
        f.memory_grow()
        f.drop()
        f.memory_size()
        b.export_func("f", fidx)
    assert run1(build) == [2]

    def build2(b):
        b.add_memory(1, 2)
        fidx, f = b.add_func([], [I32])
        f.i32_const(5)
        f.memory_grow()                  # over max → -1
        b.export_func("f", fidx)
    assert run1(build2) == [0xFFFFFFFF]


def test_signextending_loads():
    def build(b):
        b.add_memory(1)
        fidx, f = b.add_func([], [I64])
        f.i32_const(0)
        f.i64_const(0xFF)
        f.store(0x3C)                    # i64.store8
        f.i32_const(0)
        f.load(0x30)                     # i64.load8_s
        b.export_func("f", fidx)
    assert run1(build) == [(-1) & (2**64 - 1)]


# ------------------------------------------------------- globals & start ---
def test_globals_and_start():
    b = ModuleBuilder()
    g = b.add_global(I64, True, 5)
    sidx, sf = b.add_func([], [])
    sf.global_get(g)
    sf.i64_const(2)
    sf.op(0x7E)
    sf.global_set(g)
    b.set_start(sidx)
    fidx, f = b.add_func([], [I64])
    f.global_get(g)
    b.export_func("f", fidx)
    m = decode_module(b.encode())
    validate_module(m)
    inst = Instance(m)                   # start ran at instantiation
    assert inst.invoke("f", []) == [10]


def test_immutable_global_set_rejected():
    b = ModuleBuilder()
    g = b.add_global(I64, False, 5)
    fidx, f = b.add_func([], [])
    f.i64_const(1)
    f.global_set(g)
    b.export_func("f", fidx)
    with pytest.raises(WasmValidationError, match="immutable"):
        validate_module(decode_module(b.encode()))


# ------------------------------------------------------- host functions ---
def test_host_function_roundtrip():
    calls = []

    def log(inst, v):
        calls.append(v)
        return v * 2

    imports = {("env", "log"): HostFunc([I64], [I64], log)}

    def build(b):
        imp = b.import_func("env", "log", [I64], [I64])
        fidx, f = b.add_func([I64], [I64])
        f.local_get(0)
        f.call(imp)
        b.export_func("f", fidx)
    assert run1(build, args=[21], imports=imports) == [42]
    assert calls == [21]


def test_missing_and_mismatched_import():
    def build(b):
        b.import_func("env", "log", [I64], [I64])
        fidx, f = b.add_func([], [])
        b.export_func("f", fidx)
    with pytest.raises(WasmTrap, match="link"):
        run1(build, imports={})
    with pytest.raises(WasmTrap, match="link"):
        run1(build, imports={
            ("env", "log"): HostFunc([I32], [I32], lambda i, v: v)})


# ---------------------------------------------------------------- fuel ----
class CountingMeter:
    """Meters in grains of `grain` instructions against a hard cap."""

    def __init__(self, cap, grain=1):
        self.cap = cap
        self.used = 0
        self.grain = grain

    def flush(self, executed):
        self.used += executed
        return max(0, min(self.grain, self.cap - self.used))


def _loop_forever(b):
    fidx, f = b.add_func([], [])
    f.loop()
    f.br(0)
    f.end()
    b.export_func("f", fidx)


def test_fuel_exhaustion_traps():
    with pytest.raises(WasmTrap, match="fuel"):
        run1(_loop_forever, meter=CountingMeter(1000))


def test_fuel_accounting_exact():
    # straight-line body: n iterations of a counted loop executes a
    # deterministic instruction count, identical across grain sizes
    def build(b):
        fidx, f = b.add_func([I64], [I64], locals_=[I64])
        f.block()
        f.loop()
        f.local_get(1)
        f.local_get(0)
        f.op(0x5A)
        f.br_if(1)
        f.local_get(1)
        f.i64_const(1)
        f.op(0x7C)
        f.local_set(1)
        f.br(0)
        f.end()
        f.end()
        f.local_get(1)
        b.export_func("f", fidx)
    usages = []
    for grain in (1, 7, 64, 10**9):
        m = CountingMeter(10**9, grain)
        assert run1(build, args=[10], meter=m) == [10]
        usages.append(m.used)
    assert len(set(usages)) == 1, usages


def test_determinism_same_module_same_result():
    def build(b):
        b.add_memory(1)
        fidx, f = b.add_func([I64], [I64], locals_=[I64])
        f.local_get(0)
        f.i64_const(0x9E3779B97F4A7C15)
        f.op(0x7E)
        f.i64_const(31)
        f.op(0x8A)                       # rotr
        b.export_func("f", fidx)
    r1 = run1(build, args=[12345])
    r2 = run1(build, args=[12345])
    assert r1 == r2
    b = ModuleBuilder()
    build(b)
    raw1 = b.encode()
    b2 = ModuleBuilder()
    build(b2)
    assert raw1 == b2.encode()


# ------------------------------------------------ spec-edge conformance ---
M64_ = 0xFFFFFFFFFFFFFFFF


@pytest.mark.parametrize("op,a,b,expect", [
    (0x86, 5, 64, 5),                    # i64.shl count masks to 0
    (0x88, 5, 64, 5),                    # i64.shr_u count masks to 0
    (0x87, (-16) & M64_, 2, (-4) & M64_),  # shr_s keeps sign
    (0x89, 0x8000000000000001, 1, 3),    # rotl wraps both ends
    (0x8A, 3, 1, 0x8000000000000001),    # rotr wraps both ends
    (0x84, 0xF0F0, 0x0F0F, 0xFFFF),      # or
    (0x85, 0xFFFF, 0x0F0F, 0xF0F0),      # xor
])
def test_i64_edge_values(op, a, b, expect):
    assert run1(binop64(op), args=[a, b]) == [expect]


def cmp64(op):
    """i64 comparison producing the i32 flag (widened for transport)."""
    def build(b):
        fidx, f = b.add_func([I64, I64], [I64])
        f.local_get(0)
        f.local_get(1)
        f.op(op)
        f.op(0xAD)                       # i64.extend_i32_u
        b.export_func("f", fidx)
    return build


@pytest.mark.parametrize("a,b,sless,uless", [
    (0, M64_, 0, 1),                     # 0 vs -1: signed greater
    (1 << 63, 0, 1, 0),                  # INT_MIN vs 0
    (5, 5, 0, 0),
])
def test_i64_signed_vs_unsigned_compare(a, b, sless, uless):
    assert run1(cmp64(0x53), args=[a, b]) == [sless]   # lt_s
    assert run1(cmp64(0x54), args=[a, b]) == [uless]   # lt_u


def test_div_u_and_rem_u_edge():
    assert run1(binop64(0x80), args=[M64_, M64_]) == [1]
    assert run1(binop64(0x82), args=[M64_, M64_]) == [0]
    assert run1(binop64(0x80), args=[1, M64_]) == [0]


def test_globals_persist_across_invocations():
    b = ModuleBuilder()
    g = b.add_global(I64, True, 0)
    fidx, f = b.add_func([], [I64])
    f.global_get(g)
    f.i64_const(1)
    f.op(0x7C)
    f.global_set(g)
    f.global_get(g)
    b.export_func("bump", fidx)
    m = decode_module(b.encode())
    validate_module(m)
    inst = Instance(m)
    assert inst.invoke("bump", []) == [1]
    assert inst.invoke("bump", []) == [2]
    assert inst.invoke("bump", []) == [3]


def test_memory_state_persists_across_invocations():
    b = ModuleBuilder()
    b.add_memory(1)
    widx, w = b.add_func([I32, I64], [])
    w.local_get(0)
    w.local_get(1)
    w.store(0x37)
    b.export_func("put", widx)
    ridx, r = b.add_func([I32], [I64])
    r.local_get(0)
    r.load(0x29)
    b.export_func("get", ridx)
    m = decode_module(b.encode())
    validate_module(m)
    inst = Instance(m)
    inst.invoke("put", [64, 0xDEADBEEF])
    assert inst.invoke("get", [64]) == [0xDEADBEEF]
    assert inst.invoke("get", [0]) == [0]


def test_br_table_empty_targets_uses_default():
    def build(b):
        fidx, f = b.add_func([I32], [I64])
        f.block(I64)
        f.block()
        f.local_get(0)
        f.br_table([], 0)                # always default -> inner block
        f.end()
        f.i64_const(11)
        f.br(0)
        f.end()
        b.export_func("f", fidx)
    assert run1(build, args=[0]) == [11]
    assert run1(build, args=[900]) == [11]


def test_nested_block_result_threading():
    """Block results thread through nested ends (validator + label
    arity agreement)."""
    def build(b):
        fidx, f = b.add_func([], [I64])
        f.block(I64)
        f.block(I64)
        f.i64_const(40)
        f.end()
        f.i64_const(2)
        f.op(0x7C)
        f.end()
        b.export_func("f", fidx)
    assert run1(build) == [42]


def test_br_with_value_through_two_labels():
    def build(b):
        fidx, f = b.add_func([I32], [I64])
        f.block(I64)
        f.block(I64)
        f.i64_const(7)
        f.local_get(0)
        f.br_if(1)                       # carry 7 straight to the outer
        f.drop()
        f.i64_const(1)
        f.end()
        f.i64_const(100)
        f.op(0x7C)
        f.end()
        b.export_func("f", fidx)
    assert run1(build, args=[1]) == [7]
    assert run1(build, args=[0]) == [101]


def test_loop_branch_restores_stack_height():
    """br to a loop label must truncate the operand stack back to the
    loop entry height each iteration (no unbounded growth)."""
    def build(b):
        fidx, f = b.add_func([I64], [I64], locals_=[I64])
        f.block()
        f.loop()
        f.i64_const(999)                 # junk that must be discarded
        f.drop()
        f.local_get(1)
        f.local_get(0)
        f.op(0x5A)
        f.br_if(1)
        f.local_get(1)
        f.i64_const(1)
        f.op(0x7C)
        f.local_set(1)
        f.br(0)
        f.end()
        f.end()
        f.local_get(1)
        b.export_func("f", fidx)
    assert run1(build, args=[50]) == [50]


def test_call_indirect_through_mutated_intent():
    """Table entries are fixed at instantiation; repeated indirect calls
    through different indices stay consistent."""
    def build(b):
        t = b.functype([I64], [I64])
        d_idx, fd = b.add_func([I64], [I64])
        fd.local_get(0)
        fd.local_get(0)
        fd.op(0x7C)
        s_idx, fs = b.add_func([I64], [I64])
        fs.local_get(0)
        fs.local_get(0)
        fs.op(0x7E)
        b.add_table(2)
        b.add_element(0, [d_idx, s_idx])
        fidx, f = b.add_func([I32, I64], [I64])
        f.local_get(1)
        f.local_get(0)
        f.call_indirect(t)
        b.export_func("f", fidx)
    assert run1(build, args=[0, 21]) == [42]     # double
    assert run1(build, args=[1, 9]) == [81]      # square


def test_select_preserves_both_types():
    def build(b):
        fidx, f = b.add_func([I32], [I32])
        f.i32_const(10)
        f.i32_const(20)
        f.local_get(0)
        f.select()
        b.export_func("f", fidx)
    assert run1(build, args=[7]) == [10]
    assert run1(build, args=[0]) == [20]


def test_unreachable_after_branch_is_validatable():
    """Code after an unconditional br is unreachable-polymorphic and
    must validate (the spec's stack-polymorphism rule)."""
    def build(b):
        fidx, f = b.add_func([], [I64])
        f.block(I64)
        f.i64_const(5)
        f.br(0)
        f.i32_const(1)                   # wrong type — but unreachable
        f.drop()
        f.end()
        b.export_func("f", fidx)
    assert run1(build) == [5]


def test_fuel_charged_even_for_trapping_run():
    m = CountingMeter(10**9, grain=64)
    with pytest.raises(WasmTrap, match="div0"):
        run1(binop64(0x7F), args=[1, 0], meter=m)
    assert m.used > 0


def test_fuel_accounted_across_nested_call_trap():
    """A trap deep in a callee must charge the callee's executed
    instructions, not roll back to the caller's snapshot."""
    def build(b):
        g_idx, g = b.add_func([], [])
        for _ in range(30):
            g.nop()
        g.unreachable()
        f_idx, f = b.add_func([], [])
        f.call(g_idx)
        b.export_func("f", f_idx)
    m = CountingMeter(10**9, grain=1024)
    with pytest.raises(WasmTrap, match="unreachable"):
        run1(build, meter=m)
    assert m.used >= 32          # call + 30 nops + unreachable


def test_fuel_exhaustion_never_double_charges():
    """When _refuel raises, the flushed instructions must not be
    charged a second time at exit (budget must never go negative)."""
    cap = 20
    m = CountingMeter(cap, grain=8)
    with pytest.raises(WasmTrap, match="fuel"):
        run1(_loop_forever, meter=m)
    assert m.used <= cap


# ------------------------------------------------------------ bulk memory --
def test_bulk_memory_init_fill_copy_roundtrip():
    """memory.init / memory.fill / memory.copy through the full
    encode→decode→validate→run path (0xFC prefix, passive segment,
    data-count section — what SDK-built contracts emit)."""
    def build(b):
        b.add_memory(1)
        seg = b.add_passive_data(b"abcdef")
        fi, f = b.add_func([], [I64])
        (f.i32_const(0).i32_const(0).i32_const(6).memory_init(seg)
          .i32_const(6).i32_const(0x61).i32_const(2).memory_fill()
          .i32_const(8).i32_const(0).i32_const(8).memory_copy()
          .i64_const(42))
        b.export_func("f", fi)
    b = ModuleBuilder()
    build(b)
    raw = b.encode()
    m = decode_module(raw)
    assert m.data_count == 1 and m.data[0][0] is None
    validate_module(m)
    inst = Instance(m, imports={})
    assert inst.invoke("f", []) == [42]
    assert bytes(inst.memory[:16]) == b"abcdefaaabcdefaa"


def test_bulk_memory_overlapping_copy_is_memmove():
    def build(b):
        b.add_memory(1)
        b.add_data(0, b"abcdefgh")
        fi, f = b.add_func([], [])
        f.i32_const(2).i32_const(0).i32_const(6).memory_copy()
        b.export_func("f", fi)
    b = ModuleBuilder()
    build(b)
    m = decode_module(b.encode())
    validate_module(m)
    inst = Instance(m, imports={})
    inst.invoke("f", [])
    assert bytes(inst.memory[:8]) == b"ababcdef"


def test_bulk_memory_oob_traps():
    def mk(emitter):
        def build(b):
            b.add_memory(1)
            b.add_passive_data(b"xy")
            fi, f = b.add_func([], [])
            emitter(f)
            b.export_func("f", fi)
        return build
    cases = [
        lambda f: f.i32_const(65535).i32_const(0).i32_const(2)
                   .memory_copy(),
        lambda f: f.i32_const(65535).i32_const(0).i32_const(2)
                   .memory_fill(),
        lambda f: f.i32_const(0).i32_const(0).i32_const(3)
                   .memory_init(0),          # segment only 2 bytes
    ]
    for emitter in cases:
        with pytest.raises(WasmTrap, match="oob"):
            run1(mk(emitter))


def test_data_drop_then_init_traps():
    def build(b):
        b.add_memory(1)
        b.add_passive_data(b"xy")
        fi, f = b.add_func([], [])
        (f.data_drop(0)
          .i32_const(0).i32_const(0).i32_const(1).memory_init(0))
        b.export_func("f", fi)
    with pytest.raises(WasmTrap, match="oob"):
        run1(build)
    # zero-length init on a dropped segment is fine (spec)
    def build2(b):
        b.add_memory(1)
        b.add_passive_data(b"xy")
        fi, f = b.add_func([], [])
        (f.data_drop(0)
          .i32_const(0).i32_const(0).i32_const(0).memory_init(0))
        b.export_func("f", fi)
    run1(build2)


def test_trunc_sat_rejected_as_float_op():
    """0xFC 0-7 (saturating float truncations) decode but the
    deterministic profile rejects them like every float opcode —
    soroban-env's wasmi configuration equally refuses float code, so
    no valid on-chain contract contains them."""
    b = ModuleBuilder()
    fi, f = b.add_func([], [I32])
    f.i32_const(0)
    f.op(0xFC00)                       # i32.trunc_sat_f32_s
    b.export_func("f", fi)
    raw = b.encode()
    m = decode_module(raw)
    with pytest.raises(WasmValidationError, match="float"):
        validate_module(m)


def test_memory_init_requires_data_count():
    """memory.init without a data-count section is invalid (spec:
    single-pass validation needs the declared count)."""
    b = ModuleBuilder()
    b.add_memory(1)
    b.add_data(0, b"xy")               # active only: no count section
    fi, f = b.add_func([], [])
    f.i32_const(0).i32_const(0).i32_const(1).memory_init(0)
    b.export_func("f", fi)
    m = b.build()                      # direct module (no count)
    with pytest.raises(WasmValidationError, match="data count"):
        validate_module(m)
    # with the count section declared, active-segment init is legal
    # (the segment counts as dropped post-instantiation → oob at run)
    b2 = ModuleBuilder()
    b2.add_memory(1)
    b2.add_data(0, b"xy")
    fi, f = b2.add_func([], [])
    f.i32_const(0).i32_const(0).i32_const(1).memory_init(0)
    b2.export_func("f", fi)
    b2.require_data_count()
    m2 = decode_module(b2.encode())
    assert m2.data_count == 1
    validate_module(m2)
    with pytest.raises(WasmTrap, match="oob"):
        Instance(m2, imports={}).invoke("f", [])


def test_fc_sub_opcode_aliasing_rejected():
    """0xFC with an out-of-range LEB sub-opcode (e.g. 0x408, which
    would alias onto memory.init if OR'd into 0xFC00) must be rejected
    at decode, matching wasmi."""
    from stellar_core_tpu.soroban.wasm.decode import Reader, decode_expr
    from stellar_core_tpu.soroban.wasm.module import WasmFormatError
    body = bytes([0xFC, 0x88, 0x08, 0x0B])   # LEB128(0x408) then END
    with pytest.raises(WasmFormatError, match="0xFC"):
        decode_expr(Reader(body))
