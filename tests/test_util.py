"""Util-layer tests (reference behaviors: src/util/test/TimerTests.cpp,
SchedulerTests.cpp, and the verify-cache usage in crypto/SecretKey.cpp)."""

import pytest

from stellar_core_tpu.util import (
    VirtualClock, VirtualTimer, ClockMode, Scheduler, ActionType,
    RandomEvictionCache, releaseAssert, AssertionFailed,
)
from stellar_core_tpu.util.metrics import MetricsRegistry


def test_virtual_clock_starts_at_zero():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    assert clock.now() == 0.0


def test_virtual_timer_fires_in_order():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    fired = []
    for delay, tag in [(3.0, "c"), (1.0, "a"), (2.0, "b")]:
        t = VirtualTimer(clock)
        t.expires_from_now(delay)
        t.async_wait(lambda tag=tag: fired.append(tag))
    # nothing due yet
    assert clock.crank(block=False) == 0
    # blocking cranks advance virtual time to each event
    while clock.crank(block=True):
        pass
    assert fired == ["a", "b", "c"]
    assert clock.now() == 3.0


def test_virtual_timer_cancel():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    fired, cancelled = [], []
    t = VirtualTimer(clock)
    t.expires_from_now(1.0)
    t.async_wait(lambda: fired.append(1), on_cancel=lambda: cancelled.append(1))
    t.cancel()
    clock.crank_for(2.0)
    assert fired == [] and cancelled == [1]


def test_crank_until():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    hits = []
    t = VirtualTimer(clock)
    t.expires_from_now(5.0)
    t.async_wait(lambda: hits.append(1))
    assert clock.crank_until(lambda: bool(hits), timeout=10.0)
    assert not clock.crank_until(lambda: len(hits) > 1, timeout=1.0)


def test_scheduler_fairness():
    s = Scheduler()
    order = []
    for i in range(3):
        s.enqueue("a", lambda i=i: order.append(("a", i)))
        s.enqueue("b", lambda i=i: order.append(("b", i)))
    s.run_all()
    # FIFO within queues; both queues interleave
    assert [x for x in order if x[0] == "a"] == [("a", 0), ("a", 1), ("a", 2)]
    assert [x for x in order if x[0] == "b"] == [("b", 0), ("b", 1), ("b", 2)]
    assert s.stats_actions_run == 6


def test_scheduler_sheds_droppable():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    s = Scheduler(clock, latency_window=5.0)
    ran = []
    s.enqueue("q", lambda: ran.append("d"), ActionType.DROPPABLE)
    s.enqueue("q", lambda: ran.append("n"), ActionType.NORMAL)
    clock.set_virtual_time(10.0)  # everything in q is now stale
    s.run_all()
    assert ran == ["n"]
    assert s.stats_actions_dropped == 1


def test_random_eviction_cache_bounds_and_counters():
    c = RandomEvictionCache(max_size=16, seed=7)
    for i in range(100):
        c.put(i, i * 2)
    assert len(c) == 16
    assert c.inserts == 100
    hits_before = c.hits
    found = sum(1 for i in range(100) if c.maybe_get(i) is not None)
    assert found == 16
    assert c.hits == hits_before + 16
    assert c.misses == 84
    # overwrite does not grow
    for i in range(100):
        c.put(1000, i)
    assert len(c) == 16
    assert c.maybe_get(1000) == 99


def test_release_assert():
    releaseAssert(True)
    with pytest.raises(AssertionFailed):
        releaseAssert(False, "boom")


def test_metrics_registry():
    m = MetricsRegistry()
    m.new_counter("ledger.age.closed").inc(3)
    m.new_meter("scp.envelope.receive").mark(10)
    t = m.new_timer("ledger.transaction.apply")
    with t.time_scope():
        pass
    t.update(0.5)
    j = m.to_json()
    assert j["ledger.age.closed"]["count"] == 3
    assert j["scp.envelope.receive"]["count"] == 10
    assert j["ledger.transaction.apply"]["count"] == 2
    # same name returns same object
    assert m.new_counter("ledger.age.closed").count == 3


def test_gc_policy_install_and_collect():
    """util/gcpolicy (ISSUE 12): install is process-wide idempotent
    (the test process's first Application already installed it), the
    gen2 auto-threshold is pushed out so automatic full-heap scans
    cannot land inside a ledger close, and the explicit maintenance/
    teardown passes still reclaim reference cycles."""
    import gc

    from stellar_core_tpu.util import gcpolicy

    first = gcpolicy.install()
    assert gcpolicy.install() is False    # idempotent from here on
    if not first:
        # an Application was built earlier in the suite: the policy
        # must already be live
        assert gc.get_threshold()[2] >= 1_000_000

    class Cyc:
        pass

    a, b = Cyc(), Cyc()
    a.other, b.other = b, a
    del a, b
    # the explicit passes are the sanctioned full collections
    assert gcpolicy.maintenance_collect() >= 0
    assert gcpolicy.teardown_collect() >= 0
