"""History/catchup acceptance tier (VERDICT r02 #9).

The CatchupSimulation matrix (reference:
history/test/HistoryTestsUtils.h:52-95 — publish checkpoints, catch up
new nodes across modes): minimal / complete / recent, a mid-history
PROTOCOL UPGRADE every replay must cross, trailing ("online"-style)
re-catchup against a moving archive, flaky-archive retries, and
corrupted-archive failure.
"""

import glob
import gzip
import os

import pytest

import test_standalone_app as m1
from txtest_utils import op_create_account, op_payment

from stellar_core_tpu.catchup.catchup_work import (CATCHUP_MINIMAL,
                                                   CatchupConfiguration,
                                                   CatchupWork)
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.herder.upgrades import UpgradeParameters
from stellar_core_tpu.history.archive import make_tmpdir_archive
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.work import run_work_to_completion
from stellar_core_tpu.work.basic_work import State

UPGRADE_AT = 40          # ledger where the protocol bump externalizes
START_PROTO = 20
END_PROTO = 21


def _publish_with_upgrade(tmp_path, n_ledgers=130):
    """Standalone publisher that starts on protocol 20, upgrades to 21
    mid-history, and closes payments before and after the bump."""
    archive_root = str(tmp_path / "archive")
    cfg = get_test_config()
    cfg.LEDGER_PROTOCOL_VERSION = START_PROTO   # genesis protocol
    cfg.HISTORY = {"test": {
        "get": f"cp {archive_root}/{{0}} {{1}}",
        "put": f"mkdir -p $(dirname {archive_root}/{{1}}) && "
               f"cp {{0}} {archive_root}/{{1}}",
    }}
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    master = m1.master_account(app)
    dests = [m1.AppAccount(app, SecretKey.from_seed(bytes([i]) * 32))
             for i in range(1, 5)]
    for d in dests:
        m1.submit(app, master.tx([op_create_account(d.account_id,
                                                    10**12)]))
    app.manual_close()
    for d in dests:
        d.sync_seq()
    lcl = app.ledger_manager.get_last_closed_ledger_num()
    while lcl < n_ledgers:
        if lcl == UPGRADE_AT - 1:
            app.herder.upgrades.set_parameters(UpgradeParameters(
                upgrade_time=0, protocol_version=END_PROTO))
        if lcl % 5 == 0:
            d = dests[lcl % len(dests)]
            m1.submit(app, d.tx([op_payment(master.muxed, 1000)]))
        app.manual_close()
        lcl = app.ledger_manager.get_last_closed_ledger_num()
    hdr = app.ledger_manager.get_last_closed_ledger_header()
    assert hdr.ledgerVersion == END_PROTO, \
        "publisher never crossed the protocol upgrade"
    return app, make_tmpdir_archive("test", archive_root), archive_root


def _fresh_node(app_a, **cfg_overrides):
    cfg = get_test_config()
    cfg.NETWORK_PASSPHRASE = app_a.config.NETWORK_PASSPHRASE
    cfg.LEDGER_PROTOCOL_VERSION = START_PROTO   # genesis protocol
    for k, v in cfg_overrides.items():
        setattr(cfg, k, v)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    return app


def _chain_hash(app, seq):
    row = app.database.query_one(
        "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=?", (seq,))
    return bytes(row[0])


@pytest.mark.parametrize("mode,count", [
    ("complete", 0xFFFFFFFF),
    ("minimal", CATCHUP_MINIMAL),
    ("recent", 16),
])
def test_catchup_modes_across_protocol_upgrade(tmp_path, mode, count):
    """Every catchup mode lands on the publisher's post-upgrade chain:
    the replay (or bucket apply) must reproduce ledgers closed under
    BOTH protocol versions."""
    app_a, archive, _root = _publish_with_upgrade(tmp_path)
    try:
        tip = 127
        hash_a = _chain_hash(app_a, tip)
        app_b = _fresh_node(app_a)
        try:
            work = CatchupWork(app_b, archive,
                               CatchupConfiguration(to_ledger=0,
                                                    count=count))
            assert run_work_to_completion(
                app_b, work, timeout_virtual=4000) == State.WORK_SUCCESS
            assert app_b.ledger_manager.get_last_closed_ledger_num() == tip
            assert app_b.ledger_manager.get_last_closed_ledger_hash() == \
                hash_a
            hdr = app_b.ledger_manager.get_last_closed_ledger_header()
            assert hdr.ledgerVersion == END_PROTO
            bal_a = m1.app_account_entry(
                app_a, m1.master_account(app_a).account_id).balance
            bal_b = m1.app_account_entry(
                app_b, m1.master_account(app_b).account_id).balance
            assert bal_a == bal_b
        finally:
            app_b.shutdown()
    finally:
        app_a.shutdown()


def test_trailing_catchup_against_moving_archive(tmp_path):
    """The 'online' leg: a caught-up node falls behind while the
    publisher keeps closing; a second catchup brings it to the new
    tip (reference: CatchupSimulation::catchupOnline re-runs)."""
    app_a, archive, _root = _publish_with_upgrade(tmp_path, n_ledgers=130)
    try:
        app_b = _fresh_node(app_a)
        try:
            work = CatchupWork(app_b, archive,
                               CatchupConfiguration(to_ledger=0))
            assert run_work_to_completion(
                app_b, work, timeout_virtual=4000) == State.WORK_SUCCESS
            first_tip = app_b.ledger_manager.get_last_closed_ledger_num()
            assert first_tip == 127

            # the network moves on: publish two more checkpoints
            master = m1.master_account(app_a)
            lcl = app_a.ledger_manager.get_last_closed_ledger_num()
            while lcl < 260:
                if lcl % 6 == 0:
                    m1.submit(app_a, master.tx(
                        [op_payment(master.muxed, 1)]))
                app_a.manual_close()
                lcl = app_a.ledger_manager.get_last_closed_ledger_num()

            work2 = CatchupWork(app_b, archive,
                                CatchupConfiguration(to_ledger=0))
            assert run_work_to_completion(
                app_b, work2, timeout_virtual=6000) == State.WORK_SUCCESS
            tip2 = app_b.ledger_manager.get_last_closed_ledger_num()
            assert tip2 == 255
            assert app_b.ledger_manager.get_last_closed_ledger_hash() == \
                _chain_hash(app_a, tip2)
        finally:
            app_b.shutdown()
    finally:
        app_a.shutdown()


def test_catchup_survives_flaky_archive(tmp_path):
    """Every `get` fails on its first attempt; BasicWork's retry policy
    (reference: BasicWork.h RETRY_* + GetRemoteFileWork retries) must
    carry catchup to success anyway."""
    app_a, archive, root = _publish_with_upgrade(tmp_path, n_ledgers=66)
    try:
        marker_dir = str(tmp_path / "flaky-markers")
        os.makedirs(marker_dir, exist_ok=True)
        # fail each file's first fetch: marker file distinguishes tries
        archive.get_cmd = (
            f"sh -c 'm={marker_dir}/$(echo {{0}} | tr / _); "
            f"if [ ! -f $m ]; then touch $m; exit 1; fi; "
            f"cp {root}/{{0}} {{1}}'")
        app_b = _fresh_node(app_a)
        try:
            work = CatchupWork(app_b, archive,
                               CatchupConfiguration(to_ledger=0))
            assert run_work_to_completion(
                app_b, work, timeout_virtual=8000) == State.WORK_SUCCESS
            assert app_b.ledger_manager.get_last_closed_ledger_num() == 63
            assert os.listdir(marker_dir), "flaky gate never triggered"
        finally:
            app_b.shutdown()
    finally:
        app_a.shutdown()


def test_catchup_rejects_corrupted_archive(tmp_path):
    """A corrupted transactions file must fail catchup cleanly (hash /
    replay divergence detected), never externalize a wrong ledger."""
    app_a, archive, root = _publish_with_upgrade(tmp_path, n_ledgers=66)
    try:
        tx_files = sorted(glob.glob(
            os.path.join(root, "transactions", "**", "*.xdr.gz"),
            recursive=True))
        assert tx_files
        raw = gzip.decompress(open(tx_files[-1], "rb").read())
        if len(raw) > 40:
            raw = raw[:-20] + bytes([raw[-20] ^ 0xFF]) + raw[-19:]
        else:
            raw = raw + b"\x01"
        with open(tx_files[-1], "wb") as f:
            f.write(gzip.compress(raw))
        app_b = _fresh_node(app_a)
        try:
            work = CatchupWork(app_b, archive,
                               CatchupConfiguration(to_ledger=0))
            final = run_work_to_completion(app_b, work,
                                           timeout_virtual=8000)
            if final == State.WORK_SUCCESS:
                # corruption in the last checkpoint may leave earlier
                # ledgers valid — but the replayed chain must never
                # diverge from the publisher's
                tip = app_b.ledger_manager.get_last_closed_ledger_num()
                assert app_b.ledger_manager \
                    .get_last_closed_ledger_hash() == \
                    _chain_hash(app_a, tip)
            else:
                assert final == State.WORK_FAILURE
        finally:
            app_b.shutdown()
    finally:
        app_a.shutdown()
