"""Operator/testing config knobs wired to real behavior (VERDICT r03
missing #6): ARTIFICIALLY_* pessimization, apply-sleep weights,
flood-demand retry, maintenance tuning, SCP slot retention."""

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.overlay.loopback import LoopbackPeerConnection
from stellar_core_tpu.util.timer import ClockMode, VirtualClock

import test_standalone_app as m1
from txtest_utils import op_create_account, op_payment


def test_pessimized_merges_run_synchronously():
    cfg = get_test_config()
    cfg.ARTIFICIALLY_PESSIMIZE_MERGES_FOR_TESTING = True
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    try:
        assert app.bucket_manager.bucket_list._executor is None
        master = m1.master_account(app)
        dest = m1.AppAccount(app, SecretKey.from_seed(b"\x21" * 32))
        m1.submit(app, master.tx([op_create_account(dest.account_id,
                                                    10**11)]))
        for _ in range(10):     # crosses several spill boundaries
            app.manual_close()
        assert app.ledger_manager.get_last_closed_ledger_num() >= 11
    finally:
        app.shutdown()


def test_apply_sleep_weights_slow_the_close():
    import time
    cfg = get_test_config()
    cfg.OP_APPLY_SLEEP_TIME_WEIGHT_FOR_TESTING = [1]
    cfg.OP_APPLY_SLEEP_TIME_DURATION_FOR_TESTING = [25.0]  # ms per tx
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    try:
        master = m1.master_account(app)
        m1.submit(app, master.tx([op_create_account(
            SecretKey.from_seed(b"\x22" * 32).public_key().raw
            and m1.AppAccount(app, SecretKey.from_seed(b"\x22" * 32))
            .account_id, 10**11)]))
        t0 = time.monotonic()
        app.manual_close()
        assert time.monotonic() - t0 >= 0.025
    finally:
        app.shutdown()


def test_artificial_main_thread_sleep_poller():
    import time
    cfg = get_test_config()
    cfg.ARTIFICIALLY_SLEEP_MAIN_THREAD_FOR_TESTING_US = 5000
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    try:
        t0 = time.monotonic()
        for _ in range(4):
            app.clock.crank(False)
        assert time.monotonic() - t0 >= 0.015
    finally:
        app.shutdown()


def test_automatic_maintenance_timer_prunes_history():
    cfg = get_test_config()
    cfg.AUTOMATIC_MAINTENANCE_PERIOD = 30.0
    cfg.AUTOMATIC_MAINTENANCE_COUNT = 10_000
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    try:
        master = m1.master_account(app)
        dest = m1.AppAccount(app, SecretKey.from_seed(b"\x23" * 32))
        m1.submit(app, master.tx([op_create_account(dest.account_id,
                                                    10**12)]))
        app.manual_close()
        dest.sync_seq()
        for _ in range(200):
            m1.submit(app, dest.tx([op_payment(master.muxed, 5)]))
            app.manual_close()
        before = app.database.query_one(
            "SELECT COUNT(*) FROM txhistory")[0]
        app.clock.crank_for(35.0)      # maintenance timer fires
        after = app.database.query_one(
            "SELECT COUNT(*) FROM txhistory")[0]
        assert after < before
    finally:
        app.shutdown()


def test_flood_demand_retry_reroutes_to_another_peer():
    """A peer that never answers FLOOD_DEMAND must not strand the tx:
    after FLOOD_DEMAND_PERIOD_MS the demander re-demands from another
    peer that has it (reference: TxDemandsManager retry)."""
    from test_overlay import make_apps
    clock, apps = make_apps(3)
    try:
        conns = [LoopbackPeerConnection(apps[0], apps[1]),
                 LoopbackPeerConnection(apps[0], apps[2]),
                 LoopbackPeerConnection(apps[1], apps[2])]
        for c in conns:
            c.crank()
        # node0 ignores demands from node1 ONLY (node2 is served)
        om0 = apps[0].overlay_manager
        node1_side = conns[0].acceptor   # node1's peer object at node0?
        orig = om0._on_flood_demand
        blocked_peer = conns[0].initiator  # node0's peer toward node1

        def selective(peer, msg, _orig=orig, _blocked=blocked_peer):
            if peer is _blocked:
                return      # pretend the demand never arrived
            _orig(peer, msg)

        om0._on_flood_demand = selective
        # node2 receives the tx but never adverts it onward, so node1's
        # ONLY advert comes from node0 (whose demand path is dead) —
        # isolating the retry as node1's sole route to the body
        apps[2].herder.tx_advert_cb = None

        master = m1.master_account(apps[0])
        dest = m1.AppAccount(apps[0], SecretKey.from_seed(b"\x24" * 32))
        frame = master.tx([op_create_account(dest.account_id, 10**11)])
        assert m1.submit(apps[0], frame)["status"] == "PENDING"
        apps[0].overlay_manager.advert_transaction(frame.full_hash())

        def pump(seconds):
            deadline = clock.now() + seconds
            while clock.now() < deadline:
                for c in conns:
                    c.crank()
                if clock.crank(False) == 0:
                    clock.crank(True)

        pump(0.05)
        h = frame.full_hash()
        # node2 got it straight away; node1's demand went unanswered
        assert apps[2].herder.tx_queue.get_tx(h) is not None
        assert apps[1].herder.tx_queue.get_tx(h) is None
        # after the demand period, node1 re-demands from node2
        pump(2.0)
        assert apps[1].herder.tx_queue.get_tx(h) is not None
    finally:
        for app in apps:
            app.shutdown()


def test_max_slots_to_remember_bounds_envelope_window():
    cfg = get_test_config()
    cfg.MAX_SLOTS_TO_REMEMBER = 5
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    try:
        for _ in range(10):
            app.manual_close()
        from stellar_core_tpu.herder.pending_envelopes import RecvState
        from stellar_core_tpu.xdr.scp import SCPEnvelope
        lcl = app.ledger_manager.get_last_closed_ledger_num()
        app.herder.verify_envelope = lambda _e: True  # isolate the window
        env = SCPEnvelope.__new__(SCPEnvelope)

        class _Stmt:
            slotIndex = lcl - 6     # behind the 5-slot window
        env.statement = _Stmt()
        assert app.herder.recv_scp_envelope(env) == \
            RecvState.ENVELOPE_STATUS_DISCARDED
        # inside the window the same envelope gets past the gate (it
        # then fails deeper for being a stub, which is fine — the knob
        # under test is only the retention window)
        class _Stmt2:
            slotIndex = lcl - 4
        env2 = SCPEnvelope.__new__(SCPEnvelope)
        env2.statement = _Stmt2()
        try:
            r = app.herder.recv_scp_envelope(env2)
        except Exception:
            r = None
        assert r != RecvState.ENVELOPE_STATUS_DISCARDED or r is None
    finally:
        app.shutdown()


# ---------------------------------------------------------- tranche 3 --

def test_override_eviction_params_for_testing():
    """OVERRIDE_EVICTION_PARAMS_FOR_TESTING stamps the TESTING_* fields
    into the StateArchivalSettings entry at creation."""
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    from stellar_core_tpu.soroban.network_config import SorobanNetworkConfig

    cfg = get_test_config()
    cfg.LEDGER_PROTOCOL_VERSION = 20
    cfg.OVERRIDE_EVICTION_PARAMS_FOR_TESTING = True
    cfg.TESTING_EVICTION_SCAN_SIZE = 123
    cfg.TESTING_MAX_ENTRIES_TO_ARCHIVE = 7
    cfg.TESTING_MINIMUM_PERSISTENT_ENTRY_LIFETIME = 9
    cfg.TESTING_STARTING_EVICTION_SCAN_LEVEL = 3
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        with LedgerTxn(app.ledger_manager.root) as ltx:
            sa = SorobanNetworkConfig(ltx).state_archival
            assert sa.evictionScanSize == 123
            assert sa.maxEntriesToArchive == 7
            assert sa.minPersistentTTL == 9
            assert sa.startingEvictionScanLevel == 3


def test_limit_tx_queue_source_account():
    """LIMIT_TX_QUEUE_SOURCE_ACCOUNT: one queued tx per source; the
    second submission must wait for a close (replace-by-fee exempt)."""
    cfg = get_test_config()
    cfg.LIMIT_TX_QUEUE_SOURCE_ACCOUNT = True
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        master = m1.master_account(app)
        r1 = m1.submit(app, master.tx([op_payment(master.muxed, 1)]))
        assert r1["status"] == "PENDING", r1
        r2 = m1.submit(app, master.tx([op_payment(master.muxed, 2)]))
        assert r2["status"] == "TRY_AGAIN_LATER", r2
        app.manual_close()
        master.sync_seq()
        r3 = m1.submit(app, master.tx([op_payment(master.muxed, 3)]))
        assert r3["status"] == "PENDING", r3


def test_halt_on_internal_transaction_error(monkeypatch):
    """HALT_ON_INTERNAL_TRANSACTION_ERROR aborts the close instead of
    recording txINTERNAL_ERROR."""
    from stellar_core_tpu.tx.operations.payment_ops import PaymentOpFrame

    cfg = get_test_config()
    cfg.HALT_ON_INTERNAL_TRANSACTION_ERROR = True
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        master = m1.master_account(app)
        r = m1.submit(app, master.tx([op_payment(master.muxed, 1)]))
        assert r["status"] == "PENDING", r

        def boom(self, ltx, header, ctx):
            raise RuntimeError("injected internal error")

        monkeypatch.setattr(PaymentOpFrame, "do_apply", boom)
        with pytest.raises(RuntimeError, match="halting on "
                                               "txINTERNAL_ERROR"):
            app.manual_close()


def test_mode_uses_in_memory_ledger():
    """MODE_USES_IN_MEMORY_LEDGER: the dict-backed root serves the
    apply path; payments close and headers still persist."""
    from stellar_core_tpu.ledger.ledger_txn import InMemoryLedgerTxnRoot

    cfg = get_test_config()
    cfg.MODE_USES_IN_MEMORY_LEDGER = True
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        assert isinstance(app.ledger_manager.root, InMemoryLedgerTxnRoot)
        master = m1.master_account(app)
        dest = m1.AppAccount(app, SecretKey.from_seed(b"\x71" * 32))
        r = m1.submit(app, master.tx(
            [op_create_account(dest.account_id, 10**10)]))
        assert r["status"] == "PENDING", r
        app.manual_close()
        assert m1.app_account_entry(app, dest.account_id) is not None
        row = app.database.query_one(
            "SELECT COUNT(*) FROM ledgerheaders", ())
        assert row[0] >= 2


def test_disable_bucket_gc(tmp_path):
    """DISABLE_BUCKET_GC keeps unreferenced bucket files."""
    cfg = get_test_config()
    cfg.BUCKET_DIR_PATH = str(tmp_path / "b")
    cfg.DISABLE_BUCKET_GC = True
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        master = m1.master_account(app)
        for i in range(4):
            m1.submit(app, master.tx([op_payment(master.muxed, 1 + i)]))
            app.manual_close()
        assert app.bucket_manager.forget_unreferenced_buckets() == 0


def test_reduced_merge_counts_shrinks_levels():
    """ARTIFICIALLY_REDUCE_MERGE_COUNTS_FOR_TESTING: spills reach level
    1 within a few ledgers (base-4 cadence needs 2x as many)."""
    from stellar_core_tpu.bucket.bucket_list import (level_size,
                                                     set_reduced_merge_counts)
    cfg = get_test_config()
    cfg.ARTIFICIALLY_REDUCE_MERGE_COUNTS_FOR_TESTING = True
    try:
        with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                                cfg) as app:
            app.start()
            assert level_size(0) == 2
            master = m1.master_account(app)
            for i in range(4):
                m1.submit(app, master.tx([op_payment(master.muxed,
                                                     1 + i)]))
                app.manual_close()
            bl = app.bucket_manager.bucket_list
            assert not (bl.levels[0].snap.is_empty()
                        and bl.levels[1].curr.is_empty())
    finally:
        set_reduced_merge_counts(False)


def test_flood_tx_period_batches_adverts():
    """FLOOD_TX_PERIOD_MS: accepted txs advert in budgeted batches on
    the timer, not immediately."""
    cfg = get_test_config()
    cfg.FLOOD_TX_PERIOD_MS = 100
    cfg.FLOOD_OP_RATE_PER_LEDGER = 2.0
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        adverts = []
        app.herder.tx_advert_cb = adverts.append
        master = m1.master_account(app)
        for i in range(3):
            r = m1.submit(app, master.tx([op_payment(master.muxed,
                                                     1 + i)]))
            assert r["status"] == "PENDING", r
        assert adverts == []          # queued, not flooded yet
        app.clock.crank_for(0.25)
        assert len(adverts) == 3      # the drain timer fired


def test_outbound_tx_queue_byte_limit():
    """OUTBOUND_TX_QUEUE_BYTE_LIMIT drops the OLDEST queued TRANSACTION
    when the per-peer outbound queue overflows."""
    from stellar_core_tpu.overlay.flow_control import FlowControl
    from stellar_core_tpu.xdr.overlay import MessageType, StellarMessage
    from stellar_core_tpu.xdr.transaction import TransactionEnvelope

    cfg = get_test_config()
    # build three real TRANSACTION messages of equal size
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            get_test_config()) as app:
        app.start()
        master = m1.master_account(app)
        frames = [master.tx([op_payment(master.muxed, i + 1)])
                  for i in range(3)]
    msgs = [StellarMessage(MessageType.TRANSACTION, f.envelope)
            for f in frames]
    size = len(msgs[0].to_bytes())
    cfg.OUTBOUND_TX_QUEUE_BYTE_LIMIT = 2 * size + 4
    fc = FlowControl(cfg)
    # no remote capacity: everything queues
    for m in msgs:
        assert fc.try_send(m) is None
    assert fc.outbound_queue_len() == 2
    assert fc.dropped_tx_msgs == 1
    # the SURVIVORS are the two newest
    sent = fc.on_send_more(10, 10 * size)
    assert [m.value for m in sent] == [msgs[1].value, msgs[2].value]


def test_publish_to_archive_delay(tmp_path):
    """PUBLISH_TO_ARCHIVE_DELAY defers checkpoint publication until the
    timer fires."""
    import os

    import test_history_catchup as hc

    archive_root = str(tmp_path / "archive")
    cfg = get_test_config()
    cfg.PUBLISH_TO_ARCHIVE_DELAY = 30.0
    cfg.HISTORY = {"test": {
        "get": f"cp {archive_root}/{{0}} {{1}}",
        "put": f"mkdir -p $(dirname {archive_root}/{{1}}) && "
               f"cp {{0}} {archive_root}/{{1}}",
    }}
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        while app.ledger_manager.get_last_closed_ledger_num() < 63:
            app.manual_close()
        has_path = os.path.join(archive_root,
                                ".well-known/stellar-history.json")
        assert not os.path.exists(has_path), "published before the delay"
        app.clock.crank_for(35.0)
        assert os.path.exists(has_path)
        assert app.history_manager.published_count == 1


def test_histogram_window_ages_out_samples():
    """HISTOGRAM_WINDOW_SIZE: percentiles reflect only the window."""
    import time as _time

    from stellar_core_tpu.util.metrics import MetricsRegistry

    reg = MetricsRegistry(window_minutes=0.001)   # 60 ms window
    h = reg.new_histogram("test.window")
    h.update(100.0)
    assert h.percentile(0.5) == 100.0
    _time.sleep(0.08)
    h.update(1.0)
    assert h.percentile(0.99) == 1.0     # the 100.0 aged out
    assert h.count == 2                  # lifetime count stays


def test_entry_cache_and_batch_write_knobs():
    """ENTRY_CACHE_SIZE / PREFETCH_BATCH_SIZE / MAX_BATCH_WRITE_* land
    on the SQL root and commits still apply correctly when chunked to
    single-row batches."""
    cfg = get_test_config()
    cfg.DATABASE = "sqlite3://:memory:"
    cfg.ENTRY_CACHE_SIZE = 64
    cfg.PREFETCH_BATCH_SIZE = 2
    cfg.MAX_BATCH_WRITE_COUNT = 1
    cfg.MAX_BATCH_WRITE_BYTES = 1
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        root = app.ledger_manager.root
        assert root._cache.max_size == 64
        assert root.prefetch_batch == 2
        master = m1.master_account(app)
        dests = [m1.AppAccount(app, SecretKey.from_seed(bytes([80 + i])
                                                        * 32))
                 for i in range(3)]
        r = m1.submit(app, master.tx(
            [op_create_account(d.account_id, 10**9) for d in dests]))
        assert r["status"] == "PENDING", r
        app.manual_close()
        for d in dests:
            assert m1.app_account_entry(app, d.account_id) is not None


def test_mode_auto_starts_overlay_off():
    """MODE_AUTO_STARTS_OVERLAY=False keeps the TCP door closed even
    for a non-standalone node."""
    cfg = get_test_config()
    cfg.RUN_STANDALONE = False
    cfg.MODE_AUTO_STARTS_OVERLAY = False
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        assert app.overlay_manager._door is None


def test_log_file_path_writes_file(tmp_path):
    """LOG_FILE_PATH adds a file handler."""
    import logging as pylogging

    from stellar_core_tpu.util.logging import get_logger, init_logging

    path = tmp_path / "node.log"
    init_logging("info", log_file_path=str(path))
    try:
        get_logger("Ledger").info("hello-from-test")
        for h in pylogging.getLogger().handlers:
            h.flush()
        assert "hello-from-test" in path.read_text()
    finally:
        root = pylogging.getLogger()
        for h in list(root.handlers):
            if isinstance(h, pylogging.FileHandler):
                root.removeHandler(h)
                h.close()


def test_flood_lanes_respect_their_own_periods():
    """With different classic/soroban periods, the shared min-period
    timer must NOT drain the slower lane early (each lane floods at its
    own configured rate)."""
    cfg = get_test_config()
    cfg.FLOOD_TX_PERIOD_MS = 400          # slow classic lane
    cfg.FLOOD_SOROBAN_TX_PERIOD_MS = 100  # fast soroban lane
    cfg.FLOOD_OP_RATE_PER_LEDGER = 1000.0  # budget never the limiter
    cfg.FLOOD_SOROBAN_RATE_PER_LEDGER = 1000.0
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        adverts = []
        app.herder.tx_advert_cb = adverts.append
        master = m1.master_account(app)
        r = m1.submit(app, master.tx([op_payment(master.muxed, 1)]))
        assert r["status"] == "PENDING", r
        # classic queued; crank PAST the soroban period but SHORT of
        # the classic period: nothing may flood yet
        app.clock.crank_for(0.2)
        assert adverts == [], "classic lane drained at the soroban rate"
        app.clock.crank_for(0.4)
        assert len(adverts) == 1


# ---------------------------------------------------------- tranche 4 --

def test_max_concurrent_subprocesses_bound():
    cfg = get_test_config()
    cfg.MAX_CONCURRENT_SUBPROCESSES = 2
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        assert app.process_manager.max_concurrent == 2


def test_mode_stores_history_ledgerheaders_off():
    cfg = get_test_config()
    cfg.MODE_STORES_HISTORY_LEDGERHEADERS = False
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        master = m1.master_account(app)
        m1.submit(app, master.tx([op_payment(master.muxed, 1)]))
        app.manual_close()
        row = app.database.query_one(
            "SELECT COUNT(*) FROM ledgerheaders", ())
        assert row[0] == 0


def test_testing_upgrade_flags_votes_header_flags():
    from stellar_core_tpu.herder.upgrades import MASK_LEDGER_HEADER_FLAGS

    cfg = get_test_config()
    cfg.LEDGER_PROTOCOL_VERSION = 21
    flag = MASK_LEDGER_HEADER_FLAGS & 1      # DISABLE_LIQUIDITY_POOL...
    cfg.TESTING_UPGRADE_FLAGS = flag
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        app.manual_close()
        hdr = app.ledger_manager.get_last_closed_ledger_header()
        from stellar_core_tpu.herder.upgrades import _header_flags
        assert _header_flags(hdr) == flag


def test_overlay_protocol_version_window():
    """A peer advertising an overlay window below ours must be
    rejected at HELLO (reference: OVERLAY_PROTOCOL_MIN_VERSION)."""
    from test_overlay import make_apps
    clock, apps = make_apps(2)
    try:
        apps[0].config.OVERLAY_PROTOCOL_MIN_VERSION = 99
        apps[0].config.OVERLAY_PROTOCOL_VERSION = 99
        conn = LoopbackPeerConnection(apps[0], apps[1])
        for _ in range(6):
            conn.crank()
        assert len(apps[0].overlay_manager
                   .get_authenticated_peers()) == 0
        assert len(apps[1].overlay_manager
                   .get_authenticated_peers()) == 0
    finally:
        for app in apps:
            app.shutdown()


def test_best_offer_debugging_cross_checks(monkeypatch):
    """BEST_OFFER_DEBUGGING_ENABLED: every indexed lookup is checked
    against a full scan; a corrupted index aborts loudly."""
    from txtest_utils import (Price, make_asset, op_change_trust,
                              op_manage_sell_offer)

    cfg = get_test_config()
    cfg.DATABASE = "sqlite3://:memory:"
    cfg.BEST_OFFER_DEBUGGING_ENABLED = True
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        root = app.ledger_manager.root
        assert root.best_offer_debugging
        master = m1.master_account(app)
        issuer = m1.AppAccount(app, SecretKey.from_seed(b"\x91" * 32))
        m1.submit(app, master.tx([op_create_account(issuer.account_id,
                                                    10**12)]))
        app.manual_close()
        issuer.sync_seq()
        asset = make_asset(b"DBG", issuer.account_id)
        m1.submit(app, master.tx([op_change_trust(asset, 10**15)]))
        app.manual_close()
        master.sync_seq()
        # resting offer: the crossing path exercises best_offer with
        # the debug cross-check live
        from stellar_core_tpu.xdr.ledger_entries import Asset, AssetType
        native = Asset(AssetType.ASSET_TYPE_NATIVE)
        m1.submit(app, master.tx([op_manage_sell_offer(
            native, asset, 1000, Price(n=1, d=1))]))
        app.manual_close()
        row = app.database.query_one("SELECT COUNT(*) FROM offers", ())
        assert row[0] == 1


# ---------------------------------------------------------- tranche 5 --

def test_use_config_for_genesis_off():
    """USE_CONFIG_FOR_GENESIS=false: protocol-0 genesis; the configured
    protocol arrives only via a voted upgrade."""
    from stellar_core_tpu.herder.upgrades import UpgradeParameters

    cfg = get_test_config()
    cfg.USE_CONFIG_FOR_GENESIS = False
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        assert app.ledger_manager.get_last_closed_ledger_header()\
            .ledgerVersion == 0
        app.herder.upgrades.set_parameters(UpgradeParameters(
            upgrade_time=0, protocol_version=10))
        app.manual_close()
        assert app.ledger_manager.get_last_closed_ledger_header()\
            .ledgerVersion == 10


def test_internal_error_min_protocol_gates_halt(monkeypatch):
    """LEDGER_PROTOCOL_MIN_VERSION_INTERNAL_ERROR_REPORT: below the
    threshold an internal error fails the tx quietly; at/above it the
    HALT knob aborts."""
    from stellar_core_tpu.tx.operations.payment_ops import PaymentOpFrame

    def boom(self, ltx, header, ctx):
        raise RuntimeError("injected")

    for threshold, should_halt in ((99, False), (0, True)):
        cfg = get_test_config()
        cfg.HALT_ON_INTERNAL_TRANSACTION_ERROR = True
        cfg.LEDGER_PROTOCOL_MIN_VERSION_INTERNAL_ERROR_REPORT = threshold
        with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                                cfg) as app:
            app.start()
            master = m1.master_account(app)
            r = m1.submit(app, master.tx([op_payment(master.muxed, 1)]))
            assert r["status"] == "PENDING", r
            monkeypatch.setattr(PaymentOpFrame, "do_apply", boom)
            if should_halt:
                with pytest.raises(RuntimeError, match="halting"):
                    app.manual_close()
            else:
                app.manual_close()   # tx fails, node survives
            monkeypatch.undo()


def test_soroban_high_limit_override():
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    from stellar_core_tpu.soroban.network_config import SorobanNetworkConfig

    cfg = get_test_config()
    cfg.LEDGER_PROTOCOL_VERSION = 20
    cfg.TESTING_SOROBAN_HIGH_LIMIT_OVERRIDE = True
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        with LedgerTxn(app.ledger_manager.root) as ltx:
            from stellar_core_tpu.xdr.contract import ConfigSettingID
            nc = SorobanNetworkConfig(ltx)
            assert nc.ledger_cost.ledgerMaxReadLedgerEntries >= 200_000
            lanes = nc._get(
                ConfigSettingID.CONFIG_SETTING_CONTRACT_EXECUTION_LANES)
            assert lanes.ledgerMaxTxCount >= 100_000


def test_precaution_delay_meta(tmp_path):
    """EXPERIMENTAL_PRECAUTION_DELAY_META: the stream runs one ledger
    behind the LCL."""
    from stellar_core_tpu.util.xdr_stream import read_record
    from stellar_core_tpu.xdr.ledger import LedgerCloseMeta

    path = tmp_path / "meta.xdr"
    cfg = get_test_config()
    cfg.METADATA_OUTPUT_STREAM = str(path)
    cfg.EXPERIMENTAL_PRECAUTION_DELAY_META = True
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            cfg) as app:
        app.start()
        app.manual_close()          # ledger 2: held back
        import io
        assert path.read_bytes() == b""
        app.manual_close()          # ledger 3 closes; ledger 2 emits
        bio = io.BytesIO(path.read_bytes())
        seqs = []
        while True:
            rec = read_record(bio)
            if rec is None:
                break
            m = LedgerCloseMeta.from_bytes(rec)
            seqs.append(m.value.ledgerHeader.header.ledgerSeq)
        assert seqs == [2]
        assert app.ledger_manager.get_last_closed_ledger_num() == 3


def _mk_accounts(n, salt=0):
    import hashlib
    from stellar_core_tpu.tx.tx_utils import make_account_ledger_entry
    from stellar_core_tpu.xdr.types import PublicKey
    return [make_account_ledger_entry(
        PublicKey.ed25519(hashlib.sha256(b"knob-%d-%d" % (salt, i))
                          .digest()), 100 + i, 7) for i in range(n)]


def test_newest_bucket_merge_logic_flag():
    from stellar_core_tpu.bucket.bucket import (
        Bucket, NEWEST_LEDGER_PROTOCOL, merge_buckets,
        set_newest_merge_logic)

    try:
        a, b = _mk_accounts(2)
        old = Bucket.fresh(5, [], [a], [])      # ancient protocol
        new = Bucket.fresh(5, [], [b], [])
        assert merge_buckets(old, new).meta_protocol == 0  # pre-11: no meta
        set_newest_merge_logic(True)
        m = merge_buckets(old, new)
        assert m.meta_protocol == NEWEST_LEDGER_PROTOCOL
    finally:
        set_newest_merge_logic(False)


def test_persist_index_sidecar(tmp_path):
    from stellar_core_tpu.bucket.bucket import Bucket
    from stellar_core_tpu.bucket.bucket_index import set_persist_index
    import os

    try:
        set_persist_index(True)
        entries = _mk_accounts(50, salt=1)
        b = Bucket.fresh(21, [], entries, [])
        path = str(tmp_path / f"bucket-{b.hash.hex()}.xdr")
        b.write_to(path, fsync=False)
        from stellar_core_tpu.xdr.ledger_entries import ledger_entry_key
        key = ledger_entry_key(entries[7])
        assert b.get(key) is not None
        assert os.path.exists(path + ".idx")
        # a fresh bucket object reloads the sidecar and answers lookups
        b2 = Bucket.from_file(path)
        assert b2.get(key) is not None
        assert b2.get(ledger_entry_key(entries[23])) is not None
    finally:
        set_persist_index(False)


def test_enable_flow_control_bytes_off():
    from stellar_core_tpu.overlay.flow_control import FlowControl
    from stellar_core_tpu.xdr.overlay import MessageType, StellarMessage

    cfg = get_test_config()
    cfg.ENABLE_FLOW_CONTROL_BYTES = False
    with Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                            get_test_config()) as app:
        app.start()
        master = m1.master_account(app)
        frame = master.tx([op_payment(master.muxed, 1)])
    msg = StellarMessage(MessageType.TRANSACTION, frame.envelope)
    fc = FlowControl(cfg)
    fc.remote_capacity_msgs = 1
    fc.remote_capacity_bytes = 0     # no byte credit at all
    # with byte accounting off, the message-count credit suffices
    assert fc.try_send(msg) is msg


def test_retry_suppression_knob_with_jitter(tmp_path):
    """RETRY_SUPPRESSION_SECONDS is a config knob (ISSUE 5 satellite):
    an identical catchup (target, lcl) retry is suppressed for the
    configured window stretched by per-node seeded jitter (+0..25%),
    and allowed again once the jittered window elapses."""
    from stellar_core_tpu.catchup.manager import RETRY_JITTER_FRAC
    from stellar_core_tpu.history.archive import make_tmpdir_archive

    cfg = get_test_config()
    cfg.RETRY_SUPPRESSION_SECONDS = 40.0
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    app = Application.create(clock, cfg)
    app.start()
    try:
        cm = app.catchup_manager
        app.history_manager.archives = [
            make_tmpdir_archive("t", str(tmp_path / "archive"))]
        # a buffered slot far beyond LCL+1: a real ledger gap
        app.herder._buffered_values[20] = object()
        assert cm.maybe_trigger_catchup() is True
        # the jittered window derives from the knob, not the module
        # default of 300
        assert 40.0 <= cm._suppression_window \
            <= 40.0 * (1 + RETRY_JITTER_FRAC)
        # the catchup "finished" but the gap remains: an identical
        # retry inside the window is suppressed
        cm._running = None
        assert cm.maybe_trigger_catchup() is False
        # ... and allowed once the jittered window elapses
        clock.set_virtual_time(
            cm._last_attempt_time + cm._suppression_window + 0.1)
        assert cm.maybe_trigger_catchup() is True
        assert cm.catchups_started == 2
    finally:
        app.herder._buffered_values.clear()
        app.shutdown()


def test_peer_deadline_knobs_load_from_config():
    """The socket-deadline and breaker knobs ride the standard config
    loader like every other knob."""
    from stellar_core_tpu.main.config import Config

    cfg = Config.from_dict({
        "PEER_CONNECT_TIMEOUT": 3.5,
        "PEER_AUTHENTICATION_TIMEOUT": 1.0,
        "PEER_TIMEOUT": 60.0,
        "RETRY_SUPPRESSION_SECONDS": 120.0,
        "VERIFY_BREAKER_FAILURE_THRESHOLD": 5,
        "VERIFY_DISPATCH_DEADLINE_MS": 500.0,
        "VERIFY_BREAKER_PROBE_BASE_MS": 250.0,
        "VERIFY_BREAKER_PROBE_MAX_MS": 4000.0,
        "VERIFY_BREAKER_CANARY_BATCH": 8,
    })
    assert cfg.PEER_CONNECT_TIMEOUT == 3.5
    assert cfg.PEER_AUTHENTICATION_TIMEOUT == 1.0
    assert cfg.PEER_TIMEOUT == 60.0
    assert cfg.RETRY_SUPPRESSION_SECONDS == 120.0
    assert cfg.VERIFY_BREAKER_FAILURE_THRESHOLD == 5
    assert cfg.VERIFY_DISPATCH_DEADLINE_MS == 500.0
    assert cfg.VERIFY_BREAKER_PROBE_BASE_MS == 250.0
    assert cfg.VERIFY_BREAKER_PROBE_MAX_MS == 4000.0
    assert cfg.VERIFY_BREAKER_CANARY_BATCH == 8
