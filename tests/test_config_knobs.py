"""Operator/testing config knobs wired to real behavior (VERDICT r03
missing #6): ARTIFICIALLY_* pessimization, apply-sleep weights,
flood-demand retry, maintenance tuning, SCP slot retention."""

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.overlay.loopback import LoopbackPeerConnection
from stellar_core_tpu.util.timer import ClockMode, VirtualClock

import test_standalone_app as m1
from txtest_utils import op_create_account, op_payment


def test_pessimized_merges_run_synchronously():
    cfg = get_test_config()
    cfg.ARTIFICIALLY_PESSIMIZE_MERGES_FOR_TESTING = True
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    try:
        assert app.bucket_manager.bucket_list._executor is None
        master = m1.master_account(app)
        dest = m1.AppAccount(app, SecretKey.from_seed(b"\x21" * 32))
        m1.submit(app, master.tx([op_create_account(dest.account_id,
                                                    10**11)]))
        for _ in range(10):     # crosses several spill boundaries
            app.manual_close()
        assert app.ledger_manager.get_last_closed_ledger_num() >= 11
    finally:
        app.shutdown()


def test_apply_sleep_weights_slow_the_close():
    import time
    cfg = get_test_config()
    cfg.OP_APPLY_SLEEP_TIME_WEIGHT_FOR_TESTING = [1]
    cfg.OP_APPLY_SLEEP_TIME_DURATION_FOR_TESTING = [25.0]  # ms per tx
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    try:
        master = m1.master_account(app)
        m1.submit(app, master.tx([op_create_account(
            SecretKey.from_seed(b"\x22" * 32).public_key().raw
            and m1.AppAccount(app, SecretKey.from_seed(b"\x22" * 32))
            .account_id, 10**11)]))
        t0 = time.monotonic()
        app.manual_close()
        assert time.monotonic() - t0 >= 0.025
    finally:
        app.shutdown()


def test_artificial_main_thread_sleep_poller():
    import time
    cfg = get_test_config()
    cfg.ARTIFICIALLY_SLEEP_MAIN_THREAD_FOR_TESTING_US = 5000
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    try:
        t0 = time.monotonic()
        for _ in range(4):
            app.clock.crank(False)
        assert time.monotonic() - t0 >= 0.015
    finally:
        app.shutdown()


def test_automatic_maintenance_timer_prunes_history():
    cfg = get_test_config()
    cfg.AUTOMATIC_MAINTENANCE_PERIOD = 30.0
    cfg.AUTOMATIC_MAINTENANCE_COUNT = 10_000
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    try:
        master = m1.master_account(app)
        dest = m1.AppAccount(app, SecretKey.from_seed(b"\x23" * 32))
        m1.submit(app, master.tx([op_create_account(dest.account_id,
                                                    10**12)]))
        app.manual_close()
        dest.sync_seq()
        for _ in range(200):
            m1.submit(app, dest.tx([op_payment(master.muxed, 5)]))
            app.manual_close()
        before = app.database.query_one(
            "SELECT COUNT(*) FROM txhistory")[0]
        app.clock.crank_for(35.0)      # maintenance timer fires
        after = app.database.query_one(
            "SELECT COUNT(*) FROM txhistory")[0]
        assert after < before
    finally:
        app.shutdown()


def test_flood_demand_retry_reroutes_to_another_peer():
    """A peer that never answers FLOOD_DEMAND must not strand the tx:
    after FLOOD_DEMAND_PERIOD_MS the demander re-demands from another
    peer that has it (reference: TxDemandsManager retry)."""
    from test_overlay import make_apps
    clock, apps = make_apps(3)
    try:
        conns = [LoopbackPeerConnection(apps[0], apps[1]),
                 LoopbackPeerConnection(apps[0], apps[2]),
                 LoopbackPeerConnection(apps[1], apps[2])]
        for c in conns:
            c.crank()
        # node0 ignores demands from node1 ONLY (node2 is served)
        om0 = apps[0].overlay_manager
        node1_side = conns[0].acceptor   # node1's peer object at node0?
        orig = om0._on_flood_demand
        blocked_peer = conns[0].initiator  # node0's peer toward node1

        def selective(peer, msg, _orig=orig, _blocked=blocked_peer):
            if peer is _blocked:
                return      # pretend the demand never arrived
            _orig(peer, msg)

        om0._on_flood_demand = selective
        # node2 receives the tx but never adverts it onward, so node1's
        # ONLY advert comes from node0 (whose demand path is dead) —
        # isolating the retry as node1's sole route to the body
        apps[2].herder.tx_advert_cb = None

        master = m1.master_account(apps[0])
        dest = m1.AppAccount(apps[0], SecretKey.from_seed(b"\x24" * 32))
        frame = master.tx([op_create_account(dest.account_id, 10**11)])
        assert m1.submit(apps[0], frame)["status"] == "PENDING"
        apps[0].overlay_manager.advert_transaction(frame.full_hash())

        def pump(seconds):
            deadline = clock.now() + seconds
            while clock.now() < deadline:
                for c in conns:
                    c.crank()
                if clock.crank(False) == 0:
                    clock.crank(True)

        pump(0.05)
        h = frame.full_hash()
        # node2 got it straight away; node1's demand went unanswered
        assert apps[2].herder.tx_queue.get_tx(h) is not None
        assert apps[1].herder.tx_queue.get_tx(h) is None
        # after the demand period, node1 re-demands from node2
        pump(2.0)
        assert apps[1].herder.tx_queue.get_tx(h) is not None
    finally:
        for app in apps:
            app.shutdown()


def test_max_slots_to_remember_bounds_envelope_window():
    cfg = get_test_config()
    cfg.MAX_SLOTS_TO_REMEMBER = 5
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    try:
        for _ in range(10):
            app.manual_close()
        from stellar_core_tpu.herder.pending_envelopes import RecvState
        from stellar_core_tpu.xdr.scp import SCPEnvelope
        lcl = app.ledger_manager.get_last_closed_ledger_num()
        app.herder.verify_envelope = lambda _e: True  # isolate the window
        env = SCPEnvelope.__new__(SCPEnvelope)

        class _Stmt:
            slotIndex = lcl - 6     # behind the 5-slot window
        env.statement = _Stmt()
        assert app.herder.recv_scp_envelope(env) == \
            RecvState.ENVELOPE_STATUS_DISCARDED
        # inside the window the same envelope gets past the gate (it
        # then fails deeper for being a stub, which is fine — the knob
        # under test is only the retention window)
        class _Stmt2:
            slotIndex = lcl - 4
        env2 = SCPEnvelope.__new__(SCPEnvelope)
        env2.statement = _Stmt2()
        try:
            r = app.herder.recv_scp_envelope(env2)
        except Exception:
            r = None
        assert r != RecvState.ENVELOPE_STATUS_DISCARDED or r is None
    finally:
        app.shutdown()
