"""Priority-aware byte-budgeted outbound queues (ISSUE 20 tentpole):
the three drop-priority classes (SCP > demanded tx > advert/gossip),
strict class-order drain with FIFO within a class, enqueue-time shed
from the lowest class first, the never-evict-SCP-for-lower-traffic
contract, high-water tracking against the budget, and the per-class
drop accounting the `peers` route and `overlay.flow.drop.*` serve."""

from stellar_core_tpu.overlay.flow_control import (
    CLASS_GOSSIP, CLASS_NAMES, CLASS_SCP, CLASS_TX, FlowControl,
    msg_body_size, msg_class)
from stellar_core_tpu.xdr.overlay import (FloodAdvert, FloodDemand,
                                          MessageType, StellarMessage)
from stellar_core_tpu.xdr.scp import (SCPEnvelope, SCPNomination,
                                      SCPStatement, SCPStatementType,
                                      _SCPStatementPledges)
from stellar_core_tpu.xdr.types import PublicKey

from test_flow_control_edges import cfg, grant, tx_msg


def scp_msg(tag=0, votes=0):
    """A flooded SCP_MESSAGE (nomination), padded via vote count."""
    env = SCPEnvelope(
        statement=SCPStatement(
            nodeID=PublicKey.ed25519(bytes([tag]) * 32),
            slotIndex=1,
            pledges=_SCPStatementPledges(
                SCPStatementType.SCP_ST_NOMINATE,
                SCPNomination(quorumSetHash=b"\x00" * 32,
                              votes=[b"\x01" * 32] * votes,
                              accepted=[]))),
        signature=b"\x00" * 64)
    return StellarMessage(MessageType.SCP_MESSAGE, env)


def advert_msg(n=1):
    return StellarMessage(MessageType.FLOOD_ADVERT,
                          FloodAdvert(txHashes=[b"\x05" * 32] * n))


def demand_msg(n=1):
    return StellarMessage(MessageType.FLOOD_DEMAND,
                          FloodDemand(txHashes=[b"\x06" * 32] * n))


# ------------------------------------------------------------ classes --

def test_msg_class_mapping():
    assert CLASS_NAMES == ("scp", "tx", "gossip")
    assert msg_class(scp_msg()) == CLASS_SCP == 0
    assert msg_class(tx_msg()) == CLASS_TX == 1
    assert msg_class(advert_msg()) == CLASS_GOSSIP == 2
    assert msg_class(demand_msg()) == CLASS_GOSSIP


def test_drain_priority_scp_then_tx_then_gossip():
    """A grant drains strictly SCP -> tx -> gossip, FIFO within a
    class — regardless of arrival order."""
    fc = FlowControl(cfg())
    g1, t1, t2, s1 = advert_msg(), tx_msg(), tx_msg(1), scp_msg()
    for m in (g1, t1, t2, s1):            # no credit yet: all queue
        assert fc.try_send(m) is None
    assert fc.outbound_queue_len() == 4
    out = grant(fc, 10, 1_000_000)
    assert out == [s1, t1, t2, g1]
    assert fc.outbound_queue_len() == 0 and fc.queued_bytes() == 0


def test_class_head_blocks_only_its_own_class():
    """An SCP head too big for the byte grant blocks only the SCP
    class — a small tx still flows — and the head keeps first claim on
    the next grant."""
    fc = FlowControl(cfg())
    big_scp = scp_msg(votes=40)
    small_tx = tx_msg()
    assert msg_body_size(big_scp) > msg_body_size(small_tx)
    assert fc.try_send(big_scp) is None
    assert fc.try_send(small_tx) is None
    out = grant(fc, 2, msg_body_size(small_tx))
    assert out == [small_tx]
    out = grant(fc, 1, msg_body_size(big_scp))
    assert out == [big_scp]


def test_fifo_within_class_never_overtakes():
    """With credit available but an earlier same-class message queued,
    a new message queues BEHIND it; a different (empty) class may
    still pass immediately."""
    fc = FlowControl(cfg())
    t1 = tx_msg()
    assert fc.try_send(t1) is None        # no credit: queues
    fc.remote_capacity_msgs = 5
    fc.remote_capacity_bytes = 1_000_000
    t2 = tx_msg(1)
    assert fc.try_send(t2) is None        # credit, but t1 is ahead
    s = scp_msg()
    assert fc.try_send(s) is s            # SCP class empty: immediate
    assert fc.on_send_more(0, 0) == [t1, t2]


# ------------------------------------------------------- byte budget --

def test_budget_sheds_lowest_class_first():
    c = cfg()
    s, t = scp_msg(), tx_msg()
    # size the gossip head to cover ONE tx of headroom but not two, so
    # the second overflow must reach into the tx class
    n = 1
    while msg_body_size(advert_msg(n)) < msg_body_size(t):
        n += 1
    g = advert_msg(n)
    assert msg_body_size(t) <= msg_body_size(g) < 2 * msg_body_size(t)
    c.OUTBOUND_QUEUE_BYTE_LIMIT = (msg_body_size(s) + msg_body_size(t)
                                   + msg_body_size(g))
    fc = FlowControl(c)
    for m in (g, t, s):
        assert fc.try_send(m) is None
    assert fc.dropped == [0, 0, 0]        # exactly at budget: no shed
    # one more tx pushes past the budget: the gossip head sheds first
    t2 = tx_msg()
    assert fc.try_send(t2) is None
    assert fc.dropped == [0, 0, 1]
    # past the budget again with gossip empty: the OLDEST tx sheds
    t3 = tx_msg()
    assert fc.try_send(t3) is None
    assert fc.dropped == [0, 1, 1]
    # SCP survived both sheds and still drains first
    out = grant(fc, 10, 1_000_000)
    assert out[0] is s
    assert fc.dropped[CLASS_SCP] == 0


def test_scp_never_shed_for_lower_class():
    """tx/gossip never evict SCP: an incoming tx past an all-SCP
    budget sheds ITSELF; only an incoming SCP envelope may shed older
    SCP (the budget is then all consensus traffic)."""
    c = cfg()
    s1, s2 = scp_msg(1), scp_msg(2)
    c.OUTBOUND_QUEUE_BYTE_LIMIT = msg_body_size(s1) + msg_body_size(s2)
    fc = FlowControl(c)
    assert fc.try_send(s1) is None and fc.try_send(s2) is None
    t = tx_msg()
    assert fc.try_send(t) is None
    assert fc.dropped[CLASS_SCP] == 0
    assert fc.dropped[CLASS_TX] == 1      # the incoming tx itself
    assert fc.outbound_queue_len() == 2
    s3 = scp_msg(3)
    assert fc.try_send(s3) is None
    assert fc.dropped[CLASS_SCP] == 1     # oldest SCP made room
    assert grant(fc, 10, 1_000_000) == [s2, s3]


def test_zero_budget_disables_total_cap():
    c = cfg()
    c.OUTBOUND_QUEUE_BYTE_LIMIT = 0
    fc = FlowControl(c)
    for _ in range(50):
        assert fc.try_send(advert_msg(4)) is None
    assert fc.outbound_queue_len() == 50
    assert fc.dropped == [0, 0, 0]


# ----------------------------------------------------- observability --

class _Counter:
    def __init__(self):
        self.n = 0

    def inc(self):
        self.n += 1


def test_drop_counters_and_flow_stats():
    """Sheds land on the shared `overlay.flow.drop.<class>` counters
    AND the per-peer flow_stats row the `peers` route serves."""
    counters = (_Counter(), _Counter(), _Counter())
    c = cfg()
    g = advert_msg(12)
    c.OUTBOUND_QUEUE_BYTE_LIMIT = msg_body_size(g)
    fc = FlowControl(c, drop_counters=counters)
    assert fc.try_send(g) is None
    assert fc.try_send(advert_msg(12)) is None   # sheds the older one
    assert counters[CLASS_GOSSIP].n == 1
    assert counters[CLASS_SCP].n == 0 and counters[CLASS_TX].n == 0
    st = fc.flow_stats()
    assert st["queue_budget"] == c.OUTBOUND_QUEUE_BYTE_LIMIT
    assert st["queue_high_water"] == msg_body_size(g)
    assert st["queued_msgs"] == 1
    assert st["queued_bytes"] == msg_body_size(g)
    assert st["drops"] == {"scp": 0, "tx": 0, "gossip": 1}


def test_high_water_tracks_peak_not_current():
    c = cfg()
    c.OUTBOUND_QUEUE_BYTE_LIMIT = 1_000_000
    fc = FlowControl(c)
    msgs = [advert_msg(2) for _ in range(3)]
    for m in msgs:
        assert fc.try_send(m) is None
    peak = fc.queued_bytes()
    grant(fc, 10, 1_000_000)              # drain everything
    assert fc.queued_bytes() == 0
    assert fc.flow_stats()["queue_high_water"] == peak
