"""Work framework, process manager, history publish, and catchup tests
(reference: work/test/WorkTests, history/test/HistoryTests —
TmpDirHistoryConfigurator archives, publish + catchup round trips).
"""

import os

import pytest

from stellar_core_tpu.catchup import (ApplyBucketsWork,
                                      CatchupConfiguration, CatchupWork,
                                      GetHistoryArchiveStateWork)
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.history import (CHECKPOINT_FREQUENCY,
                                      HistoryArchiveState,
                                      checkpoint_containing,
                                      is_checkpoint_ledger,
                                      make_tmpdir_archive)
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.work import (BasicWork, State, WorkSequence,
                                   run_work_to_completion)

import test_standalone_app as m1
from txtest_utils import op_create_account, op_payment


# ------------------------------------------------------------------ work --

class _FlakyWork(BasicWork):
    """Fails n times then succeeds."""

    def __init__(self, app, fail_times: int, max_retries: int = 5):
        super().__init__(app, "flaky", max_retries)
        self.fail_times = fail_times
        self.attempts = 0

    def on_run(self) -> State:
        self.attempts += 1
        if self.attempts <= self.fail_times:
            return State.WORK_FAILURE
        return State.WORK_SUCCESS


def _mini_app():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    cfg = get_test_config()
    app = Application.create(clock, cfg)
    app.start()
    return app


def test_work_retries_until_success():
    app = _mini_app()
    try:
        w = _FlakyWork(app, fail_times=2)
        assert run_work_to_completion(app, w) == State.WORK_SUCCESS
        assert w.attempts == 3
    finally:
        app.shutdown()


def test_work_fails_after_max_retries():
    app = _mini_app()
    try:
        w = _FlakyWork(app, fail_times=10, max_retries=2)
        assert run_work_to_completion(app, w) == State.WORK_FAILURE
        assert w.attempts == 3  # initial + 2 retries
    finally:
        app.shutdown()


def test_work_sequence_order():
    app = _mini_app()
    try:
        order = []

        class _W(BasicWork):
            def __init__(self, app, tag):
                super().__init__(app, f"w{tag}", 0)
                self.tag = tag

            def on_run(self):
                order.append(self.tag)
                return State.WORK_SUCCESS

        seq = WorkSequence(app, "seq", [_W(app, i) for i in range(4)])
        assert run_work_to_completion(app, seq) == State.WORK_SUCCESS
        assert order == [0, 1, 2, 3]
    finally:
        app.shutdown()


def test_process_manager_runs_commands(tmp_path):
    app = _mini_app()
    try:
        import time as _time

        def wait_for(lst, timeout=10.0):
            deadline = _time.monotonic() + timeout
            while not lst and _time.monotonic() < deadline:
                app.clock.crank(False)
                _time.sleep(0.01)  # subprocesses run in real time

        done = []
        out = tmp_path / "touched"
        app.process_manager.run_process(
            f"touch {out}", lambda code: done.append(code))
        wait_for(done)
        assert done == [0] and out.exists()
        # failing command reports nonzero
        done2 = []
        app.process_manager.run_process(
            "false", lambda code: done2.append(code))
        wait_for(done2)
        assert done2 and done2[0] != 0
    finally:
        app.shutdown()


# ------------------------------------------------------------ checkpoints --

def test_checkpoint_math():
    assert is_checkpoint_ledger(63)
    assert is_checkpoint_ledger(127)
    assert not is_checkpoint_ledger(64)
    assert checkpoint_containing(1) == 63
    assert checkpoint_containing(63) == 63
    assert checkpoint_containing(64) == 127


# --------------------------------------------------------------- publish --

def make_publishing_app(tmp_path, n_ledgers=130):
    """Standalone node with a tmpdir archive, closing n ledgers with
    scattered payments."""
    archive_root = str(tmp_path / "archive")
    cfg = get_test_config()
    cfg.HISTORY = {"test": {
        "get": f"cp {archive_root}/{{0}} {{1}}",
        "put": f"mkdir -p $(dirname {archive_root}/{{1}}) && "
               f"cp {{0}} {archive_root}/{{1}}",
    }}
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    app = Application.create(clock, cfg)
    app.start()
    master = m1.master_account(app)
    dests = [m1.AppAccount(app, SecretKey.from_seed(bytes([i]) * 32))
             for i in range(1, 6)]
    for d in dests:
        m1.submit(app, master.tx([op_create_account(d.account_id,
                                                    10**12)]))
    app.manual_close()
    for d in dests:
        d.sync_seq()
    for seq in range(3, n_ledgers + 1):
        if seq % 7 == 0:
            d = dests[seq % len(dests)]
            m1.submit(app, d.tx([op_payment(master.muxed, 1000)]))
        app.manual_close()
    return app, make_tmpdir_archive("test", archive_root), archive_root


def test_publish_writes_checkpoints(tmp_path):
    app, archive, root = make_publishing_app(tmp_path)
    try:
        assert app.history_manager.published_count == 2  # cp 63, 127
        assert os.path.exists(os.path.join(
            root, ".well-known/stellar-history.json"))
        with open(os.path.join(root,
                               ".well-known/stellar-history.json")) as f:
            has = HistoryArchiveState.from_json(f.read())
        assert has.current_ledger == 127
        assert os.path.exists(os.path.join(
            root, "ledger/00/00/00/ledger-0000007f.xdr.gz"))
        assert os.path.exists(os.path.join(
            root, "transactions/00/00/00/transactions-0000007f.xdr.gz"))
        for hex_hash in has.bucket_hashes():
            assert os.path.exists(os.path.join(
                root, f"bucket/{hex_hash[:2]}/{hex_hash[2:4]}/"
                      f"{hex_hash[4:6]}/bucket-{hex_hash}.xdr.gz"))
    finally:
        app.shutdown()


# --------------------------------------------------------------- catchup --

def test_catchup_complete_replay(tmp_path):
    """Fresh node replays the whole published history and lands on the
    identical chain (north-star path, SURVEY.md §3.3)."""
    app_a, archive, root = make_publishing_app(tmp_path)
    try:
        hash_a = bytes(app_a.database.query_one(
            "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=127")[0])
        master_balance_a = m1.app_account_entry(
            app_a, m1.master_account(app_a).account_id).balance

        cfg_b = get_test_config()
        cfg_b.NETWORK_PASSPHRASE = app_a.config.NETWORK_PASSPHRASE
        clock_b = VirtualClock(ClockMode.VIRTUAL_TIME)
        app_b = Application.create(clock_b, cfg_b)
        app_b.start()
        try:
            work = CatchupWork(app_b, archive,
                               CatchupConfiguration(to_ledger=0))
            assert run_work_to_completion(app_b, work,
                                          timeout_virtual=3000) == \
                State.WORK_SUCCESS
            assert app_b.ledger_manager.get_last_closed_ledger_num() == 127
            assert app_b.ledger_manager.get_last_closed_ledger_hash() == \
                hash_a
            bal_b = m1.app_account_entry(
                app_b, m1.master_account(app_b).account_id).balance
            assert bal_b == master_balance_a
        finally:
            app_b.shutdown()
    finally:
        app_a.shutdown()


def test_catchup_minimal_bucket_apply(tmp_path):
    """Bucket-apply fast-forward assumes checkpoint state without
    replay."""
    app_a, archive, root = make_publishing_app(tmp_path)
    try:
        hash_a = bytes(app_a.database.query_one(
            "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=127")[0])

        cfg_c = get_test_config()
        cfg_c.NETWORK_PASSPHRASE = app_a.config.NETWORK_PASSPHRASE
        clock_c = VirtualClock(ClockMode.VIRTUAL_TIME)
        app_c = Application.create(clock_c, cfg_c)
        # do NOT start (no genesis): state comes purely from buckets
        try:
            has_work = GetHistoryArchiveStateWork(app_c, archive)
            assert run_work_to_completion(app_c, has_work) == \
                State.WORK_SUCCESS
            import tempfile
            work = ApplyBucketsWork(app_c, archive, has_work.has,
                                    tempfile.mkdtemp(prefix="ab-"))
            assert run_work_to_completion(app_c, work,
                                          timeout_virtual=1000) == \
                State.WORK_SUCCESS
            assert app_c.ledger_manager.get_last_closed_ledger_num() == 127
            assert app_c.ledger_manager.get_last_closed_ledger_hash() == \
                hash_a
            # an account created in ledger 2 exists with its balance
            dest = m1.AppAccount(app_c, SecretKey.from_seed(b"\x01" * 32))
            acc = m1.app_account_entry(app_c, dest.account_id)
            assert acc is not None
        finally:
            app_c.shutdown()
    finally:
        app_a.shutdown()


def test_catchup_to_specific_ledger(tmp_path):
    app_a, archive, root = make_publishing_app(tmp_path)
    try:
        cfg_b = get_test_config()
        cfg_b.NETWORK_PASSPHRASE = app_a.config.NETWORK_PASSPHRASE
        app_b = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                                   cfg_b)
        app_b.start()
        try:
            work = CatchupWork(app_b, archive,
                               CatchupConfiguration(to_ledger=63))
            assert run_work_to_completion(app_b, work,
                                          timeout_virtual=3000) == \
                State.WORK_SUCCESS
            assert app_b.ledger_manager.get_last_closed_ledger_num() == 63
            hash_a63 = bytes(app_a.database.query_one(
                "SELECT ledgerhash FROM ledgerheaders "
                "WHERE ledgerseq=63")[0])
            assert app_b.ledger_manager.get_last_closed_ledger_hash() == \
                hash_a63
        finally:
            app_b.shutdown()
    finally:
        app_a.shutdown()


def test_catchup_with_tpu_batch_prevalidation(tmp_path):
    """The north-star path: checkpoint signatures batch-verified on the
    device before apply; identical chain, near-zero sync fallbacks
    (SURVEY.md §3.3)."""
    from stellar_core_tpu.ops.verifier import TpuBatchVerifier

    app_a, archive, root = make_publishing_app(tmp_path)
    try:
        hash_a = bytes(app_a.database.query_one(
            "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=127")[0])
        cfg_b = get_test_config()
        cfg_b.NETWORK_PASSPHRASE = app_a.config.NETWORK_PASSPHRASE
        cfg_b.SIGNATURE_VERIFY_BACKEND = "tpu"
        app_b = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                                   cfg_b)
        app_b.start()
        try:
            # long batch_grace: the test must deterministically observe
            # the batch results being consumed (production default is a
            # 50ms bounded stall with sync fallback)
            work = CatchupWork(app_b, archive,
                               CatchupConfiguration(to_ledger=0),
                               batch_grace=60.0)
            assert work.batch_verifier is not None
            assert run_work_to_completion(app_b, work,
                                          timeout_virtual=3000) == \
                State.WORK_SUCCESS
            assert app_b.ledger_manager.get_last_closed_ledger_num() == 127
            assert app_b.ledger_manager.get_last_closed_ledger_hash() == \
                hash_a
            # the batch actually carried the verifies
            hits = sum(cw.prevalidated.hits
                       for cw in work.applied_checkpoints
                       if cw.prevalidated is not None)
            misses = sum(cw.prevalidated.misses
                         for cw in work.applied_checkpoints
                         if cw.prevalidated is not None)
            assert hits > 0
            assert misses == 0  # single-signer txs: all cache hits
        finally:
            app_b.shutdown()
    finally:
        app_a.shutdown()


def feed_externalized_slot(app_a, app_b, seq):
    """Hand app_b the externalized value + tx set for app_a's ledger
    `seq`, as the overlay would after SCP externalizes."""
    from stellar_core_tpu.herder.tx_set import TxSetFrame
    from stellar_core_tpu.xdr.ledger import (GeneralizedTransactionSet,
                                             LedgerHeader, TransactionSet)
    hdr_row = app_a.database.query_one(
        "SELECT data FROM ledgerheaders WHERE ledgerseq=?", (seq,))
    header = LedgerHeader.from_bytes(bytes(hdr_row[0]))
    set_row = app_a.database.query_one(
        "SELECT isgeneralized, txset FROM txsethistory "
        "WHERE ledgerseq=?", (seq,))
    xdr_set = GeneralizedTransactionSet.from_bytes(
        bytes(set_row[1])) if set_row[0] else \
        TransactionSet.from_bytes(bytes(set_row[1]))
    frame = TxSetFrame(xdr_set, app_b.config.network_id())
    app_b.herder.pending_envelopes.add_tx_set(
        frame.get_contents_hash(), frame)
    app_b.herder.value_externalized_from_scp(
        seq, header.scpValue.to_bytes())


def test_out_of_sync_node_recovers_via_catchup(tmp_path):
    """A node far behind the network buffers an externalized value with
    a ledger gap, the CatchupManager fills the gap from the archive, and
    the buffered ledgers then apply (reference: CatchupManagerImpl +
    herder tracking states, SURVEY.md §5.3)."""
    app_a, archive, root = make_publishing_app(tmp_path, n_ledgers=130)
    try:
        # node A closes one more ledger beyond the checkpoint
        app_a.manual_close()  # 131
        assert app_a.ledger_manager.get_last_closed_ledger_num() == 131

        # node B: fresh, same network, archive configured for reads
        cfg_b = get_test_config()
        cfg_b.NETWORK_PASSPHRASE = app_a.config.NETWORK_PASSPHRASE
        # get-only: a catching-up node must not overwrite the
        # archive another node writes (one writer per archive)
        cfg_b.HISTORY = {n: {"get": c["get"]}
                         for n, c in app_a.config.HISTORY.items()}
        app_b = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                                   cfg_b)
        app_b.start()
        try:
            # hand B the externalized values for slots 128..131 with a
            # gap (B is at ledger 1): values rebuilt from A's chain
            for seq in (128, 129, 130, 131):
                feed_externalized_slot(app_a, app_b, seq)

            # gap detected → catchup runs → buffered values drain
            assert app_b.catchup_manager.catchups_started == 1
            import time as _time
            deadline = _time.monotonic() + 60
            while app_b.ledger_manager.get_last_closed_ledger_num() < 131 \
                    and _time.monotonic() < deadline:
                if app_b.clock.crank(False) == 0:
                    _time.sleep(0.002)  # archive `cp` runs in real time
            assert app_b.ledger_manager.get_last_closed_ledger_num() == 131
            assert app_b.ledger_manager.get_last_closed_ledger_hash() == \
                app_a.ledger_manager.get_last_closed_ledger_hash()
        finally:
            app_b.shutdown()
    finally:
        app_a.shutdown()


def test_catchup_to_midcheckpoint_target_then_second_gap(tmp_path):
    """Catchup must stop exactly at the requested target ledger even
    mid-checkpoint (no overshoot past buffered slots), and a later gap
    must trigger a second catchup (regression: a stale buffered entry
    used to wedge gap detection forever)."""
    app_a, archive, root = make_publishing_app(tmp_path, n_ledgers=130)
    try:
        cfg_b = get_test_config()
        cfg_b.NETWORK_PASSPHRASE = app_a.config.NETWORK_PASSPHRASE
        # get-only: a catching-up node must not overwrite the
        # archive another node writes (one writer per archive)
        cfg_b.HISTORY = {n: {"get": c["get"]}
                         for n, c in app_a.config.HISTORY.items()}
        app_b = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                                   cfg_b)
        app_b.start()

        def feed_slot(seq):
            feed_externalized_slot(app_a, app_b, seq)

        def crank_until_lcl(target):
            import time as _time
            deadline = _time.monotonic() + 60
            while app_b.ledger_manager.get_last_closed_ledger_num() \
                    < target and _time.monotonic() < deadline:
                if app_b.clock.crank(False) == 0:
                    _time.sleep(0.002)

        try:
            # slot 100 is mid-checkpoint (checkpoints end at 63, 127)
            feed_slot(100)
            assert app_b.catchup_manager.catchups_started == 1
            crank_until_lcl(100)
            # catchup replayed exactly to 99, then the buffered slot
            # 100 applied — NOT the whole checkpoint through 127
            assert app_b.ledger_manager.get_last_closed_ledger_num() \
                == 100
            assert not app_b.herder._buffered_values

            # a later gap must still be detected and recovered
            feed_slot(125)
            assert app_b.catchup_manager.catchups_started == 2
            crank_until_lcl(125)
            assert app_b.ledger_manager.get_last_closed_ledger_num() \
                == 125
            row = app_a.database.query_one(
                "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=?",
                (125,))
            assert app_b.ledger_manager.get_last_closed_ledger_hash() \
                == bytes(row[0])
        finally:
            app_b.shutdown()
    finally:
        app_a.shutdown()


# ------------------------------------------------- tx-results verification --

def _rewrite_results_file(root, checkpoint, mutate):
    """Load, mutate, and re-gzip one archived results file."""
    import gzip
    import io as _io
    from stellar_core_tpu.history.archive import file_path
    from stellar_core_tpu.util.xdr_stream import read_record, write_record
    from stellar_core_tpu.xdr.ledger import TransactionHistoryResultEntry
    path = os.path.join(root, file_path("results", checkpoint))
    entries = []
    with gzip.open(path, "rb") as f:
        bio = _io.BytesIO(f.read())
    while True:
        rec = read_record(bio)
        if rec is None:
            break
        entries.append(TransactionHistoryResultEntry.from_bytes(rec))
    mutate(entries)
    out = _io.BytesIO()
    for e in entries:
        write_record(out, e.to_bytes())
    with gzip.open(path, "wb") as f:
        f.write(out.getvalue())


def test_catchup_rejects_results_diverging_from_headers(tmp_path, caplog):
    """Archived results that do not hash to the signed header chain fail
    catchup at download-verify time, naming the ledger (reference:
    historywork/VerifyTxResultsWork.cpp)."""
    app_a, archive, root = make_publishing_app(tmp_path)
    try:
        def corrupt(entries):
            assert entries, "expected archived results"
            res = entries[0].txResultSet.results[0].result
            res.feeCharged += 1          # silent history tamper
        _rewrite_results_file(root, 127, corrupt)

        cfg_b = get_test_config()
        cfg_b.NETWORK_PASSPHRASE = app_a.config.NETWORK_PASSPHRASE
        app_b = Application.create(
            VirtualClock(ClockMode.VIRTUAL_TIME), cfg_b)
        app_b.start()
        try:
            work = CatchupWork(app_b, archive,
                               CatchupConfiguration(to_ledger=0))
            with caplog.at_level("ERROR"):
                final = run_work_to_completion(app_b, work,
                                               timeout_virtual=3000)
            assert final == State.WORK_FAILURE
            assert any("do not match the signed header chain" in r.message
                       for r in caplog.records)
        finally:
            app_b.shutdown()
    finally:
        app_a.shutdown()


def test_replay_divergence_fails_at_offending_ledger(tmp_path, caplog):
    """If the (header-consistent) archive disagrees with what replay
    produces, catchup fails AT the offending ledger and names the tx
    (reference: DownloadVerifyTxResultsWork anchoring the replay).
    Simulated by injecting a verified-but-wrong results anchor."""
    from stellar_core_tpu.catchup.catchup_work import (
        DownloadVerifyTxResultsWork)

    app_a, archive, root = make_publishing_app(tmp_path)
    try:
        cfg_b = get_test_config()
        cfg_b.NETWORK_PASSPHRASE = app_a.config.NETWORK_PASSPHRASE
        app_b = Application.create(
            VirtualClock(ClockMode.VIRTUAL_TIME), cfg_b)
        app_b.start()
        try:
            work = CatchupWork(app_b, archive,
                               CatchupConfiguration(to_ledger=0))

            # let catchup build its checkpoint works, then replace the
            # first checkpoint's anchor with a doctored one
            from stellar_core_tpu.work import run_work_to_completion
            clock = app_b.clock

            def crank_until(pred, limit=20000):
                import time as _time
                work.start_work(None)
                for _ in range(limit):
                    work.crank_work()
                    if pred() or work.is_done():
                        return
                    if clock.crank(False) == 0:
                        clock.crank(True)
                        _time.sleep(0.002)  # archive cp runs in real time

            crank_until(lambda: work.applied_checkpoints)
            assert work.applied_checkpoints
            acw = work.applied_checkpoints[0]
            rw = acw.results_work
            # run the real anchor to completion, then poison one entry
            import time as _time
            while not rw.is_done():
                rw.ensure_started(acw.wake_up)
                rw.crank_work()
                if clock.crank(False) == 0:
                    clock.crank(True)
                    _time.sleep(0.002)
            assert rw.get_state() == State.WORK_SUCCESS
            poisoned_seq = sorted(rw.results_by_seq)[0]
            # simulate a replay that diverges from (self-consistent)
            # verified history: doctor the expected results AND the
            # verified header's result hash together, as a divergent
            # network's archive would carry them
            from stellar_core_tpu.crypto.sha import sha256
            entry = rw.results_by_seq[poisoned_seq]
            entry.txResultSet.results[0].result.feeCharged += 1
            acw.headers[poisoned_seq].header.txSetResultHash = \
                sha256(entry.txResultSet.to_bytes())

            import time as _time
            with caplog.at_level("ERROR"):
                for _ in range(40000):
                    if work.is_done():
                        break
                    work.crank_work()
                    if clock.crank(False) == 0:
                        clock.crank(True)
                        _time.sleep(0.002)
            assert work.get_state() == State.WORK_FAILURE
            msgs = [r.message for r in caplog.records]
            assert any(f"replay diverged at ledger {poisoned_seq}" in m
                       for m in msgs), msgs
            # replay stopped AT the offending ledger, not at the end
            assert app_b.ledger_manager.get_last_closed_ledger_num() \
                == poisoned_seq
        finally:
            app_b.shutdown()
    finally:
        app_a.shutdown()


# ------------------------------- recent-qsets + single-header audits --

def test_check_single_ledger_header_work(tmp_path):
    """Archive audit (reference: CheckSingleLedgerHeaderWork.cpp): an
    archived header matching the trusted hash passes; a divergent hash
    fails loudly."""
    from stellar_core_tpu.catchup.catchup_work import (
        CheckSingleLedgerHeaderWork)
    app, archive, root = make_publishing_app(tmp_path)
    try:
        row = app.database.query_one(
            "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=100")
        good = CheckSingleLedgerHeaderWork(
            app, archive, 100, bytes(row[0]), str(tmp_path / "dl1"))
        assert run_work_to_completion(app, good) == State.WORK_SUCCESS
        bad = CheckSingleLedgerHeaderWork(
            app, archive, 100, b"\x13" * 32, str(tmp_path / "dl2"))
        assert run_work_to_completion(app, bad) == State.WORK_FAILURE
    finally:
        app.shutdown()


def test_fetch_recent_qsets_work(tmp_path):
    """SCP-state recovery from archives (reference:
    FetchRecentQsetsWork.cpp): a fresh node learns the validators'
    quorum sets from the published SCP files."""
    from stellar_core_tpu.catchup.catchup_work import FetchRecentQsetsWork
    from stellar_core_tpu.scp import local_node as ln
    from stellar_core_tpu.simulation import topologies

    archive_root = str(tmp_path / "archive")

    def cfg_gen(cfg):
        if cfg.PEER_PORT == 35000:     # only node 0 publishes
            cfg.HISTORY = {"sim": {
                "get": f"cp {archive_root}/{{0}} {{1}}",
                "put": f"mkdir -p $(dirname {archive_root}/{{1}}) && "
                       f"cp {{0}} {archive_root}/{{1}}",
            }}

    sim = topologies.core(3, configure=cfg_gen)
    try:
        sim.start_all_nodes()
        assert sim.crank_until(
            lambda: sim.have_all_externalized(66),
            timeout_virtual_seconds=600), "quorum stalled"
        # let the publish subprocess finish (real time)
        import time as _time
        deadline = _time.monotonic() + 20
        app0 = sim.apps()[0]
        while app0.history_manager.published_count < 1 and \
                _time.monotonic() < deadline:
            sim.clock.crank(False)
            _time.sleep(0.02)
        assert app0.history_manager.published_count >= 1
    finally:
        sim.stop_all_nodes()

    from stellar_core_tpu.history import make_tmpdir_archive
    archive = make_tmpdir_archive("sim", archive_root)
    app = _mini_app()
    try:
        work = FetchRecentQsetsWork(app, archive, str(tmp_path / "dl"))
        assert run_work_to_completion(app, work) == State.WORK_SUCCESS
        # all three validators inferred, pinning the shared qset
        assert len(work.inferred) == 3
        qhashes = set(work.inferred.values())
        assert len(qhashes) == 1
        qh = qhashes.pop()
        assert qh in work.qsets
        # and persisted for the local herder to consult
        row = app.database.query_one(
            "SELECT qset FROM scpquorums WHERE qsethash=?", (qh,))
        assert row is not None
        assert ln.qset_hash(work.qsets[qh]) == qh
    finally:
        app.shutdown()
