"""Native XDR codec (_scxdr) differential tests.

The C schema-program interpreter (native/src/pyext/xdr_codec.cpp) must
be byte- and semantics-identical to the Python runtime — the Python
path is the oracle (and the fallback when the extension can't build).
Reference analogue: xdrpp's generated codecs are exercised by every
wire-format test; here the two codecs cross-check each other
(src/Makefile.am:46-51).
"""

import random

import pytest

from stellar_core_tpu.main.fuzzer import XdrGenerator
from stellar_core_tpu.xdr import runtime
from stellar_core_tpu.xdr.ledger import (LedgerCloseMeta, LedgerHeader,
                                         TransactionMeta)
from stellar_core_tpu.xdr.ledger_entries import LedgerEntry, LedgerKey
from stellar_core_tpu.xdr.overlay import StellarMessage
from stellar_core_tpu.xdr.results import TransactionResult
from stellar_core_tpu.xdr.scp import SCPEnvelope
from stellar_core_tpu.xdr.transaction import TransactionEnvelope

CORPUS_TYPES = [TransactionEnvelope, LedgerEntry, LedgerKey,
                TransactionResult, SCPEnvelope, StellarMessage,
                LedgerHeader, TransactionMeta, LedgerCloseMeta]


def _nc():
    nc = runtime._nc()
    if nc is None:
        pytest.skip("native XDR codec unavailable in this environment")
    return nc


def _py_pack(v) -> bytes:
    w = runtime.Writer()
    v._pack(w)
    return bytes(w.buf)


def test_differential_pack_unpack_clone_corpus():
    nc = _nc()
    for seed in range(40):
        gen = XdrGenerator(random.Random(seed))
        for cls in CORPUS_TYPES:
            try:
                v = gen.gen(cls)
            except runtime.XdrError:
                # depth bottom-out can hit unions whose zero-value
                # switch isn't an arm (e.g. _FeeBumpInnerTx) — skip
                continue
            pb = _py_pack(v)
            nb = nc.pack(nc.cap, cls._nidx, v)
            assert nb == pb, (cls.__name__, seed)

            # native unpack == python unpack, and re-packs identically
            nv = nc.unpack(nc.cap, cls._nidx, nb)
            pv = cls._unpack(runtime.Reader(pb))
            assert nv == pv == v
            assert nc.pack(nc.cap, cls._nidx, nv) == pb

            # clone: equal, distinct identity, deep
            cv = nc.clone(nc.cap, cls._nidx, v)
            assert cv == v and cv is not v


def test_native_clone_is_deep():
    from stellar_core_tpu.xdr.transaction import (Memo, MemoType,
                                                  MuxedAccount,
                                                  Preconditions,
                                                  PreconditionType,
                                                  Transaction, _TxExt)
    nc = _nc()
    tx = Transaction(
        sourceAccount=MuxedAccount.from_ed25519(b"\x01" * 32),
        fee=100, seqNum=7,
        cond=Preconditions(PreconditionType.PRECOND_NONE),
        memo=Memo(MemoType.MEMO_NONE), operations=[], ext=_TxExt(0))
    c = nc.clone(nc.cap, Transaction._nidx, tx)
    assert c == tx
    c.fee = 999
    c.sourceAccount.value = b"\x02" * 32
    assert tx.fee == 100
    assert tx.sourceAccount.value == b"\x01" * 32


def test_malformed_rejected_identically():
    nc = _nc()
    cases = [
        # short input
        (LedgerKey, b"\x00\x00"),
        # invalid enum discriminant
        (LedgerKey, (0x7FFFFFF0).to_bytes(4, "big") + b"\x00" * 32),
        # trailing bytes after a full value
        (TransactionResult, b"\x00" * 200),
    ]
    for cls, raw in cases:
        with pytest.raises(runtime.XdrError):
            cls.from_bytes(raw)   # dispatches native, falls back python
        # the native path itself must also reject
        with pytest.raises(Exception):
            nc.unpack(nc.cap, cls._nidx, raw)


def test_nonzero_padding_rejected_native():
    nc = _nc()

    class _PadProbe(runtime.Struct):
        FIELDS = [("b", runtime.VarOpaque(8))]

    raw_ok = (1).to_bytes(4, "big") + b"\xaa\x00\x00\x00"
    v = _PadProbe.from_bytes(raw_ok)
    assert v.b == b"\xaa"
    raw_bad = (1).to_bytes(4, "big") + b"\xaa\x00\x00\x01"
    with pytest.raises(Exception):
        nc.unpack(nc.cap, _PadProbe._nidx, raw_bad)
    with pytest.raises(runtime.XdrError):
        _PadProbe.from_bytes(raw_bad)


def test_bool_and_optional_strictness_native():
    nc = _nc()

    class _BoolProbe(runtime.Struct):
        FIELDS = [("f", runtime.Bool)]

    class _OptProbe(runtime.Struct):
        FIELDS = [("f", runtime.Optional(runtime.Uint32))]

    assert _BoolProbe.from_bytes((1).to_bytes(4, "big")).f is True
    with pytest.raises(Exception):
        nc.unpack(nc.cap, _BoolProbe._nidx, (2).to_bytes(4, "big"))
    with pytest.raises(Exception):
        nc.unpack(nc.cap, _OptProbe._nidx, (3).to_bytes(4, "big"))
    assert _OptProbe.from_bytes(b"\x00" * 4).f is None


def test_generation_bump_recompiles():
    """Types created after the first compile are picked up (the
    register_arm / late-import path)."""
    nc_before = _nc()

    class _LateStruct(runtime.Struct):
        FIELDS = [("x", runtime.Uint64), ("y", runtime.VarOpaque(4))]

    v = _LateStruct(x=2**40, y=b"ab")
    raw = v.to_bytes()          # triggers recompile via generation bump
    nc = _nc()
    assert nc.pack(nc.cap, _LateStruct._nidx, v) == raw
    assert _LateStruct.from_bytes(raw) == v
    assert nc_before is nc


def test_register_arm_integrates_natively():
    from enum import IntEnum

    class _Sw(IntEnum):
        A = 0
        B = 1

    class _U(runtime.Union):
        SWITCH = _Sw
        ARMS = {_Sw.A: None}

    u = _U(_Sw.A)
    assert u.to_bytes() == b"\x00\x00\x00\x00"
    _U.register_arm(_Sw.B, "payload", runtime.Uint32)
    u2 = _U(_Sw.B, 77)
    raw = u2.to_bytes()
    assert raw == b"\x00\x00\x00\x01" + (77).to_bytes(4, "big")
    assert _U.from_bytes(raw) == u2


def test_python_fallback_matches(monkeypatch):
    """With the native codec disabled the Python path produces the same
    bytes (the oracle property the dispatch relies on)."""
    gen = XdrGenerator(random.Random(99))
    vals = [(cls, gen.gen(cls)) for cls in CORPUS_TYPES]
    native = [(v.to_bytes()) for _, v in vals]
    monkeypatch.setattr(runtime, "_NC", [False])
    python = [(v.to_bytes()) for _, v in vals]
    assert native == python
    for (cls, v), raw in zip(vals, python):
        assert cls.from_bytes(raw) == v
        c = v.clone()
        assert c == v and c is not v
