"""Overlay tests: handshake/auth, flooding, pull-mode tx dissemination,
fetch, flow control, fault injection — over LoopbackPeer pairs
(reference: overlay/test/OverlayTests.cpp + LoopbackPeer harness), and a
full 3-node consensus run through the real overlay path.
"""

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.main import Application, Config, QuorumSetConfig
from stellar_core_tpu.overlay import (LoopbackPeerConnection, PeerState)
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.xdr.overlay import MessageType, StellarMessage

import test_standalone_app as m1
from txtest_utils import op_create_account

PASSPHRASE = "overlay test network"


def make_apps(n, threshold=None, clock=None):
    clock = clock or VirtualClock(ClockMode.VIRTUAL_TIME)
    seeds = [SecretKey.from_seed(sha256(b"ovl-%d" % i)) for i in range(n)]
    node_ids = [s.public_key().raw for s in seeds]
    apps = []
    for i in range(n):
        cfg = Config()
        cfg.NETWORK_PASSPHRASE = PASSPHRASE
        cfg.NODE_SEED = seeds[i]
        cfg.NODE_IS_VALIDATOR = True
        cfg.RUN_STANDALONE = True
        cfg.FORCE_SCP = True
        cfg.MANUAL_CLOSE = True  # tests drive closes explicitly
        cfg.EXPECTED_LEDGER_CLOSE_TIME = 1.0
        cfg.INVARIANT_CHECKS = [".*"]
        cfg.PEER_PORT = 34000 + i
        cfg.QUORUM_SET = QuorumSetConfig(
            threshold=threshold or (n // 2 + 1), validators=list(node_ids))
        app = Application.create(clock, cfg)
        app.start()
        apps.append(app)
    return clock, apps


def shutdown(apps):
    for a in apps:
        a.shutdown()


def test_handshake_authenticates_both_sides():
    clock, apps = make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        assert conn.initiator.state == PeerState.GOT_AUTH
        assert conn.acceptor.state == PeerState.GOT_AUTH
        assert conn.initiator.peer_id == apps[1].config.node_id()
        assert conn.acceptor.peer_id == apps[0].config.node_id()
        assert apps[0].overlay_manager.get_authenticated_peers()
        assert apps[1].overlay_manager.get_authenticated_peers()
        # flow control primed both ways
        assert conn.initiator.flow.remote_capacity_msgs > 0
        assert conn.acceptor.flow.remote_capacity_msgs > 0
    finally:
        shutdown(apps)


def test_wrong_network_rejected():
    clock, apps = make_apps(2)
    try:
        apps[1].config.NETWORK_PASSPHRASE = "some other network"
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        assert conn.initiator.state == PeerState.CLOSING
    finally:
        shutdown(apps)


def test_damaged_messages_drop_peer():
    """Corrupting authenticated traffic trips the HMAC check
    (reference: LoopbackPeer damage tests)."""
    clock, apps = make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        assert conn.initiator.state == PeerState.GOT_AUTH
        conn.initiator.damage_prob = 1.0
        master = m1.master_account(apps[0])
        dest = m1.AppAccount(apps[0], SecretKey.from_seed(b"\x31" * 32))
        frame = master.tx([op_create_account(dest.account_id, 10**11)])
        conn.initiator.send_message(StellarMessage(
            MessageType.TRANSACTION, frame.envelope))
        conn.crank()
        # acceptor saw garbage → dropped the connection
        assert conn.acceptor.state == PeerState.CLOSING
    finally:
        shutdown(apps)


def test_transaction_pull_mode_flood():
    """TRANSACTION at node0 → FLOOD_ADVERT → FLOOD_DEMAND → body lands
    in node1's queue."""
    clock, apps = make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        master = m1.master_account(apps[0])
        dest = m1.AppAccount(apps[0], SecretKey.from_seed(b"\x32" * 32))
        frame = master.tx([op_create_account(dest.account_id, 10**11)])
        assert m1.submit(apps[0], frame)["status"] == "PENDING"
        # local submission must advertise to peers too (reference:
        # Herder::recvTransaction → broadcast via overlay)
        apps[0].overlay_manager.advert_transaction(frame.full_hash())
        conn.crank()
        assert apps[1].herder.tx_queue.get_tx(frame.full_hash()) is not None
    finally:
        shutdown(apps)


def test_scp_flood_and_txset_fetch_close_ledger():
    """Full consensus over the real overlay: 3 nodes, loopback mesh.
    SCP envelopes flood, tx sets are fetched via GET_TX_SET, all close
    the same ledger with the same hash."""
    clock, apps = make_apps(3, threshold=2)
    conns = []
    try:
        for i in range(3):
            for j in range(i + 1, 3):
                conns.append(LoopbackPeerConnection(apps[i], apps[j]))
        for c in conns:
            c.crank()
        # submit a tx at node 2; advertise
        master = m1.master_account(apps[2])
        dest = m1.AppAccount(apps[2], SecretKey.from_seed(b"\x33" * 32))
        frame = master.tx([op_create_account(dest.account_id, 10**11)])
        assert m1.submit(apps[2], frame)["status"] == "PENDING"
        apps[2].overlay_manager.advert_transaction(frame.full_hash())
        for _ in range(5):
            for c in conns:
                c.crank()
        # everyone has the tx queued
        for app in apps:
            assert app.herder.tx_queue.get_tx(frame.full_hash()) is not None

        # all validators propose; envelopes + fetches ride the overlay
        for app in apps:
            app.herder.trigger_next_ledger_scp()
            for c in conns:
                c.crank()
        for _ in range(30):
            moved = sum(c.crank() for c in conns)
            n = clock.crank(False)
            if moved == 0 and n == 0:
                if all(a.ledger_manager.get_last_closed_ledger_num() >= 2
                       for a in apps):
                    break
                clock.crank(True)  # advance to next timer
        assert all(a.ledger_manager.get_last_closed_ledger_num() >= 2
                   for a in apps)
        for app in apps:
            acc = m1.app_account_entry(app, dest.account_id)
            assert acc is not None and acc.balance == 10**11
        hashes = set()
        for app in apps:
            row = app.database.query_one(
                "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=2")
            hashes.add(bytes(row[0]))
        assert len(hashes) == 1
    finally:
        shutdown(apps)


def test_flow_control_queues_when_out_of_credit():
    clock, apps = make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        peer = conn.initiator
        # exhaust the credit the acceptor granted; replenish per message
        # so the drain is observable without 40 txs
        peer.flow.remote_capacity_msgs = 1
        conn.acceptor.flow.batch_msgs = 1
        master = m1.master_account(apps[0])
        frames = []
        for i in range(3):
            d = m1.AppAccount(apps[0], SecretKey.from_seed(
                bytes([0x41 + i]) * 32))
            frames.append(master.tx([op_create_account(d.account_id,
                                                       10**10)]))
        for f in frames:
            peer.send_message(StellarMessage(MessageType.TRANSACTION,
                                             f.envelope))
        assert peer.flow.outbound_queue_len() == 2   # 1 sent, 2 queued
        conn.crank()  # acceptor processes + SEND_MOREs → queue drains
        assert peer.flow.outbound_queue_len() == 0
    finally:
        shutdown(apps)


def test_get_scp_state_syncs_late_joiner():
    """A node that connects after externalization learns the outcome via
    GET_SCP_STATE."""
    clock, apps = make_apps(3, threshold=2)
    conns = []
    try:
        # only nodes 0,1 connected at first
        c01 = LoopbackPeerConnection(apps[0], apps[1])
        conns.append(c01)
        c01.crank()
        for app in apps[:2]:
            app.herder.trigger_next_ledger_scp()
            c01.crank()
        for _ in range(20):
            if c01.crank() == 0 and clock.crank(False) == 0:
                if all(a.ledger_manager.get_last_closed_ledger_num() >= 2
                       for a in apps[:2]):
                    break
                clock.crank(True)
        assert apps[0].ledger_manager.get_last_closed_ledger_num() >= 2

        # node 2 joins and asks for SCP state
        c02 = LoopbackPeerConnection(apps[0], apps[2])
        conns.append(c02)
        c02.crank()
        peer_to_0 = apps[2].overlay_manager.get_authenticated_peers()[0]
        peer_to_0.send_message(StellarMessage(MessageType.GET_SCP_STATE, 0))
        for _ in range(20):
            if c02.crank() == 0 and clock.crank(False) == 0:
                if apps[2].ledger_manager.get_last_closed_ledger_num() >= 2:
                    break
                clock.crank(True)
        assert apps[2].ledger_manager.get_last_closed_ledger_num() >= 2
    finally:
        shutdown(apps)
