"""State archival close-loop (protocol 23+): the eviction scan moves
expired persistent entries into the hot archive at ledger close, and
RestoreFootprint brings them back (reference: the protocol-next hot
archive in src/bucket/ + InvokeHostFunctionOp/RestoreFootprintOp
interplay). The version sweep: deploy at p23, expire, evict, restore,
and keep using the contract with its state preserved."""

import pytest

from stellar_core_tpu.bucket.hot_archive import (
    FIRST_PROTOCOL_STATE_ARCHIVAL)
from stellar_core_tpu.herder.upgrades import UpgradeParameters
from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.soroban.host import instance_key, ttl_key_for
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.xdr import contract as cx
from stellar_core_tpu.xdr.ledger_entries import LedgerKey
from stellar_core_tpu.xdr.next_types import HotArchiveBucketEntryType

import test_standalone_app as m1
import test_soroban as ts

SHORT_TTL = 16


@pytest.fixture
def app():
    cfg = get_test_config()
    a = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    a.start()
    # vote the node onto the state-archival protocol
    a.herder.upgrades.set_parameters(UpgradeParameters(
        upgrade_time=0,
        protocol_version=FIRST_PROTOCOL_STATE_ARCHIVAL))
    a.manual_close()
    assert a.ledger_manager.get_last_closed_ledger_header()\
        .ledgerVersion == FIRST_PROTOCOL_STATE_ARCHIVAL
    _shrink_persistent_ttl(a)
    ts.COUNTER_CODE = ts.CODE_BUILDS["scvm"]
    yield a
    a.shutdown()


def _shrink_persistent_ttl(app) -> None:
    """Test-scale archival cadence: minPersistentTTL -> SHORT_TTL."""
    key = LedgerKey.config_setting(
        cx.ConfigSettingID.CONFIG_SETTING_STATE_ARCHIVAL)
    with LedgerTxn(app.ledger_manager.root) as ltx:
        le = ltx.load(key)
        le.data.value.value.minPersistentTTL = SHORT_TTL
        le.data.value.value.minTemporaryTTL = SHORT_TTL
        ltx.commit()


def _close_n(app, n):
    for _ in range(n):
        app.manual_close()


def _live(app, key):
    with LedgerTxn(app.ledger_manager.root) as ltx:
        return ltx.load_without_record(key)


def test_evict_then_restore_roundtrip(app):
    master, cid = ts.deploy(app)
    ro, rw = ts.invoke_footprints(cid)
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "increment"), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res
    ckey = ts.counter_key(cid)
    assert _live(app, ckey) is not None

    # run past the shortened TTL: the close-loop eviction scan fires
    _close_n(app, SHORT_TTL + 2)
    assert _live(app, ckey) is None, "expired entry not evicted"
    assert _live(app, ttl_key_for(ckey)) is None
    hal = app.bucket_manager.hot_archive
    be = hal.get_entry(ckey)
    assert be is not None and \
        be.disc == HotArchiveBucketEntryType.HOT_ARCHIVE_ARCHIVED
    # the archived record carries the full entry (count == 1)
    assert be.value.data.value.val.value == 1

    # an invoke against evicted state fails loudly (ENTRY_ARCHIVED)
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "increment"), ro, rw))
    assert res.result.result.disc.name == "txFAILED"

    # restore everything the contract needs: code, instance, counter
    addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid)
    restore_keys = [LedgerKey.contract_code(ts.wasm_hash()),
                    instance_key(addr), ckey]
    from stellar_core_tpu.xdr.transaction import (_OperationBody,
                                                  OperationType)
    from stellar_core_tpu.xdr.types import ExtensionPoint
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master,
        _OperationBody(OperationType.RESTORE_FOOTPRINT,
                       cx.RestoreFootprintOp(ext=ExtensionPoint(0))),
        [], restore_keys))
    assert res.result.result.disc.name == "txSUCCESS", res
    le = _live(app, ckey)
    assert le is not None, "restore did not recreate the entry"
    assert le.data.value.val.value == 1
    ttl = _live(app, ttl_key_for(ckey))
    assert ttl is not None and \
        ttl.data.value.liveUntilLedgerSeq >= \
        app.ledger_manager.get_last_closed_ledger_num() + SHORT_TTL - 2

    # the archive now marks the key LIVE (tombstone recorded at close)
    be = hal.get_entry(ckey)
    assert be is not None and \
        be.disc == HotArchiveBucketEntryType.HOT_ARCHIVE_LIVE

    # and the contract keeps working with its state intact
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "increment"), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res
    assert _live(app, ckey).data.value.val.value == 2


def test_temporary_entries_evict_to_nowhere(app):
    """Expired TEMPORARY entries are deleted outright — never archived
    (reference: only persistent entries are recoverable)."""
    master, cid = ts.deploy(app)
    # the counter contract writes persistent state; craft a temporary
    # entry directly through a host put via the nonce mechanism is
    # overkill — write one via LedgerTxn as the host would
    from stellar_core_tpu.soroban.host import SorobanHost, Budget
    from stellar_core_tpu.soroban.network_config import \
        SorobanNetworkConfig
    addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT, cid)
    tkey = LedgerKey.contract_data(
        addr, cx.SCVal(cx.SCValType.SCV_SYMBOL, b"tmp"),
        cx.ContractDataDurability.TEMPORARY)
    from stellar_core_tpu.xdr.ledger_entries import (_LedgerEntryData,
                                                     _LedgerEntryExt,
                                                     LedgerEntry,
                                                     LedgerEntryType)
    from stellar_core_tpu.xdr.types import ExtensionPoint
    with LedgerTxn(app.ledger_manager.root) as ltx:
        host = SorobanHost(
            ltx, ltx.get_header(), SorobanNetworkConfig(ltx),
            cx.LedgerFootprint(readOnly=[], readWrite=[tkey]),
            Budget(10_000_000), app.config.network_id(),
            master.account_id)
        host.put_entry(tkey, LedgerEntry(
            lastModifiedLedgerSeq=1,
            data=_LedgerEntryData(
                LedgerEntryType.CONTRACT_DATA,
                cx.ContractDataEntry(
                    ext=ExtensionPoint(0), contract=addr,
                    key=cx.SCVal(cx.SCValType.SCV_SYMBOL, b"tmp"),
                    durability=cx.ContractDataDurability.TEMPORARY,
                    val=cx.SCVal(cx.SCValType.SCV_U32, 7))),
            ext=_LedgerEntryExt(0)),
            durability=cx.ContractDataDurability.TEMPORARY)
        ltx.commit()
    assert _live(app, tkey) is not None
    _close_n(app, SHORT_TTL + 2)
    assert _live(app, tkey) is None
    assert app.bucket_manager.hot_archive.get_entry(tkey) is None


def test_hot_archive_survives_restart(tmp_path):
    """Protocol-23 headers commit to the hot archive, so a restarted
    node must reload it (persisted level state + bucket files) — and
    archived entries stay restorable."""
    cfg = get_test_config()
    cfg.DATABASE = f"sqlite3://{tmp_path}/node.db"
    cfg.BUCKET_DIR_PATH = str(tmp_path / "buckets")
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    app.herder.upgrades.set_parameters(UpgradeParameters(
        upgrade_time=0,
        protocol_version=FIRST_PROTOCOL_STATE_ARCHIVAL))
    app.manual_close()
    _shrink_persistent_ttl(app)
    ts.COUNTER_CODE = ts.CODE_BUILDS["scvm"]
    master, cid = ts.deploy(app)
    ro, rw = ts.invoke_footprints(cid)
    res = ts.submit_and_close(app, ts.soroban_tx(
        app, master, ts.invoke_op(cid, "increment"), ro, rw))
    assert res.result.result.disc.name == "txSUCCESS", res
    ckey = ts.counter_key(cid)
    _close_n(app, SHORT_TTL + 2)
    assert _live(app, ckey) is None
    assert app.bucket_manager.hot_archive.get_entry(ckey) is not None
    lcl = app.ledger_manager.get_last_closed_ledger_num()
    lcl_header_hash_bytes = \
        app.ledger_manager.get_last_closed_ledger_hash()
    app.shutdown()

    cfg2 = get_test_config()
    cfg2.DATABASE = cfg.DATABASE
    cfg2.BUCKET_DIR_PATH = cfg.BUCKET_DIR_PATH
    cfg2.NETWORK_PASSPHRASE = cfg.NETWORK_PASSPHRASE
    app2 = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg2)
    app2.start()
    try:
        assert app2.ledger_manager.get_last_closed_ledger_num() == lcl
        assert app2.ledger_manager.get_last_closed_ledger_hash() == \
            lcl_header_hash_bytes
        # the archive reloaded: the evicted entry is still there…
        be = app2.bucket_manager.hot_archive.get_entry(ckey)
        assert be is not None and \
            be.disc == HotArchiveBucketEntryType.HOT_ARCHIVE_ARCHIVED
        # …the header's combined hash verifies against it…
        hdr = app2.ledger_manager.get_last_closed_ledger_header()
        assert bytes(hdr.bucketListHash) == \
            app2.bucket_manager.snapshot_ledger_hash(hdr.ledgerVersion)
        # …and closes keep working on the reloaded state
        app2.manual_close()
    finally:
        app2.shutdown()


def _make_expiring_entries(app, n, expire_at, tag=b"bulk"):
    """Create n persistent contract-data entries whose TTLs all lapse at
    `expire_at`, written directly through the root (the eviction scan
    only sees committed state, so this is equivalent to n uploads)."""
    from stellar_core_tpu.crypto.sha import sha256
    from stellar_core_tpu.xdr.ledger_entries import (_LedgerEntryData,
                                                     _LedgerEntryExt,
                                                     LedgerEntry,
                                                     LedgerEntryType)
    from stellar_core_tpu.xdr.types import ExtensionPoint
    keys = []
    with LedgerTxn(app.ledger_manager.root) as ltx:
        for i in range(n):
            addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT,
                                sha256(tag + b"-%d" % i))
            sckey = cx.SCVal(cx.SCValType.SCV_U32, i)
            key = LedgerKey.contract_data(
                addr, sckey, cx.ContractDataDurability.PERSISTENT)
            ltx.create(LedgerEntry(
                lastModifiedLedgerSeq=1,
                data=_LedgerEntryData(
                    LedgerEntryType.CONTRACT_DATA,
                    cx.ContractDataEntry(
                        ext=ExtensionPoint(0), contract=addr, key=sckey,
                        durability=cx.ContractDataDurability.PERSISTENT,
                        val=cx.SCVal(cx.SCValType.SCV_U32, i))),
                ext=_LedgerEntryExt(0)))
            ttlk = ttl_key_for(key)
            ltx.create(LedgerEntry(
                lastModifiedLedgerSeq=1,
                data=_LedgerEntryData(
                    LedgerEntryType.TTL,
                    cx.TTLEntry(keyHash=ttlk.value.keyHash,
                                liveUntilLedgerSeq=expire_at)),
                ext=_LedgerEntryExt(0)))
            keys.append(key)
        ltx.commit()
    return keys


def _set_archival(app, **kw):
    key = LedgerKey.config_setting(
        cx.ConfigSettingID.CONFIG_SETTING_STATE_ARCHIVAL)
    with LedgerTxn(app.ledger_manager.root) as ltx:
        le = ltx.load(key)
        for k, v in kw.items():
            setattr(le.data.value.value, k, v)
        ltx.commit()


def _eviction_cursor(app):
    key = LedgerKey.config_setting(
        cx.ConfigSettingID.CONFIG_SETTING_EVICTION_ITERATOR)
    with LedgerTxn(app.ledger_manager.root) as ltx:
        le = ltx.load_without_record(key)
        return None if le is None else \
            le.data.value.value.bucketFileOffset


def test_eviction_scan_bounded_on_large_state(app):
    """VERDICT r04 missing #2: with 50k contract entries, per-close
    eviction work must be O(evictionScanSize), never O(total state), and
    the persistent iterator must advance through the key space."""
    N = 50_000
    SCAN = 512
    lcl = app.ledger_manager.get_last_closed_ledger_num()
    _make_expiring_entries(app, N, expire_at=lcl + 1)
    _set_archival(app, evictionScanSize=SCAN, maxEntriesToArchive=64)

    def archived_count():
        # UNIQUE archived keys: a spill leaves the same record visible
        # in the spilling level's snap and the level below's curr
        from stellar_core_tpu.xdr.ledger_entries import ledger_entry_key
        hal = app.bucket_manager.hot_archive
        seen = set()
        for lvl in hal.levels:
            for b in (lvl.curr, lvl.snap):
                for be in b.entries():
                    if be.disc == \
                            HotArchiveBucketEntryType.HOT_ARCHIVE_ARCHIVED:
                        seen.add(ledger_entry_key(be.value).to_bytes())
        return len(seen)

    offsets = []
    counts = [archived_count()]
    for _ in range(6):
        app.manual_close()
        # the scan probed at most SCAN keys of the 50k
        assert 0 < app.ledger_manager.last_eviction_probes <= SCAN, \
            app.ledger_manager.last_eviction_probes
        offsets.append(_eviction_cursor(app))
        counts.append(archived_count())
    # the consensus cursor exists and its per-close movement is bounded
    # by the scan budget. (The ordinal can stay FLAT while evictions
    # delete exactly the probed keys below it — the cursor tracks the
    # same next key in a shrinking index; advancement is proven by the
    # per-close archived counts below and the no-skip test.)
    assert offsets[0] is not None
    deltas = [(offsets[i + 1] - offsets[i]) % N
              for i in range(len(offsets) - 1)]
    assert all(d <= SCAN for d in deltas), deltas
    # archival throughput respects maxEntriesToArchive per close, and
    # entries really are flowing into the hot archive (the first close
    # archives nothing: the TTLs lapse only after it)
    per_close = [counts[i + 1] - counts[i] for i in range(6)]
    assert per_close[0] == 0, per_close
    assert all(0 < c <= 64 for c in per_close[1:]), per_close


def test_eviction_cursor_does_not_skip_under_mutation(app):
    """The stored cursor is adjusted for index shifts (evictions delete
    keys below it every close): every expired entry must be archived in
    one pass — a drifting ordinal would skip entries until wraparound."""
    N = 12
    SCAN = 4
    lcl = app.ledger_manager.get_last_closed_ledger_num()
    keys = _make_expiring_entries(app, N, expire_at=lcl + 1,
                                  tag=b"noskip")
    _set_archival(app, evictionScanSize=SCAN, maxEntriesToArchive=SCAN)
    # first close: TTLs not yet lapsed; then ceil(12/4)=3 evicting
    # closes must archive everything
    for _ in range(1 + 3):
        app.manual_close()
    hal = app.bucket_manager.hot_archive
    missing = [k for k in keys if hal.get_entry(k) is None]
    assert not missing, f"{len(missing)} keys skipped by the cursor"


def test_eviction_restart_mid_scan_is_deterministic(tmp_path):
    """Eviction outcomes must be byte-identical whether or not the node
    restarts mid-scan: the iterator is consensus (ledger) state, and the
    key index rebuilds from identical committed state."""
    N = 300
    SCAN = 32

    def run_chain(name, restart_after):
        cfg = get_test_config()
        cfg.DATABASE = f"sqlite3://{tmp_path}/{name}.db"
        cfg.BUCKET_DIR_PATH = str(tmp_path / f"{name}-buckets")
        app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                                 cfg)
        app.start()
        app.herder.upgrades.set_parameters(UpgradeParameters(
            upgrade_time=0,
            protocol_version=FIRST_PROTOCOL_STATE_ARCHIVAL))
        app.manual_close()
        lcl = app.ledger_manager.get_last_closed_ledger_num()
        _make_expiring_entries(app, N, expire_at=lcl + 1)
        _set_archival(app, evictionScanSize=SCAN,
                      maxEntriesToArchive=SCAN)
        hashes = []
        closes_done = 0
        total_closes = (N // SCAN) + 4
        while closes_done < total_closes:
            app.manual_close()
            closes_done += 1
            hashes.append(
                app.ledger_manager.get_last_closed_ledger_hash())
            if restart_after is not None and \
                    closes_done == restart_after:
                # restart MID-SCAN: cursor is partway through the keys
                assert 0 < (_eviction_cursor(app) or 0) < N
                app.shutdown()
                cfg2 = get_test_config()
                cfg2.DATABASE = cfg.DATABASE
                cfg2.BUCKET_DIR_PATH = cfg.BUCKET_DIR_PATH
                cfg2.NETWORK_PASSPHRASE = cfg.NETWORK_PASSPHRASE
                app = Application.create(
                    VirtualClock(ClockMode.VIRTUAL_TIME), cfg2)
                app.start()
        hot = app.bucket_manager.hot_archive.get_hash()
        app.shutdown()
        return hashes, hot

    hashes_a, hot_a = run_chain("cont", restart_after=None)
    hashes_b, hot_b = run_chain("rest", restart_after=3)
    assert hashes_a == hashes_b, "restart mid-scan diverged the chain"
    assert hot_a == hot_b


def test_hot_archive_published_and_bucket_applied(tmp_path):
    """The published HAS must carry the hot-archive levels and upload
    their bucket files, and bucket-apply catchup must rebuild the hot
    archive — otherwise the protocol-23 combined header hash can never
    verify on a chain with evictions (reference: HAS-v2 hot-archive
    handling, HistoryArchive.h:33-123 + AssumeStateWork)."""
    import json
    import os
    import tempfile

    from stellar_core_tpu.catchup import (ApplyBucketsWork,
                                          GetHistoryArchiveStateWork)
    from stellar_core_tpu.history import (HistoryArchiveState,
                                          make_tmpdir_archive)
    from stellar_core_tpu.work import State, run_work_to_completion

    archive_root = str(tmp_path / "archive")
    cfg = get_test_config()
    cfg.HISTORY = {"test": {
        "get": f"cp {archive_root}/{{0}} {{1}}",
        "put": f"mkdir -p $(dirname {archive_root}/{{1}}) && "
               f"cp {{0}} {archive_root}/{{1}}",
    }}
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    try:
        app.herder.upgrades.set_parameters(UpgradeParameters(
            upgrade_time=0,
            protocol_version=FIRST_PROTOCOL_STATE_ARCHIVAL))
        app.manual_close()
        _shrink_persistent_ttl(app)
        ts.COUNTER_CODE = ts.CODE_BUILDS["scvm"]
        master, cid = ts.deploy(app)
        ro, rw = ts.invoke_footprints(cid)
        res = ts.submit_and_close(app, ts.soroban_tx(
            app, master, ts.invoke_op(cid, "increment"), ro, rw))
        assert res.result.result.disc.name == "txSUCCESS", res
        ckey = ts.counter_key(cid)
        _close_n(app, SHORT_TTL + 2)
        assert app.bucket_manager.hot_archive.get_entry(ckey) is not None
        while app.ledger_manager.get_last_closed_ledger_num() < 63:
            app.manual_close()
        assert app.history_manager.published_count >= 1
        lcl_hash = app.ledger_manager.get_last_closed_ledger_hash()

        # the published HAS records the hot-archive levels and every
        # referenced hot bucket file exists in the archive
        with open(os.path.join(archive_root,
                               ".well-known/stellar-history.json")) as f:
            has = HistoryArchiveState.from_json(f.read())
        assert has.hot_archive_buckets, "hot archive absent from HAS"
        hot_hashes = has.hot_bucket_hashes()
        assert hot_hashes
        for hx in hot_hashes:
            assert os.path.exists(os.path.join(
                archive_root, f"bucket/{hx[:2]}/{hx[2:4]}/{hx[4:6]}/"
                              f"bucket-{hx}.xdr.gz"))

        # bucket-apply into a fresh node: the combined header hash only
        # verifies if the hot archive was rebuilt
        archive = make_tmpdir_archive("test", archive_root)
        cfg_c = get_test_config()
        cfg_c.NETWORK_PASSPHRASE = cfg.NETWORK_PASSPHRASE
        app_c = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                                   cfg_c)
        try:
            has_work = GetHistoryArchiveStateWork(app_c, archive)
            assert run_work_to_completion(app_c, has_work) == \
                State.WORK_SUCCESS
            work = ApplyBucketsWork(app_c, archive, has_work.has,
                                    tempfile.mkdtemp(prefix="ab-hot-"))
            assert run_work_to_completion(app_c, work,
                                          timeout_virtual=1000) == \
                State.WORK_SUCCESS
            assert app_c.ledger_manager.get_last_closed_ledger_num() == 63
            assert app_c.ledger_manager.get_last_closed_ledger_hash() == \
                lcl_hash
            be = app_c.bucket_manager.hot_archive.get_entry(ckey)
            assert be is not None and \
                be.disc == HotArchiveBucketEntryType.HOT_ARCHIVE_ARCHIVED
            hdr = app_c.ledger_manager.get_last_closed_ledger_header()
            assert bytes(hdr.bucketListHash) == \
                app_c.bucket_manager.snapshot_ledger_hash(
                    hdr.ledgerVersion)
        finally:
            app_c.shutdown()
    finally:
        app.shutdown()
