"""Analyzer self-tests (stellar_core_tpu/analysis/, docs/ANALYSIS.md).

Three layers:

1. **Fixture packages** — tiny synthetic packages proving each pass
   catches its known-bad shape with an exact file:line finding and a
   remediation hint, and stays silent on the known-good twins
   (posted access, locked access, allowlisted entry).
2. **Committed-tree gate** — the real package analyzed with the real
   ALLOWLIST must produce zero live findings. This is the tier-1 lint.
3. **Runtime affinity** — the opt-in thread-affinity assertions
   (util/threads.py) catch a mis-declared domain directly, and a
   multi-node simulation runs violation-free with checking enabled.
"""

import os
import sys
import threading

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from stellar_core_tpu import analysis
from stellar_core_tpu.util import threads


# ------------------------------------------------------------ fixtures --

def _write_pkg(tmp_path, files):
    """Materialize {relpath: source} as package `fixpkg`; returns its
    root. Every directory gets an __init__.py."""
    root = tmp_path / "fixpkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        d = p.parent
        while d != tmp_path:
            init = d / "__init__.py"
            if not init.exists():
                init.write_text("")
            d = d.parent
        p.write_text(src)
    return str(root)


def _run(tmp_path, files, allowlist=None, passes=("determinism",
                                                  "domains", "registry")):
    pkg = _write_pkg(tmp_path, files)
    allowlist_path = None
    if allowlist is not None:
        allowlist_path = str(tmp_path / "ALLOWLIST")
        with open(allowlist_path, "w") as f:
            f.write(allowlist)
    return analysis.run_all(pkg_root=pkg, repo_root=str(tmp_path),
                            allowlist_path=allowlist_path, passes=passes)


def _live(res, prefix):
    """Live findings under a key prefix (root-missing noise excluded —
    fixture packages only define the roots a test needs)."""
    return [f for f in res.findings
            if f.key.startswith(prefix)
            and not f.key.startswith("determinism:root-missing")]


# Pass 1 known-bad: wall-clock reachable from close_ledger THROUGH a
# util/ helper — the exact shape the retired directory-grep missed.
_WALLCLOCK_VIA_HELPER = {
    "ledger/ledger_manager.py": (
        "from ..util.clockutil import stamp\n"
        "\n"
        "class LedgerManager:\n"
        "    def close_ledger(self, lcd):\n"
        "        return self._close_ledger(lcd)\n"
        "\n"
        "    def _close_ledger(self, lcd):\n"
        "        return stamp()\n"
    ),
    "util/clockutil.py": (
        "import time\n"
        "\n"
        "def stamp():\n"
        "    return time.time()\n"
    ),
}


def test_pass1_wallclock_reachable_via_util_helper(tmp_path):
    res = _run(tmp_path, _WALLCLOCK_VIA_HELPER, passes=("determinism",))
    hits = _live(res, "determinism:util.clockutil:stamp")
    assert len(hits) == 1, [f.render() for f in res.findings]
    f = hits[0]
    assert f.path.endswith(os.path.join("util", "clockutil.py"))
    assert f.lineno == 4                       # the time.time() line
    assert "reachable from consensus root" in f.message
    assert "VirtualClock" in f.hint            # remediation present
    # the evidence chain names the path from the root to the sink
    assert any("close_ledger" in step for step in f.chain)


def test_pass1_unreachable_wallclock_not_flagged(tmp_path):
    files = dict(_WALLCLOCK_VIA_HELPER)
    files["ledger/ledger_manager.py"] = (
        "class LedgerManager:\n"
        "    def close_ledger(self, lcd):\n"
        "        return self._close_ledger(lcd)\n"
        "\n"
        "    def _close_ledger(self, lcd):\n"
        "        return 7\n"
    )
    res = _run(tmp_path, files, passes=("determinism",))
    assert not _live(res, "determinism:util.clockutil")


# Pass 2 known-bad: one attribute written from two domains, the worker
# write neither posted nor locked.
_CROSS_DOMAIN_WRITE = {
    "svc/workers.py": (
        "import threading\n"
        "\n"
        "class Service:\n"
        "    def __init__(self):\n"
        "        self.shared = 0\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "\n"
        "    def _run(self):  # thread-domain: completion-worker\n"
        "        self.shared = 1\n"
        "\n"
        "    def touch(self):\n"
        "        self.shared = 2\n"
    ),
}


def test_pass2_cross_domain_unprotected_write(tmp_path):
    res = _run(tmp_path, _CROSS_DOMAIN_WRITE, passes=("domains",))
    hits = _live(res, "domain:svc.workers:Service.shared")
    assert len(hits) == 1, [f.render() for f in res.findings]
    f = hits[0]
    assert f.path.endswith(os.path.join("svc", "workers.py"))
    assert "completion-worker" in f.message and "crank" in f.message
    assert "UNPROTECTED" in f.message
    assert "clock.post" in f.hint              # remediation present


def test_pass2_posted_access_is_clean(tmp_path):
    files = {
        "svc/good_post.py": (
            "class Good:\n"
            "    def __init__(self, clock):\n"
            "        self.clock = clock\n"
            "        self.value = 0\n"
            "\n"
            "    def _run(self):  # thread-domain: completion-worker\n"
            "        self.clock.post(self._apply)\n"
            "\n"
            "    def _apply(self):\n"
            "        self.value = 1\n"
        ),
    }
    res = _run(tmp_path, files, passes=("domains",))
    assert not _live(res, "domain:"), [f.render() for f in res.findings]


def test_pass2_locked_access_is_clean(tmp_path):
    files = {
        "svc/good_lock.py": (
            "import threading\n"
            "\n"
            "class GoodLocked:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.value = 0\n"
            "\n"
            "    def _run(self):  # thread-domain: completion-worker\n"
            "        with self._lock:\n"
            "            self.value = 1\n"
            "\n"
            "    def touch(self):\n"
            "        with self._lock:\n"
            "            self.value = 2\n"
        ),
    }
    res = _run(tmp_path, files, passes=("domains",))
    assert not _live(res, "domain:"), [f.render() for f in res.findings]


# Pass 3 known-bad: a FaultSpec naming a seam no chaos.point fires —
# the typo that silently injects nothing.
_SEAM_TYPO = {
    "svc/seams.py": (
        "from ..util import chaos\n"
        "\n"
        "def fire():\n"
        "    chaos.point(\"overlay.send\")\n"
    ),
    "svc/spec.py": (
        "SPEC = 'FaultSpec(\"overlay.sendx\")'\n"
    ),
}


def test_pass3_seam_typo_both_directions(tmp_path):
    res = _run(tmp_path, _SEAM_TYPO, passes=("registry",))
    typo = _live(res, "seamref:overlay.sendx")
    assert len(typo) == 1, [f.render() for f in res.findings]
    assert "no chaos.point call site fires it" in typo[0].message
    assert "typo" in typo[0].hint
    # and the fired-but-unreferenced direction catches the orphan seam
    orphan = _live(res, "seam:overlay.send")
    assert len(orphan) == 1
    assert "no test/scenario references it" in orphan[0].message


# Allowlist semantics: a justified entry suppresses; rot (unjustified
# or unused entries) is itself a finding.
def test_allowlisted_finding_is_suppressed_not_lost(tmp_path):
    res = _run(tmp_path, _CROSS_DOMAIN_WRITE, passes=("domains",),
               allowlist="domain:svc.workers:Service.shared"
                         "  # reviewed: fixture, benign by test design\n")
    assert not _live(res, "domain:")
    assert not _live(res, "allowlist:")
    assert [f.key for f in res.suppressed] == \
        ["domain:svc.workers:Service.shared"]


def test_allowlist_rot_is_flagged(tmp_path):
    res = _run(tmp_path, _CROSS_DOMAIN_WRITE, passes=("domains",),
               allowlist="domain:svc.workers:Service.shared\n"
                         "domain:svc.workers:Service.gone  # obsolete\n")
    keys = sorted(f.key for f in res.findings)
    assert "allowlist:unjustified:domain:svc.workers:Service.shared" \
        in keys
    assert "allowlist:unused:domain:svc.workers:Service.gone" in keys


# ---------------------------------------------------- committed tree --

def test_committed_tree_is_clean():
    """THE tier-1 gate: the real package + the real ALLOWLIST analyze
    to zero live findings. A new true positive must be fixed or carry
    a justified allowlist entry; allowlist rot fails here too."""
    res = analysis.run_all()
    assert not res.findings, "\n" + "\n".join(
        f.render() for f in res.findings)
    # every suppression is a reviewed true positive with justification
    assert all(res.allowlist.entries[k]
               for k in res.allowlist.entries), \
        "ALLOWLIST entries must carry justifications"


def test_artifact_shape():
    doc = analysis.run_all().to_json()
    assert doc["counts"] == {}
    assert doc["allowlist_size"] >= 7
    assert doc["modules"] > 150 and doc["functions"] > 2000
    assert isinstance(doc["findings"], list)
    assert isinstance(doc["suppressed"], list)
    assert sum(doc["suppressed_counts"].values()) == \
        len(doc["suppressed"])
    assert all({"key", "pass", "path", "line", "message"} <=
               set(f) for f in doc["suppressed"])


# ------------------------------------------------- runtime affinity --

@pytest.fixture
def affinity():
    threads.enable(raise_on_violation=True)
    try:
        yield
    finally:
        threads.disable()
        threads.bind("crank")  # leave the pytest thread neutral-bound


def test_affinity_violation_raises(affinity):
    done = []

    def worker():
        threads.bind("completion-worker")
        try:
            threads.assert_domain("crank")
        except threads.ThreadDomainViolation as e:
            done.append(str(e))

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert done and "completion-worker" in done[0] \
        and "crank" in done[0]


def test_affinity_unbound_thread_passes(affinity):
    res = []

    def worker():
        # never bound: assertions must not fire (binding is opt-in)
        threads.assert_domain("crank")
        res.append(True)

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert res == [True]


def test_affinity_recording_mode(affinity):
    threads.enable(raise_on_violation=False)
    threads.bind("http")
    threads.assert_domain("crank")
    v = threads.violations()
    assert len(v) == 1 and "'http'" in v[0]


def test_multinode_sim_with_affinity_checks(affinity):
    """A real multi-node simulation cranked to consensus with affinity
    checking ON: the crank thread binds `crank`, the completion worker
    binds `completion-worker`, and the `close_ledger` /
    `_complete_close` assertions must all hold — a wrong declaration
    anywhere fails this test instead of silently weakening Pass 2."""
    from stellar_core_tpu.simulation import topologies
    threads.enable(raise_on_violation=True)
    sim = topologies.pair()
    try:
        sim.start_all_nodes()
        assert sim.crank_until(lambda: sim.have_all_externalized(3))
        for app in sim.apps():
            app.ledger_manager.join_completion()
        assert sim.ledger_hashes_agree(3)
    finally:
        sim.stop_all_nodes()
    assert threads.violations() == []
    assert threads.current() == "crank"   # the crank loop bound us
