"""XDR schema identity + protocol-curr/next split tests.

Reference mechanisms: the dual protocol-curr/protocol-next XDR trees
(Makefile.am:46-51) and the .x identity hashes cross-checked between
core and its Rust host (Makefile.am:28-32, rust/src/lib.rs:631)."""

import subprocess
import sys

from stellar_core_tpu.xdr import schema
from stellar_core_tpu.xdr.next_types import (BucketListType,
                                             BucketMetadata,
                                             _BucketMetadataExt)


def test_identity_stable_within_process():
    a = schema.identity()
    b = schema.identity()
    assert a == b
    assert len(a["curr"]) == 64 and len(a["next"]) == 64


def test_identity_stable_across_processes():
    """Hash must be a pure function of the definitions (no dict-order
    or id() leakage) — the whole point of a schema identity."""
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = ("import sys; sys.path.insert(0, %r); "
            "from stellar_core_tpu.xdr import schema; "
            "i = schema.identity(); print(i['curr'], i['next'])") % repo
    outs = set()
    for _ in range(2):
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        outs.add(r.stdout.strip())
    assert len(outs) == 1
    here = schema.identity()
    assert outs.pop() == f"{here['curr']} {here['next']}"


def test_curr_and_next_differ_structurally():
    ident = schema.identity()
    assert ident["curr"] != ident["next"]
    curr = schema.curr_namespace()
    nxt = schema.next_namespace()
    # the delta: next's BucketMetadata has the bucketListType arm
    assert curr["BucketMetadata"] is not nxt["BucketMetadata"]
    assert "BucketListType" not in curr
    assert nxt["BucketListType"] is BucketListType
    # everything not overridden is SHARED, not copied
    assert curr["LedgerHeader"] is nxt["LedgerHeader"]
    assert curr["TransactionEnvelope"] is nxt["TransactionEnvelope"]


def test_next_bucket_metadata_roundtrip_and_wire_compat():
    """The next build round-trips its structural change; the v0 arm is
    wire-compatible with the curr encoding (upgrade safety)."""
    bm = BucketMetadata(ledgerVersion=23,
                        ext=_BucketMetadataExt(
                            1, BucketListType.HOT_ARCHIVE))
    assert BucketMetadata.from_bytes(bm.to_bytes()) == bm
    # v0 (void ext) bytes == curr encoding of the same metadata
    curr_cls = schema.curr_namespace()["BucketMetadata"]
    from stellar_core_tpu.xdr.types import ExtensionPoint
    curr_bm = curr_cls(ledgerVersion=23, ext=ExtensionPoint(0))
    next_bm = BucketMetadata(ledgerVersion=23,
                             ext=_BucketMetadataExt(0))
    assert curr_bm.to_bytes() == next_bm.to_bytes()


def test_describe_covers_every_type_in_both_builds():
    for ns in (schema.curr_namespace(), schema.next_namespace()):
        assert len(ns) > 100
        for cls in set(ns.values()):
            d = schema.describe_type(cls)
            assert d.startswith(("struct ", "union ", "enum "))


def test_schema_hash_sensitive_to_structure():
    """Adding one arm to one union must change the hash (sanity that
    the descriptor actually captures structure)."""
    ns = dict(schema.curr_namespace())
    h0 = schema.schema_hash(ns)
    from stellar_core_tpu.xdr.next_types import BucketMetadata as NextBM
    ns["BucketMetadata"] = NextBM
    assert schema.schema_hash(ns) != h0


def test_info_reports_xdr_identity():
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             get_test_config())
    app.start()
    try:
        info = app.info()
        assert info["xdr"] == schema.identity()
    finally:
        app.shutdown()
