"""Conflict-staged parallel apply (ledger/parallel_apply.py).

The hard invariant is byte-identity: for any txset, the staged-parallel
apply path must produce exactly the results, metas and ledger header of
the sequential loop (reference: the parallel apply phases of Lokhava et
al., SOSP 2019 §6, keep apply-order semantics). Every differential test
here runs the same deterministic workload through a sequential manager
(apply_parallel=0) and a parallel one and compares close meta bytes and
header hashes per close — including the all-conflicting case where the
engine must fully serialize, and mixed sets with imprecise-footprint
barrier txs (offers, change_trust, merges).
"""

import random
import threading

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
from stellar_core_tpu.ledger.parallel_apply import (ApplyWorkerPool,
                                                    partition_stages)
from stellar_core_tpu.tx.footprint import TxFootprint, extract_footprint
from stellar_core_tpu.xdr.ledger_entries import LedgerKey, Price
from stellar_core_tpu.xdr.transaction import MuxedAccount

from test_ledger_close import (close_with, make_manager, make_tx,
                               master_key, master_seq, xpk)
from txtest_utils import (make_asset, native, op_account_merge,
                          op_bump_sequence, op_change_trust,
                          op_create_account, op_manage_data,
                          op_manage_sell_offer,
                          op_path_payment_strict_receive, op_payment,
                          op_set_options)


def keyed(tag):
    return SecretKey.from_seed(sha256(b"parallel apply " + tag))


def muxed(sk):
    return MuxedAccount.from_ed25519(sk.public_key().raw)


def acct_seq(lm, sk):
    with LedgerTxn(lm.root) as ltx:
        le = ltx.load(LedgerKey.account(xpk(sk)))
        seq = le.data.value.seqNum
        ltx.rollback()
    return seq


# ----------------------------------------------------------- partition --

def fp(*keys, precise=True):
    return TxFootprint(set(keys), precise)


def test_partition_disjoint_is_one_stage():
    fps = [fp(b"a"), fp(b"b"), fp(b"c"), fp(b"d")]
    assert partition_stages(fps) == [[0, 1, 2, 3]]


def test_partition_conflict_chain_serializes():
    fps = [fp(b"a", b"b"), fp(b"b", b"c"), fp(b"c", b"d")]
    assert partition_stages(fps) == [[0], [1], [2]]


def test_partition_independent_pairs_stack():
    # 0↔2 share a, 1↔3 share b: two components, two stages of width 2
    fps = [fp(b"a"), fp(b"b"), fp(b"a"), fp(b"b")]
    assert partition_stages(fps) == [[0, 1], [2, 3]]


def test_partition_imprecise_tx_is_barrier():
    # the imprecise tx at index 2 flushes [0,1] first, runs alone, and
    # starts a fresh segment — even though it shares no keys with anyone
    fps = [fp(b"a"), fp(b"b"), fp(b"z", precise=False), fp(b"c"), fp(b"d")]
    assert partition_stages(fps) == [[0, 1], [2], [3, 4]]


def test_partition_all_conflicting_fully_serializes():
    fps = [fp(b"m", bytes([i])) for i in range(5)]
    assert partition_stages(fps) == [[0], [1], [2], [3], [4]]


def test_partition_preserves_apply_order_within_component():
    # conflicting txs stay in index order across stages
    fps = [fp(b"a"), fp(b"b"), fp(b"a"), fp(b"a"), fp(b"b")]
    stages = partition_stages(fps)
    pos = {}
    for d, stage in enumerate(stages):
        for i in stage:
            pos[i] = d
    assert pos[0] < pos[2] < pos[3]
    assert pos[1] < pos[4]
    for stage in stages:
        keys = [k for i in stage for k in fps[i].keys]
        assert len(keys) == len(set(keys))


def test_partition_empty():
    assert partition_stages([]) == []


# ---------------------------------------------------------- footprints --

def test_footprint_payment_is_precise():
    lm = make_manager(invariants=False)
    mk = master_key()
    dest = keyed(b"fp dest")
    tx = make_tx(lm, mk, master_seq(lm) + 1,
                 [op_payment(muxed(dest), 100)])
    f = extract_footprint(tx)
    assert f.precise
    assert LedgerKey.account(xpk(mk)).to_bytes() in f.keys
    assert LedgerKey.account(xpk(dest)).to_bytes() in f.keys


def test_footprint_credit_payment_names_trustlines():
    lm = make_manager(invariants=False)
    mk = master_key()
    issuer, dest = keyed(b"fp issuer"), keyed(b"fp tl dest")
    usd = make_asset(b"USD", xpk(issuer))
    tx = make_tx(lm, mk, master_seq(lm) + 1,
                 [op_payment(muxed(dest), 100, asset=usd)])
    f = extract_footprint(tx)
    assert f.precise
    # issuer account + both endpoints' trustlines are named
    assert LedgerKey.account(xpk(issuer)).to_bytes() in f.keys
    assert len(f.keys) >= 5


def test_footprint_orderbook_and_merge_are_imprecise():
    lm = make_manager(invariants=False)
    mk = master_key()
    other = keyed(b"fp other")
    usd = make_asset(b"USD", xpk(mk))
    offer = make_tx(lm, mk, master_seq(lm) + 1,
                    [op_manage_sell_offer(usd, native(), 10, Price(n=1, d=1))])
    assert not extract_footprint(offer).precise
    merge = make_tx(lm, mk, master_seq(lm) + 1,
                    [op_account_merge(muxed(other))])
    mf = extract_footprint(merge)
    assert not mf.precise
    # keys still collected for the prefetch even when imprecise
    assert LedgerKey.account(xpk(other)).to_bytes() in mf.keys


def test_footprint_manage_data_delete_is_imprecise():
    lm = make_manager(invariants=False)
    mk = master_key()
    put = make_tx(lm, mk, master_seq(lm) + 1,
                  [op_manage_data(b"k", b"v")])
    assert extract_footprint(put).precise
    rm = make_tx(lm, mk, master_seq(lm) + 1,
                 [op_manage_data(b"k", None)])
    assert not extract_footprint(rm).precise


# ---------------------------------------------------------------- pool --

def test_worker_pool_runs_jobs_and_reports_errors():
    pool = ApplyWorkerPool(3)
    hits, lock = [], threading.Lock()

    def job(i):
        def run():
            with lock:
                hits.append(i)
        return run

    pool.run([job(i) for i in range(20)])
    assert sorted(hits) == list(range(20))

    def boom():
        raise ValueError("stage bug")

    with pytest.raises(RuntimeError):
        pool.run([boom])
    # sticky error cleared: the pool stays usable
    pool.run([job(99)])
    assert 99 in hits


# -------------------------------------------------------- differential --

def run_differential(build_closes, workers=3, min_txs=2):
    """Run the same deterministic close script through a sequential and
    a parallel manager; assert byte-identical metas and headers."""
    lms, caps = [], []
    for parallel in (0, workers):
        lm = make_manager()
        lm.apply_parallel = parallel
        lm.apply_parallel_min_txs = min_txs
        cap = []
        lm.meta_stream = cap.append
        lm.defer_completion = False
        for txs in build_closes(lm):
            close_with(lm, txs)
        lms.append(lm)
        caps.append(cap)
    seq, par = lms
    assert seq.get_last_closed_ledger_hash() == \
        par.get_last_closed_ledger_hash()
    assert seq.get_last_closed_ledger_header().to_bytes() == \
        par.get_last_closed_ledger_header().to_bytes()
    assert len(caps[0]) == len(caps[1]) > 0
    for ms, mp in zip(caps[0], caps[1]):
        assert ms.to_bytes() == mp.to_bytes()
    return seq, par


def test_differential_all_conflicting_serializes_identically():
    """Chained same-source txs: every stage is width 1, and the result
    must still be byte-identical (full-serialization degenerate case)."""
    def build(lm):
        mk = master_key()
        seq = master_seq(lm)
        yield [make_tx(lm, mk, seq + 1 + i,
                       [op_create_account(xpk(keyed(b"conf %d" % i)),
                                          10 ** 9)])
               for i in range(8)]
    _, par = run_differential(build)
    assert par.last_apply_stages == 8
    assert max(par.last_stage_widths) == 1


def test_differential_disjoint_payments_run_wide():
    """Payments among disjoint account pairs form one wide stage and
    merge byte-identically, with zero audit fallbacks."""
    accts = [keyed(b"pair %d" % i) for i in range(8)]

    def build(lm):
        mk = master_key()
        seq = master_seq(lm)
        yield [make_tx(lm, mk, seq + 1 + i,
                       [op_create_account(xpk(a), 10 ** 9)])
               for i, a in enumerate(accts)]
        yield [make_tx(lm, accts[i], acct_seq(lm, accts[i]) + 1,
                       [op_payment(muxed(accts[i + 1]), 1000 + i)])
               for i in range(0, 8, 2)]
    _, par = run_differential(build)
    assert par.apply_fallbacks == 0
    assert max(par.last_stage_widths) == 4


def test_differential_mixed_precise_and_imprecise():
    """Offers, change_trust and merges (imprecise barriers) interleaved
    with precise payments/manage_data/set_options: barriers apply inline
    on the real ltx, the rest stages — all byte-identical."""
    accts = [keyed(b"mix %d" % i) for i in range(6)]
    issuer = accts[0]

    def build(lm):
        mk = master_key()
        seq = master_seq(lm)
        yield [make_tx(lm, mk, seq + 1 + i,
                       [op_create_account(xpk(a), 10 ** 9)])
               for i, a in enumerate(accts)]
        usd = make_asset(b"USD", xpk(issuer))
        yield [
            make_tx(lm, accts[1], acct_seq(lm, accts[1]) + 1,
                    [op_payment(muxed(accts[2]), 500)]),
            make_tx(lm, accts[3], acct_seq(lm, accts[3]) + 1,
                    [op_manage_data(b"note", b"staged")]),
            make_tx(lm, accts[4], acct_seq(lm, accts[4]) + 1,
                    [op_change_trust(usd, 10 ** 6)]),
            make_tx(lm, accts[5], acct_seq(lm, accts[5]) + 1,
                    [op_set_options(homeDomain=b"example.org")]),
            make_tx(lm, accts[2], acct_seq(lm, accts[2]) + 1,
                    [op_bump_sequence(0)]),
        ]
        yield [
            # order-book + merge barriers mixed among precise txs
            make_tx(lm, accts[4], acct_seq(lm, accts[4]) + 1,
                    [op_manage_sell_offer(native(), usd, 10,
                                          Price(n=1, d=1))]),
            make_tx(lm, accts[1], acct_seq(lm, accts[1]) + 1,
                    [op_payment(muxed(accts[2]), 700)]),
            make_tx(lm, accts[3], acct_seq(lm, accts[3]) + 1,
                    [op_manage_data(b"note2", b"merged")]),
            # path payment (order-book walker, imprecise barrier);
            # native→native with an empty path degenerates to a send
            make_tx(lm, accts[2], acct_seq(lm, accts[2]) + 1,
                    [op_path_payment_strict_receive(
                        native(), 900, muxed(accts[1]), native(), 900)]),
            make_tx(lm, accts[5], acct_seq(lm, accts[5]) + 1,
                    [op_account_merge(muxed(accts[2]))]),
        ]
    run_differential(build)


def test_differential_randomized_workload():
    """Seeded random mix over a small hot-biased account set, three
    closes deep: whatever the partitioner decides, metas and headers
    must match the sequential loop byte for byte."""
    accts = [keyed(b"rand %d" % i) for i in range(6)]

    def build(lm):
        mk = master_key()
        seq = master_seq(lm)
        yield [make_tx(lm, mk, seq + 1 + i,
                       [op_create_account(xpk(a), 10 ** 9)])
               for i, a in enumerate(accts)]
        rng = random.Random(0xC0FFEE)
        seqs = {i: None for i in range(len(accts))}
        for _ in range(3):
            for i in range(len(accts)):
                seqs[i] = acct_seq(lm, accts[i])
            txs = []
            for _ in range(10):
                # hot bias: half the traffic originates from account 0
                si = 0 if rng.random() < 0.5 else \
                    rng.randrange(len(accts))
                di = rng.randrange(len(accts))
                while di == si:
                    di = rng.randrange(len(accts))
                seqs[si] += 1
                roll = rng.random()
                if roll < 0.6:
                    ops = [op_payment(muxed(accts[di]),
                                      100 + rng.randrange(900))]
                elif roll < 0.8:
                    ops = [op_manage_data(b"k%d" % rng.randrange(3),
                                          b"v%d" % rng.randrange(100))]
                else:
                    ops = [op_set_options()]
                txs.append(make_tx(lm, accts[si], seqs[si], ops))
            yield txs
    run_differential(build)


# ------------------------------------------------ app-level + threads --

def test_app_differential_with_soroban_and_zipf():
    """Full-application differential: the same seeded load (payments,
    Soroban uploads, Zipfian-hot payments) against APPLY_PARALLEL=0 and
    =4 must externalize identical ledger hashes every close."""
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock

    def drive(parallel):
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        # pin the instance: loadgen account keys derive from PEER_PORT,
        # so both runs must see identical ports to build identical txs
        cfg = get_test_config(instance=94)
        cfg.APPLY_PARALLEL = parallel
        cfg.APPLY_PARALLEL_MIN_TXS = 2
        hashes = []
        with Application.create(clock, cfg) as app:
            app.start()
            lg = LoadGenerator(app, seed=42)
            assert lg.generate_accounts(8) == 8
            app.manual_close()
            lg.sync_account_seqs()
            hashes.append(app.ledger_manager.get_last_closed_ledger_hash())

            assert lg.generate_payments(10) == 10
            app.manual_close()
            lg.sync_account_seqs()
            hashes.append(app.ledger_manager.get_last_closed_ledger_hash())

            assert lg.generate_soroban_uploads(3) == 3
            app.manual_close()
            lg.sync_account_seqs()
            hashes.append(app.ledger_manager.get_last_closed_ledger_hash())

            assert lg.generate_payments_zipf(10) == 10
            app.manual_close()
            hashes.append(app.ledger_manager.get_last_closed_ledger_hash())
            assert lg.failed == 0
            widths = list(app.ledger_manager.last_stage_widths)
        return hashes, widths

    seq_hashes, _ = drive(0)
    par_hashes, _ = drive(4)
    assert seq_hashes == par_hashes


def test_zipf_loadgen_is_seed_deterministic_and_hot():
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock

    def sources(seed):
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        # same instance → same ports → same derived account keys; only
        # the explicit loadgen seed may change the traffic shape
        with Application.create(clock, get_test_config(instance=93)) as app:
            app.start()
            lg = LoadGenerator(app, seed=seed)
            assert lg.generate_accounts(6) == 6
            app.manual_close()
            lg.sync_account_seqs()
            assert lg.generate_payments_zipf(12) == 12
            txs = app.herder.tx_queue.get_transactions()
            return sorted(tx.full_hash() for tx in txs)

    a, b, c = sources(7), sources(7), sources(8)
    assert a == b          # same seed, same traffic
    assert a != c          # different seed diverges


def test_sim_pair_with_thread_checks_and_parallel_apply():
    """Tier-1 leg: a two-node sim cranked to consensus with runtime
    thread-domain checking ON while the staged apply engine runs (test
    configs default APPLY_PARALLEL=4). Apply workers must bind
    `apply-worker` and trip zero crank-domain assertions."""
    from stellar_core_tpu.simulation import topologies
    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    from stellar_core_tpu.util import threads

    threads.enable(raise_on_violation=False)
    try:
        sim = topologies.pair()
        for app in sim.apps():
            app.ledger_manager.apply_parallel_min_txs = 2
        try:
            sim.start_all_nodes()
            assert sim.crank_until(lambda: sim.have_all_externalized(2))
            app0 = sim.apps()[0]
            lg = LoadGenerator(app0)
            assert lg.generate_accounts(6) == 6
            target = app0.ledger_manager.get_last_closed_ledger_num() + 2
            assert sim.crank_until(
                lambda: sim.have_all_externalized(target))
            lg.sync_account_seqs()
            # disjoint account pairs: generate_payments' ring shape is
            # one conflict chain, these three stage at width 3 and
            # really dispatch apply workers under the checker
            from stellar_core_tpu.herder import AddResult
            for s, d in ((0, 1), (2, 3), (4, 5)):
                res = lg._sign_and_submit(
                    lg.accounts[s], [lg._payment_op(lg.accounts[d], 1000)])
                assert res == AddResult.ADD_STATUS_PENDING
            target = app0.ledger_manager.get_last_closed_ledger_num() + 2
            assert sim.crank_until(
                lambda: sim.have_all_externalized(target))
            for app in sim.apps():
                app.ledger_manager.join_completion()
            seq = min(a.ledger_manager.get_last_closed_ledger_num()
                      for a in sim.apps())
            assert sim.ledger_hashes_agree(seq)
            assert lg.failed == 0
            # the payment close really went through the staged engine
            # (later closes may be empty, so check the width histogram,
            # not just the last close's shape)
            assert any(app.ledger_manager.apply_stage_width_hist is not None
                       and app.ledger_manager.apply_stage_width_hist._max >= 3
                       for app in sim.apps())
            assert all(app.ledger_manager.apply_fallbacks == 0
                       for app in sim.apps())
        finally:
            sim.stop_all_nodes()
        assert threads.violations() == []
    finally:
        threads.disable()
        threads.bind("crank")
