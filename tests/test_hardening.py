"""Hardening-layer tests: maintainer + cursors, meta stream, self-check,
quorum intersection (reference: MaintainerTests, ExternalQueue usage,
QuorumIntersectionTests core cases)."""

import hashlib
import io

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.herder.quorum_intersection import \
    QuorumIntersectionChecker
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.util.xdr_stream import read_record
from stellar_core_tpu.xdr.ledger import LedgerCloseMeta
from stellar_core_tpu.xdr.scp import SCPQuorumSet
from stellar_core_tpu.xdr.types import PublicKey

import test_standalone_app as m1
from txtest_utils import op_create_account


def node(i):
    return hashlib.sha256(b"qic-%d" % i).digest()


def qset(nodes, threshold):
    return SCPQuorumSet(threshold=threshold,
                        validators=[PublicKey.ed25519(n) for n in nodes],
                        innerSets=[])


class TestQuorumIntersection:
    def test_healthy_majority_network(self):
        ids = [node(i) for i in range(4)]
        qmap = {n: qset(ids, 3) for n in ids}
        assert QuorumIntersectionChecker(
            qmap).network_enjoys_quorum_intersection()

    def test_split_network_detected(self):
        a = [node(i) for i in range(3)]
        b = [node(i) for i in range(10, 13)]
        qmap = {}
        for n in a:
            qmap[n] = qset(a, 2)
        for n in b:
            qmap[n] = qset(b, 2)
        checker = QuorumIntersectionChecker(qmap)
        assert not checker.network_enjoys_quorum_intersection()
        q1, q2 = checker.potential_split
        assert not (q1 & q2)

    def test_fifty_percent_threshold_splits(self):
        """threshold n/2 allows two disjoint halves."""
        ids = [node(i) for i in range(4)]
        qmap = {n: qset(ids, 2) for n in ids}
        assert not QuorumIntersectionChecker(
            qmap).network_enjoys_quorum_intersection()


class TestMaintainerAndCursors:
    def test_cursors_and_maintenance(self):
        cfg = get_test_config()
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        with Application.create(clock, cfg) as app:
            app.start()
            for _ in range(20):
                app.manual_close()
            h = app.command_handler.handle
            assert h("setcursor", {"id": "HORIZON", "cursor": "5"}) == \
                {"status": "ok"}
            assert h("getcursor", {"id": "HORIZON"})["cursors"] == \
                {"HORIZON": 5}
            # too few ledgers: the checkpoint-safety floor forbids GC
            before = app.database.query_one(
                "SELECT COUNT(*) FROM txsethistory")[0]
            out = h("maintenance", {"count": "1000"})
            assert out["status"] == "ok" and out["deleted"] == 0
            assert app.database.query_one(
                "SELECT COUNT(*) FROM txsethistory")[0] == before

            # past two checkpoints the floor moves: rows below
            # min(cursor, lcl - 128) become deletable
            for _ in range(140):
                app.manual_close()
            out = h("maintenance", {"count": "1000"})
            assert out["deleted"] > 0
            rows = app.database.query_all(
                "SELECT ledgerseq FROM txsethistory ORDER BY ledgerseq")
            assert all(seq >= 5 for (seq,) in rows)
            assert h("dropcursor", {"id": "HORIZON"}) == {"status": "ok"}
            assert h("getcursor", {})["cursors"] == {}


class TestMetaStream:
    def test_meta_written_per_ledger(self, tmp_path):
        meta_path = str(tmp_path / "meta.xdr")
        cfg = get_test_config()
        cfg.METADATA_OUTPUT_STREAM = meta_path
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        with Application.create(clock, cfg) as app:
            app.start()
            master = m1.master_account(app)
            dest = m1.AppAccount(app, SecretKey.from_seed(b"\x61" * 32))
            m1.submit(app, master.tx(
                [op_create_account(dest.account_id, 10**11)]))
            app.manual_close()
            app.manual_close()
        metas = []
        with open(meta_path, "rb") as f:
            while True:
                rec = read_record(f)
                if rec is None:
                    break
                metas.append(LedgerCloseMeta.from_bytes(rec))
        assert len(metas) == 2
        # protocol 21 → generalized sets → v1 meta with the tx inside
        assert metas[0].disc == 1
        v1 = metas[0].value
        assert v1.ledgerHeader.header.ledgerSeq == 2
        n_txs = sum(len(c.value.txs)
                    for phase in v1.txSet.value.phases
                    for c in phase.value)
        assert n_txs == 1
        assert len(v1.txProcessing) == 1


class TestSelfCheck:
    def test_self_check_passes_on_healthy_node(self):
        cfg = get_test_config()
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        with Application.create(clock, cfg) as app:
            app.start()
            for _ in range(3):
                app.manual_close()
            out = app.command_handler.handle("self-check")
            assert out["status"] == "ok", out
            rep = out["report"]
            assert rep["header_chain_ok"]
            assert rep["bucket_list_consistent"]
            assert rep["verify_per_second_cpu"] > 0

    def test_self_check_detects_corruption(self):
        cfg = get_test_config()
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        with Application.create(clock, cfg) as app:
            app.start()
            app.manual_close()
            # corrupt a stored header
            app.database.execute(
                "UPDATE ledgerheaders SET ledgerhash=? WHERE ledgerseq=1",
                (b"\x00" * 32,))
            out = app.command_handler.handle("self-check")
            assert out["status"] == "failed"


class TestSurvey:
    def test_three_node_survey_relay(self):
        """Surveyor asks a non-adjacent node through a relay; response
        comes back encrypted (reference: SurveyManager relay tests)."""
        from test_overlay import make_apps, shutdown
        from stellar_core_tpu.overlay import LoopbackPeerConnection
        from stellar_core_tpu.crypto.strkey import StrKey
        clock, apps = make_apps(3, threshold=2)
        try:
            # chain: 0 - 1 - 2 (no direct 0-2 link)
            c01 = LoopbackPeerConnection(apps[0], apps[1])
            c12 = LoopbackPeerConnection(apps[1], apps[2])
            for _ in range(4):
                c01.crank()
                c12.crank()
            target = StrKey.encode_ed25519_public(apps[2].config.node_id())
            out = apps[0].command_handler.handle(
                "surveytopology", {"node": target})
            assert out["status"] == "ok"
            for _ in range(6):
                c01.crank()
                c12.crank()
            res = apps[0].command_handler.handle("getsurveyresult")
            topo = res["topology"]
            assert target in topo
            # node 2 reports exactly one peer (node 1)
            t = topo[target]
            assert t["totalInbound"] + t["totalOutbound"] == 1
        finally:
            shutdown(apps)


class TestFeeBumpEndToEnd:
    def test_fee_bump_through_node(self):
        """Fee-bump envelope paid by another account applies through the
        full node pipeline (reference: FeeBumpTransactionFrame)."""
        from stellar_core_tpu.xdr.transaction import (
            FeeBumpTransaction, FeeBumpTransactionEnvelope, MuxedAccount,
            TransactionEnvelope, _FeeBumpInnerTx, _TxExt,
            DecoratedSignature)
        from stellar_core_tpu.xdr.types import EnvelopeType
        from stellar_core_tpu.tx.frame import make_frame

        cfg = get_test_config()
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        with Application.create(clock, cfg) as app:
            app.start()
            master = m1.master_account(app)
            payer = m1.AppAccount(app, SecretKey.from_seed(b"\x71" * 32))
            dest = m1.AppAccount(app, SecretKey.from_seed(b"\x72" * 32))
            m1.submit(app, master.tx(
                [op_create_account(payer.account_id, 10**11)]))
            app.manual_close()
            payer.sync_seq()

            # inner tx: master creates dest, but PAYER pays the fee
            inner = master.tx([op_create_account(dest.account_id, 10**10)])
            fb = FeeBumpTransaction(
                feeSource=payer.muxed, fee=400,
                innerTx=_FeeBumpInnerTx(
                    EnvelopeType.ENVELOPE_TYPE_TX, inner.envelope.value),
                ext=_TxExt(0))
            env = FeeBumpTransactionEnvelope(tx=fb, signatures=[])
            outer = TransactionEnvelope(
                EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP, env)
            frame = make_frame(outer, app.config.network_id())
            sig = payer.key.sign(frame.contents_hash())
            env.signatures = [DecoratedSignature(
                hint=payer.key.public_key().hint(), signature=sig)]
            frame.signatures = env.signatures

            payer_before = m1.app_account_entry(
                app, payer.account_id).balance
            r = m1.submit(app, frame)
            assert r["status"] == "PENDING", r
            app.manual_close()
            assert m1.app_account_entry(app, dest.account_id) is not None
            payer_after = m1.app_account_entry(
                app, payer.account_id).balance
            assert payer_before - payer_after == 400  # payer paid


def test_automatic_self_check_period():
    """AUTOMATIC_SELF_CHECK_PERIOD arms a recurring self-check timer
    (reference: ApplicationImpl.cpp:823-826)."""
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock

    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    cfg = get_test_config()
    cfg.AUTOMATIC_SELF_CHECK_PERIOD = 5.0
    with Application.create(clock, cfg) as app:
        app.start()
        assert getattr(app, "_self_check_timer", None) is not None
        ran = []
        from stellar_core_tpu.main import self_check as sc_mod
        orig = sc_mod.self_check
        sc_mod.self_check = lambda a, **k: (ran.append(1), orig(a, **k))[1]
        try:
            clock.crank_for(16.0)
        finally:
            sc_mod.self_check = orig
        # the first firing captured the unpatched function; at least one
        # later (re-armed) firing is observed and the timer stays armed
        assert len(ran) >= 1
        assert app._self_check_timer is not None
